"""Ring attention — sequence-parallel attention over the device mesh.

The long-context path of the framework: the sequence axis is sharded
across devices, K/V blocks rotate around the ring via ``ppermute``
while each device accumulates attention for its resident Q block with
an online (flash-style) softmax — peak memory stays O(S/n) per device
and all communication is neighbor-hop ICI traffic.

THREE SCHEDULES, one merge contract (``variant=``):

- ``"serial"`` — attend the resident block, THEN move K/V. Every hop's
  ICI time sits on the critical path between block attends. Kept as the
  measured baseline the ``ring-overlap-efficiency`` probe metric
  compares against; numerically it is the bitwise reference for the
  overlapped schedule.
- ``"overlap"`` (default) — double-buffered: the next-hop ``ppermute``
  is issued BEFORE the resident block's attend (two-slot carry, a
  ``lax.optimization_barrier`` pins the transfer ahead of the compute
  in the schedule), so per-step ICI time hides under attention math.
  Same blocks merged in the same order as serial ⇒ bit-identical
  output, lse, and gradients.
- ``"bidir"`` — K/V split into sequence halves permuted clockwise /
  counter-clockwise simultaneously, driving BOTH directions of each
  ICI link per hop (half the per-hop wire time on full-duplex links,
  the NCCL bidirectional-ring trick). Step 0 attends the full local
  (diagonal) block while the first hops are in flight; later steps
  merge one half per direction. Merge ORDER differs from serial, so
  agreement is numerical (same online-softmax state), not bitwise.

Every schedule performs exactly n−1 K/V hops per direction: the old
"send the blocks home" final rotation was a full-payload ppermute per
call doing nothing (the homeward K/V are discarded), and is gone. The
backward's dK/dV accumulators still make n hops — their last hop
carries real gradients home.

TRAINING-GRADE: the op carries a ``jax.custom_vjp``. The forward scan
also produces the GLOBAL logsumexp per query row; the backward runs a
second ring pass that rotates K/V again (same variant schedule) and
recomputes each block's probabilities as ``p = exp(s − lse_global)`` —
exact global attention probabilities, so per-block dK/dV contributions
sum exactly. The dK/dV accumulators rotate WITH their K/V blocks,
keeping backward memory O(S/n) per device too — the sequence-parallel
axis can appear in a differentiated train step
(build_sharded_train_step(attention="ring")).

Used by the ``ring-attention`` probe both as a correctness check
(sequence-parallel result must match single-device attention) and as a
sequence-parallelism bandwidth/throughput canary — the probe times the
serial schedule against the overlapped one and exports the ratio as
``ring-overlap-efficiency`` plus the sustained fraction of rated ICI
ring bandwidth.

Shapes inside ``shard_map`` (per device): q, k, v are
``[batch, seq_local, heads, head_dim]``; the global sequence is
``seq_local × n_devices`` with device i owning the i-th contiguous
block. Causality is enforced blockwise: a KV block strictly after the
Q block is skipped entirely, the diagonal block gets the triangular
mask, earlier blocks attend fully.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.parallel.partition import (
    match_partition_rules,
    shard_map,
)

_NEG_INF = -1e30

VARIANTS = ("serial", "overlap", "bidir")


def ring_partition_rules(
    axis: str = "sp", batch_axis=None, heads_axis=None
):
    """Default partition rules for the ring's q/k/v pytree: the
    sequence dim (position 1) rides the ring axis; batch and heads are
    embarrassingly parallel and take whatever axes the composed mesh
    offers. The layout is DATA — a composed dp×tp×sp step re-meshes by
    passing different axes here (or its own rules), never by editing
    the schedule code below."""
    return (("^(q|k|v)$", P(batch_axis, axis, heads_axis, None)),)

# Test hook: when set to a list, every ring hop TRACED appends
# (tag, direction). With ``unroll=True`` (python-loop schedule, same
# body) each hop traces individually, so the log length IS the hop
# count — tests assert the n−1-hop contract without HLO spelunking.
_HOP_LOG = None


def _hop(x, axis_name, perm, tag, direction="cw"):
    """One ring hop (neighbor ppermute), routed through a single site so
    the traced-hop counter sees every transfer a schedule issues."""
    if _HOP_LOG is not None:
        _HOP_LOG.append((tag, direction))
    return jax.lax.ppermute(x, axis_name, perm)


def _run_steps(body, carry, n_steps, unroll, start=0):
    """Drive ``body(carry, step)`` for steps start..start+n_steps−1.

    Default is ``lax.scan`` — one traced step regardless of ring size,
    so compile time and HLO size stay flat as slices grow.
    ``unroll=True`` runs the SAME body in a python loop: numerics are
    identical, but each hop traces individually for ``_HOP_LOG``."""
    if n_steps <= 0:
        return carry
    if unroll:
        for step in range(start, start + n_steps):
            carry, _ = body(carry, step)
        return carry
    carry, _ = jax.lax.scan(
        body, carry, jnp.arange(start, start + n_steps)
    )
    return carry


def _block_attend(q, k, v, mask):
    """Scores for one (Q-block, KV-block) pair.

    Returns (scores_max, exp_scores @ v, exp_scores row sums) for the
    online-softmax accumulation. q: [B,Sq,H,D]; k,v: [B,Sk,Hkv,D] with
    Hkv dividing H (GQA: each group of H//Hkv query heads shares a K/V
    head); mask: [Sq,Sk] bool (True = attend) or None. The merge state
    comes back q-head-indexed ([B,H,Sq]) regardless of grouping.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    batch, seq_q, heads, head_dim = q.shape
    heads_kv = k.shape[2]
    group = heads // heads_kv
    # upcast K/V here, not before the ring rotation: ppermute moves the
    # input-dtype blocks, so bf16 inputs cost bf16 (not f32) ICI traffic
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    qg = q.reshape(batch, seq_q, heads_kv, group, head_dim)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, None, :, :], scores, _NEG_INF)
    block_max = jnp.max(scores, axis=-1)  # [B,Hkv,G,Sq]
    exp = jnp.exp(scores - block_max[..., None])
    if mask is not None:
        # rows with no visible keys: exp(NEG_INF - NEG_INF) = 1 — zero them
        any_visible = jnp.any(mask, axis=-1)  # [Sq]
        exp = exp * any_visible[None, None, None, :, None]
    out = jnp.einsum("bhgqk,bkhd->bqhgd", exp, v).reshape(
        batch, seq_q, heads, head_dim
    )
    denom = jnp.sum(exp, axis=-1)  # [B,Hkv,G,Sq]
    return (
        block_max.reshape(batch, heads, seq_q),
        out,
        denom.reshape(batch, heads, seq_q),
    )


def _flash_half_ok(use_flash: bool, seq_q: int, half_len: int) -> bool:
    """The fused partial kernel tiles 8-aligned sequences only; a half
    K/V block that doesn't tile falls back to the einsum block compute
    (same merge contract, so the mixture is invisible to the merge)."""
    return (
        use_flash
        and seq_q % 8 == 0
        and half_len % 8 == 0
        and half_len > 0
    )


def _ring_attention_sharded(
    q, k, v, *, axis_name: str, n_devices: int, causal: bool,
    use_flash: bool, variant: str = "overlap", unroll: bool = False,
):
    """Body run per device inside shard_map; returns ``(out, lse)``
    where ``lse`` is the GLOBAL logsumexp per query row (the backward
    pass's residual). See the module docstring for the three schedule
    variants. With ``use_flash`` the per-step block compute runs the
    fused Pallas kernel (ops/flash_attention.py partial mode) instead
    of XLA einsums — same (max, unnormalized out, denom) merge
    contract, but the local score matrix stays in VMEM."""
    my_idx = jax.lax.axis_index(axis_name)
    batch, seq_local, heads, head_dim = q.shape

    causal_mask = jnp.tril(jnp.ones((seq_local, seq_local), jnp.bool_))
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    if use_flash:
        from activemonitor_tpu.ops.flash_attention import flash_attention_partial

    qf = q.astype(jnp.float32)

    def skip(_q_in, _kf, _vf):
        # one skip state for every branch construct below: a
        # (NEG_INF max, zero acc, zero denom) triple the merge
        # treats as an empty block (operands arrive because every
        # lax.cond branch shares the signature)
        return (
            jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),
            jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),
            jnp.zeros((batch, heads, seq_local), jnp.float32),
        )

    def attend_block(kv_idx, kf, vf):
        """(max, unnormalized out, denom) for the full K/V block owned
        by ring position ``kv_idx``, with the causal skip/diag/full
        selection."""
        if use_flash:
            # fused path: diagonal block runs the causal kernel, earlier
            # blocks the unmasked one — two pallas variants under
            # lax.switch so each step's compute stays in VMEM. The
            # kernel upcasts internally, so it gets the ORIGINAL-dtype q
            # (bf16 inputs keep bf16 Q-block HBM traffic; the f32 qf
            # exists for the XLA einsum path)
            def attend_full(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=False)

            def attend_diag(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=True)

            if causal:
                branch = (
                    (kv_idx < my_idx).astype(jnp.int32)
                    + 2 * (kv_idx == my_idx).astype(jnp.int32)
                )  # 0 = skip (kv after us), 1 = full, 2 = diagonal
                return jax.lax.switch(
                    branch, (skip, attend_full, attend_diag), q, kf, vf
                )
            return attend_full(q, kf, vf)
        if causal:
            # kv block strictly after our q block ⇒ nothing to attend:
            # skip the einsums entirely (lax.cond, so the dead ~half of
            # the causal grid costs nothing at runtime); diagonal block
            # gets the triangular mask, earlier blocks attend fully
            def attend(qf, kf, vf):
                mask = jnp.where(
                    kv_idx == my_idx, causal_mask, jnp.ones_like(causal_mask)
                )
                return _block_attend(qf, kf, vf, mask)

            return jax.lax.cond(kv_idx > my_idx, skip, attend, qf, kf, vf)
        return _block_attend(qf, kf, vf, None)

    def merge(stats, block):
        """Online-softmax merge — the one contract every schedule and
        both block-compute paths share."""
        acc, denom, running_max = stats
        block_max, block_out, block_denom = block
        new_max = jnp.maximum(running_max, block_max)
        old_scale = jnp.exp(running_max - new_max)
        blk_scale = jnp.exp(block_max - new_max)
        acc = acc * old_scale.transpose(0, 2, 1)[..., None] + block_out * (
            blk_scale.transpose(0, 2, 1)[..., None]
        )
        denom = denom * old_scale + block_denom * blk_scale
        return acc, denom, new_max

    stats0 = (
        jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),  # acc
        jnp.zeros((batch, heads, seq_local), jnp.float32),  # denom
        jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),  # max
    )

    if variant == "bidir":
        def attend_diag_full(kf, vf):
            if use_flash:
                return flash_attention_partial(q, kf, vf, causal=causal)
            return _block_attend(qf, kf, vf, causal_mask if causal else None)

        def attend_half(kv_idx, kh, vh):
            """Full-or-skip attend for a half K/V block from ring
            position ``kv_idx`` — halves only ride for steps ≥ 1, so
            the diagonal never lands here and no mask is needed."""
            def attend(q_in, kh, vh):
                if _flash_half_ok(use_flash, seq_local, kh.shape[1]):
                    return flash_attention_partial(q_in, kh, vh, causal=False)
                return _block_attend(q_in.astype(jnp.float32), kh, vh, None)

            if causal:
                return jax.lax.cond(kv_idx > my_idx, skip, attend, q, kh, vh)
            return attend(q, kh, vh)

        if n_devices == 1:
            stats = merge(stats0, attend_diag_full(k, v))
        else:
            half = seq_local // 2
            perm_ccw = [(i, (i - 1) % n_devices) for i in range(n_devices)]
            k_cw, k_ccw = k[:, :half], k[:, half:]
            v_cw, v_ccw = v[:, :half], v[:, half:]
            # the first hop of each direction rides under the diagonal
            # attend — both ICI link directions are busy from step 0
            k_cw = _hop(k_cw, axis_name, perm, "k", "cw")
            v_cw = _hop(v_cw, axis_name, perm, "v", "cw")
            k_ccw = _hop(k_ccw, axis_name, perm_ccw, "k", "ccw")
            v_ccw = _hop(v_ccw, axis_name, perm_ccw, "v", "ccw")
            (k_cw, v_cw, k_ccw, v_ccw), (kd, vd) = jax.lax.optimization_barrier(
                ((k_cw, v_cw, k_ccw, v_ccw), (k, v))
            )
            stats = merge(stats0, attend_diag_full(kd, vd))

            def step_fn(carry, t):
                k_cw, v_cw, k_ccw, v_ccw, stats = carry
                kn_cw = _hop(k_cw, axis_name, perm, "k", "cw")
                vn_cw = _hop(v_cw, axis_name, perm, "v", "cw")
                kn_ccw = _hop(k_ccw, axis_name, perm_ccw, "k", "ccw")
                vn_ccw = _hop(v_ccw, axis_name, perm_ccw, "v", "ccw")
                (kn_cw, vn_cw, kn_ccw, vn_ccw), (k_cw, v_cw, k_ccw, v_ccw) = (
                    jax.lax.optimization_barrier(
                        ((kn_cw, vn_cw, kn_ccw, vn_ccw),
                         (k_cw, v_cw, k_ccw, v_ccw))
                    )
                )
                stats = merge(
                    stats, attend_half((my_idx - t) % n_devices, k_cw, v_cw)
                )
                stats = merge(
                    stats, attend_half((my_idx + t) % n_devices, k_ccw, v_ccw)
                )
                return (kn_cw, vn_cw, kn_ccw, vn_ccw, stats), None

            # steps 1..n−2 prefetch inside the loop; the last pair of
            # halves attends in place — n−1 hops per direction, no
            # homeward rotation
            carry = _run_steps(
                step_fn, (k_cw, v_cw, k_ccw, v_ccw, stats),
                n_devices - 2, unroll, start=1,
            )
            k_cw, v_cw, k_ccw, v_ccw, stats = carry
            t_last = n_devices - 1
            stats = merge(
                stats, attend_half((my_idx - t_last) % n_devices, k_cw, v_cw)
            )
            stats = merge(
                stats, attend_half((my_idx + t_last) % n_devices, k_ccw, v_ccw)
            )
    else:
        def step_fn(carry, step):
            kf, vf, stats = carry
            kv_idx = (my_idx - step) % n_devices  # owner of the resident block
            if variant == "overlap":
                # double-buffered: issue the next-hop transfer BEFORE
                # the block attend — the ppermute rides the ICI links
                # while the MXU works; the barrier pins the collective
                # ahead of the compute it should hide under
                k_next = _hop(kf, axis_name, perm, "k")
                v_next = _hop(vf, axis_name, perm, "v")
                (k_next, v_next), (kf, vf) = jax.lax.optimization_barrier(
                    ((k_next, v_next), (kf, vf))
                )
                stats = merge(stats, attend_block(kv_idx, kf, vf))
            else:  # serial: attend, THEN move — the measured baseline
                stats = merge(stats, attend_block(kv_idx, kf, vf))
                k_next = _hop(kf, axis_name, perm, "k")
                v_next = _hop(vf, axis_name, perm, "v")
            return (k_next, v_next, stats), None

        # n−1 real hops: the final block attends in place (K/V rotate in
        # input dtype — bf16 inputs keep bf16 ICI traffic)
        kf, vf, stats = _run_steps(
            step_fn, (k, v, stats0), n_devices - 1, unroll
        )
        stats = merge(
            stats, attend_block((my_idx - (n_devices - 1)) % n_devices, kf, vf)
        )

    acc, denom, running_max = stats
    out = acc / jnp.maximum(denom.transpose(0, 2, 1)[..., None], 1e-30)
    # global logsumexp per query row — the backward pass reconstructs
    # exact global probabilities from this (p = exp(s - lse)); clamped
    # like the flash kernel so fully-masked rows stay finite
    lse = jnp.maximum(running_max, _NEG_INF / 2) + jnp.log(
        jnp.maximum(denom, 1e-30)
    )  # [B, H, Sq] float32
    return out.astype(q.dtype), lse


def _ring_attention_bwd_sharded(
    q, k, v, out, lse, dout, *, axis_name: str, n_devices: int,
    causal: bool, use_flash: bool, variant: str = "overlap",
    unroll: bool = False,
):
    """Second ring pass: dQ/dK/dV per device.

    K/V rotate around the ring with the same schedule as the forward —
    n−1 hops per direction, the overlapped variant prefetching the next
    block under the current block's gradient math. The float32 dK/dV
    accumulators rotate IN LOCKSTEP with their blocks, so the
    accumulator for block j is always resident with block j itself;
    each device adds its Q-block's contribution to whatever block is
    visiting. Accumulators make n hops: n−1 alongside their blocks plus
    ONE homeward hop after the final (in-place) block — that last hop
    carries real gradients, unlike the discarded homeward K/V rotation
    this layer removed. dQ accumulates locally. With ``use_flash`` the
    per-block gradient math runs the fused backward kernels against the
    global statistics (flash_attention_backward_block); otherwise XLA
    einsums recompute s and p = exp(s − lse_global) directly. The
    bidirectional variant's half-blocks always use the einsum path (the
    fused backward kernel wants square blocks); its full diagonal block
    still honors ``use_flash``."""
    my_idx = jax.lax.axis_index(axis_name)
    batch, seq_local, heads, head_dim = q.shape
    heads_kv = k.shape[2]
    group = heads // heads_kv  # GQA: grouped heads share a K/V head
    scale = 1.0 / (head_dim ** 0.5)
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    causal_mask = jnp.tril(jnp.ones((seq_local, seq_local), jnp.bool_))

    qf = q.astype(jnp.float32)
    dof = dout.astype(jnp.float32)
    # per-row correction Δ = rowsum(dO ∘ O), same as the single-chip
    # backward kernels (ops/flash_attention.py _backward_bhsd)
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, out.astype(jnp.float32))

    # grouped views: head index h = hkv*group + g, matching the
    # forward's reshape; dK/dV einsums sum over the group axis
    qg = qf.reshape(batch, seq_local, heads_kv, group, head_dim)
    dog = dof.reshape(batch, seq_local, heads_kv, group, head_dim)
    lse_g = lse.reshape(batch, heads_kv, group, seq_local)
    delta_g = delta.reshape(batch, heads_kv, group, seq_local)

    def _einsum_grads(kf, vf, mask):
        """Per-block (dq, dk, dv) contributions against the GLOBAL row
        statistics; ``kf``/``vf`` may be a half block (any Sk)."""
        kff = kf.astype(jnp.float32)
        vff = vf.astype(jnp.float32)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kff) * scale
        if mask is not None:
            s = jnp.where(mask[None, None, None], s, _NEG_INF)
        p = jnp.exp(s - lse_g[..., None])  # exact global probabilities
        dv_blk = jnp.einsum("bhgqk,bqhgd->bkhd", p, dog)
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", dog, vff)
        ds = p * (dp - delta_g[..., None]) * scale
        dq_blk = jnp.einsum("bhgqk,bkhd->bqhgd", ds, kff).reshape(
            batch, seq_local, heads, head_dim
        )
        dk_blk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qg)
        return dq_blk, dk_blk, dv_blk

    if use_flash:
        from activemonitor_tpu.ops.flash_attention import (
            flash_attention_backward_block,
        )

        def attend_full(q_in, kf, vf):
            return flash_attention_backward_block(
                q_in, kf, vf, lse, delta, dout, causal=False
            )

        def attend_diag(q_in, kf, vf):
            return flash_attention_backward_block(
                q_in, kf, vf, lse, delta, dout, causal=True
            )
    else:
        def attend_full(_q_in, kf, vf):
            return _einsum_grads(kf, vf, None)

        def attend_diag(_q_in, kf, vf):
            return _einsum_grads(kf, vf, causal_mask)

    def skip(_q_in, kf, _vf):
        # lax.cond-branch signature parity; an out-of-window block
        # contributes zero to every gradient (zeros sized to the
        # visiting block, so half blocks skip cleanly too)
        zq = jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32)
        zkv = jnp.zeros((batch, kf.shape[1], heads_kv, head_dim), jnp.float32)
        return zq, zkv, zkv

    def contrib_block(kv_idx, kf, vf):
        if causal:
            branch = (
                (kv_idx < my_idx).astype(jnp.int32)
                + 2 * (kv_idx == my_idx).astype(jnp.int32)
            )  # 0 = skip (kv after us), 1 = full, 2 = diagonal
            return jax.lax.switch(
                branch, (skip, attend_full, attend_diag), q, kf, vf
            )
        return attend_full(q, kf, vf)

    if variant == "bidir":
        def contrib_half(kv_idx, kh, vh):
            def work(_q_in, kh, vh):
                return _einsum_grads(kh, vh, None)

            if causal:
                return jax.lax.cond(kv_idx > my_idx, skip, work, q, kh, vh)
            return work(q, kh, vh)

        def diag_contrib(kf, vf):
            if causal:
                return attend_diag(q, kf, vf)
            return attend_full(q, kf, vf)

        if n_devices == 1:
            dq, dk, dv = diag_contrib(k, v)
            return (
                dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
            )

        half = seq_local // 2
        perm_ccw = [(i, (i - 1) % n_devices) for i in range(n_devices)]
        k_cw, k_ccw = k[:, :half], k[:, half:]
        v_cw, v_ccw = v[:, :half], v[:, half:]
        # first K/V hops ride under the diagonal's gradient math
        k_cw = _hop(k_cw, axis_name, perm, "k", "cw")
        v_cw = _hop(v_cw, axis_name, perm, "v", "cw")
        k_ccw = _hop(k_ccw, axis_name, perm_ccw, "k", "ccw")
        v_ccw = _hop(v_ccw, axis_name, perm_ccw, "v", "ccw")
        (k_cw, v_cw, k_ccw, v_ccw), (kd, vd) = jax.lax.optimization_barrier(
            ((k_cw, v_cw, k_ccw, v_ccw), (k, v))
        )
        dq, dk_d, dv_d = diag_contrib(kd, vd)
        # the accumulators split like their blocks and start the ring
        # journey alongside them
        dk_cw = _hop(dk_d[:, :half], axis_name, perm, "dk", "cw")
        dv_cw = _hop(dv_d[:, :half], axis_name, perm, "dv", "cw")
        dk_ccw = _hop(dk_d[:, half:], axis_name, perm_ccw, "dk", "ccw")
        dv_ccw = _hop(dv_d[:, half:], axis_name, perm_ccw, "dv", "ccw")

        def step_fn(carry, t):
            (k_cw, v_cw, k_ccw, v_ccw,
             dk_cw, dv_cw, dk_ccw, dv_ccw, dq) = carry
            kn_cw = _hop(k_cw, axis_name, perm, "k", "cw")
            vn_cw = _hop(v_cw, axis_name, perm, "v", "cw")
            kn_ccw = _hop(k_ccw, axis_name, perm_ccw, "k", "ccw")
            vn_ccw = _hop(v_ccw, axis_name, perm_ccw, "v", "ccw")
            (kn_cw, vn_cw, kn_ccw, vn_ccw), (k_cw, v_cw, k_ccw, v_ccw) = (
                jax.lax.optimization_barrier(
                    ((kn_cw, vn_cw, kn_ccw, vn_ccw),
                     (k_cw, v_cw, k_ccw, v_ccw))
                )
            )
            dq1, dkb_cw, dvb_cw = contrib_half(
                (my_idx - t) % n_devices, k_cw, v_cw
            )
            dq2, dkb_ccw, dvb_ccw = contrib_half(
                (my_idx + t) % n_devices, k_ccw, v_ccw
            )
            dq = dq + dq1 + dq2
            dk_cw = _hop(dk_cw + dkb_cw, axis_name, perm, "dk", "cw")
            dv_cw = _hop(dv_cw + dvb_cw, axis_name, perm, "dv", "cw")
            dk_ccw = _hop(dk_ccw + dkb_ccw, axis_name, perm_ccw, "dk", "ccw")
            dv_ccw = _hop(dv_ccw + dvb_ccw, axis_name, perm_ccw, "dv", "ccw")
            return (
                kn_cw, vn_cw, kn_ccw, vn_ccw,
                dk_cw, dv_cw, dk_ccw, dv_ccw, dq,
            ), None

        carry = _run_steps(
            step_fn,
            (k_cw, v_cw, k_ccw, v_ccw, dk_cw, dv_cw, dk_ccw, dv_ccw, dq),
            n_devices - 2, unroll, start=1,
        )
        (k_cw, v_cw, k_ccw, v_ccw,
         dk_cw, dv_cw, dk_ccw, dv_ccw, dq) = carry
        t_last = n_devices - 1
        dq1, dkb_cw, dvb_cw = contrib_half(
            (my_idx - t_last) % n_devices, k_cw, v_cw
        )
        dq2, dkb_ccw, dvb_ccw = contrib_half(
            (my_idx + t_last) % n_devices, k_ccw, v_ccw
        )
        dq = dq + dq1 + dq2
        # homeward hop: the accumulators' n-th — carrying real gradients
        dk_cw = _hop(dk_cw + dkb_cw, axis_name, perm, "dk", "cw")
        dv_cw = _hop(dv_cw + dvb_cw, axis_name, perm, "dv", "cw")
        dk_ccw = _hop(dk_ccw + dkb_ccw, axis_name, perm_ccw, "dk", "ccw")
        dv_ccw = _hop(dv_ccw + dvb_ccw, axis_name, perm_ccw, "dv", "ccw")
        dk = jnp.concatenate([dk_cw, dk_ccw], axis=1)
        dv = jnp.concatenate([dv_cw, dv_ccw], axis=1)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    init = (
        k,  # rotates in input dtype, like the forward
        v,
        jnp.zeros((batch, seq_local, heads_kv, head_dim), jnp.float32),  # dk
        jnp.zeros((batch, seq_local, heads_kv, head_dim), jnp.float32),  # dv
        jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),  # dq
    )

    def step_fn(carry, step):
        kf, vf, dk, dv, dq = carry
        kv_idx = (my_idx - step) % n_devices
        if variant == "overlap":
            # prefetch the next K/V block under this step's gradient
            # math (the dominant per-step cost — ~3x the forward FLOPs)
            k_next = _hop(kf, axis_name, perm, "k")
            v_next = _hop(vf, axis_name, perm, "v")
            (k_next, v_next), (kf, vf) = jax.lax.optimization_barrier(
                ((k_next, v_next), (kf, vf))
            )
            dq_blk, dk_blk, dv_blk = contrib_block(kv_idx, kf, vf)
        else:
            dq_blk, dk_blk, dv_blk = contrib_block(kv_idx, kf, vf)
            k_next = _hop(kf, axis_name, perm, "k")
            v_next = _hop(vf, axis_name, perm, "v")
        dq = dq + dq_blk
        # accumulators travel WITH their block
        dk = _hop(dk + dk_blk, axis_name, perm, "dk")
        dv = _hop(dv + dv_blk, axis_name, perm, "dv")
        return (k_next, v_next, dk, dv, dq), None

    kf, vf, dk, dv, dq = _run_steps(step_fn, init, n_devices - 1, unroll)
    dq_blk, dk_blk, dv_blk = contrib_block(
        (my_idx - (n_devices - 1)) % n_devices, kf, vf
    )
    dq = dq + dq_blk
    dk = dk + dk_blk
    dv = dv + dv_blk
    if n_devices > 1:
        # homeward hop: the accumulators' n-th — carrying real gradients
        dk = _hop(dk, axis_name, perm, "dk")
        dv = _hop(dv, axis_name, perm, "dv")
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_diff(q, k, v, axis_name, n_devices, causal, use_flash, variant, unroll):
    out, _ = _ring_attention_sharded(
        q, k, v, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash, variant=variant, unroll=unroll,
    )
    return out


def _ring_diff_fwd(q, k, v, axis_name, n_devices, causal, use_flash, variant, unroll):
    out, lse = _ring_attention_sharded(
        q, k, v, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash, variant=variant, unroll=unroll,
    )
    return out, (q, k, v, out, lse)


def _ring_diff_bwd(
    axis_name, n_devices, causal, use_flash, variant, unroll, residuals, dout
):
    q, k, v, out, lse = residuals
    return _ring_attention_bwd_sharded(
        q, k, v, out, lse, dout, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash, variant=variant, unroll=unroll,
    )


_ring_diff.defvjp(_ring_diff_fwd, _ring_diff_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    use_flash: bool = False,
    in_spec: P | None = None,
    variant: str = "overlap",
    unroll: bool = False,
    rules=None,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh[axis]``, differentiable
    (custom VJP: the backward is a second K/V ring pass recomputing
    block probabilities from the saved global logsumexp).

    q, k, v: global ``[batch, seq, heads, head_dim]`` arrays; the seq
    dim is sharded over the axis. K/V may carry FEWER heads (GQA — any
    divisor of q's heads, down to 1 for MQA): the narrow K/V blocks are
    what rotates, so grouped heads shrink ICI traffic by the group
    factor, and dK/dV come back group-summed in K/V's own shape.
    Returns attention output with q's global shape/sharding.

    ``variant`` picks the communication schedule (module docstring):
    ``"overlap"`` (default) double-buffers the K/V rotation under the
    block attends — bit-identical to ``"serial"``, which exists as the
    measured baseline; ``"bidir"`` splits K/V halves over both ICI
    link directions (numerically, not bitwise, equal). ``unroll``
    trades flat compile time for a python-loop schedule whose hops are
    individually traced (the probe/test hop counter).
    ``use_flash`` runs each ring step's block compute (forward AND
    backward) through the fused Pallas kernels.

    The shard_map partitioning resolves from regex partition RULES
    (:func:`ring_partition_rules` by default) matched over the
    ``{"q","k","v"}`` pytree — pass ``rules=`` to re-mesh a composed
    probe without touching the schedules, e.g.
    ``(("^(q|k|v)$", P("data", "sp", "model", None)),)`` to run the
    ring inside a dp×tp×sp train step (batch and heads are
    embarrassingly parallel for the ring; only position 1, the
    sequence dim, must carry ``axis``). ``in_spec`` is the legacy
    spelling of the same override (one spec for all three operands)
    and is mutually exclusive with ``rules``.
    """
    n = mesh.shape[axis]
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, got {variant!r}")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA needs n_heads ({q.shape[2]}) divisible by n_kv_heads "
            f"({k.shape[2]})"
        )
    if variant == "bidir" and n > 1 and q.shape[1] // n < 2:
        raise ValueError(
            "bidirectional ring attention needs >= 2 tokens per shard "
            f"to split K/V halves (got {q.shape[1]} over {n} devices)"
        )
    if rules is not None and in_spec is not None:
        raise ValueError("pass rules= or in_spec=, not both")
    if rules is None:
        rules = (
            ring_partition_rules(axis)
            if in_spec is None
            else (("^(q|k|v)$", in_spec),)
        )
    resolved = match_partition_rules(rules, {"q": q, "k": k, "v": v}, mesh=mesh)
    for name in ("q", "k", "v"):
        spec = resolved[name]
        if len(spec) <= 1 or spec[1] != axis:
            raise ValueError(
                f"resolved spec for {name!r} must shard the sequence dim "
                f"(position 1) over {axis!r}, got {spec}"
            )
    in_specs = (resolved["q"], resolved["k"], resolved["v"])

    def body(q, k, v):
        # positional call: custom_vjp rejects keyword arguments
        return _ring_diff(q, k, v, axis, n, causal, use_flash, variant, unroll)

    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=resolved["q"],
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True, segment_ids=None) -> jax.Array:
    """Single-device attention for correctness checks.

    Generalized the same way as the fused kernel
    (ops/flash_attention.py): K/V may carry fewer heads (GQA — each
    group of ``n_heads // n_kv_heads`` query heads shares a K/V head),
    a different sequence length (causal masking bottom-right aligned:
    query row i attends keys ≤ i + seq_k − seq_q, the decode
    convention; equal lengths reduce to the standard mask), and packed
    sequences (``segment_ids``: one [B, S] array or a (q_ids, kv_ids)
    tuple — attention only within matching segments)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    heads, heads_kv = q.shape[2], k.shape[2]
    if heads != heads_kv:
        k = jnp.repeat(k, heads // heads_kv, axis=2)
        v = jnp.repeat(v, heads // heads_kv, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = q.shape[1], k.shape[1]
        q_pos = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
        mask = q_pos >= jnp.arange(seq_k)[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            q_seg, kv_seg = segment_ids
        else:
            q_seg = kv_seg = segment_ids
        seg = q_seg[:, :, None] == kv_seg[:, None, :]  # [B, Sq, Sk]
        scores = jnp.where(seg[:, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)
