"""Critical-path latency observatory (ISSUE 17).

Units for the pure waterfall math (conservation to the wall span,
innermost-wins segmentation, the probe-phase carve, the TTFT split) and
the scripted FakeClock + FakeEngine acceptance: a front-door submit
that coalesces into a scheduled run yields ONE waterfall whose stage
durations sum to the trace's wall span (±1e-9, ``untracked`` included),
visible identically via /statusz, the
``healthcheck_critical_path_seconds`` gauges, and ``am-tpu waterfall``;
an injected queue-wait degradation flips the dominant stage to
``queue_wait``, fires exactly one profile-on-anomaly capture (a second
trigger inside the cooldown fires none), and the flight bundle carries
both the waterfall and the capture path.
"""

import argparse
import asyncio
import collections
import json
import os

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.frontdoor import (
    AdmissionController,
    FrontDoor,
    OUTCOME_JOINED,
    OUTCOME_RUN,
    TenantQuota,
)
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import criticalpath
from activemonitor_tpu.obs.criticalpath import (
    STAGES,
    build_waterfall,
    decompose_ttft,
    dominant_stage,
    errored_span_names,
    queue_wait,
)
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = (
    "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
)


# ---------------------------------------------------------------------
# unit: the pure waterfall math
# ---------------------------------------------------------------------


class FakeSpan:
    def __init__(self, name, start, end, error="", trace_id="t-1"):
        self.name = name
        self.start = start
        self.end = end
        self.error = error
        self.trace_id = trace_id

    @property
    def duration(self):
        return max(0.0, (self.end or self.start) - self.start)


def test_build_waterfall_conserves_wall_with_untracked_gap():
    spans = [
        FakeSpan("reconcile", 0.0, 10.0),
        FakeSpan("dequeue", 0.0, 2.0),
        FakeSpan("parse", 2.0, 2.5),
        FakeSpan("submit", 2.5, 3.0),
        # [3.0, 3.2] is covered by no mapped span: the untracked gap
        FakeSpan("poll", 3.2, 9.0),
        FakeSpan("status_write", 9.0, 9.7),
    ]
    w = build_waterfall(spans, timings={"allreduce": 1.5, "compile": 1.5})
    assert w["wall_seconds"] == pytest.approx(10.0)
    assert set(w["stages"]) == set(STAGES)
    assert sum(w["stages"].values()) == pytest.approx(10.0, abs=1e-9)
    assert w["stages"]["queue_wait"] == pytest.approx(2.0)
    # probe phases carve out of poll, never double-book
    assert w["stages"]["probe_phase"] == pytest.approx(3.0)
    assert w["stages"]["poll"] == pytest.approx(5.8 - 3.0)
    # the uncovered gap plus the post-status_write tail, booked honestly
    assert w["stages"]["untracked"] == pytest.approx(0.2 + 0.3)
    assert w["dominant_stage"] == "probe_phase"
    # segments are orderable for the ASCII rendering and exclude the
    # placement-free untracked residual
    assert [s["stage"] for s in w["segments"]] == [
        "queue_wait", "schedule", "submit", "poll", "probe_phase",
        "status_write",
    ]


def test_nested_spans_book_innermost_wins():
    # status_write nested inside poll: the overlap belongs to the child
    spans = [
        FakeSpan("poll", 0.0, 8.0),
        FakeSpan("status_write", 6.0, 8.0),
    ]
    w = build_waterfall(spans)
    assert w["stages"]["poll"] == pytest.approx(6.0)
    assert w["stages"]["status_write"] == pytest.approx(2.0)
    assert sum(w["stages"].values()) == pytest.approx(8.0, abs=1e-9)


def test_probe_phase_carve_is_capped_at_the_poll_stage():
    spans = [FakeSpan("poll", 0.0, 2.0)]
    # the probe claims more phase time than the controller polled for:
    # the carve caps at the poll window so the sum stays conserved
    w = build_waterfall(spans, timings={"soak": 50.0, "bogus": "x"})
    assert w["stages"]["probe_phase"] == pytest.approx(2.0)
    assert w["stages"]["poll"] == 0.0
    assert sum(w["stages"].values()) == pytest.approx(2.0, abs=1e-9)


def test_build_waterfall_needs_a_finished_span():
    assert build_waterfall([]) is None
    assert build_waterfall([FakeSpan("poll", 1.0, None)]) is None


def test_queue_wait_and_errored_span_names_definitions():
    spans = [
        FakeSpan("dequeue", 0.0, 3.0),
        FakeSpan("poll", 3.0, 4.0, error="TimeoutError"),
        FakeSpan("dequeue", 0.0, 1.0),
    ]
    assert queue_wait(spans) == pytest.approx(3.0)
    assert errored_span_names(spans) == ["poll"]
    assert queue_wait([]) == 0.0


def test_dominant_stage_ties_break_in_path_order():
    assert dominant_stage({"poll": 1.0, "queue_wait": 1.0}) == "queue_wait"
    assert dominant_stage({}) == "queue_wait"


def test_decompose_ttft_reads_the_scheduler_stamps():
    class Req:
        def __init__(self, arrival):
            self.arrival = arrival

    class Seq:
        def __init__(self, arrival, admitted, first_token, first_decode):
            self.req = Req(arrival)
            self.admitted_at = admitted
            self.first_token_at = first_token
            self.first_decode_at = first_decode

    split = decompose_ttft(
        [
            Seq(0.0, 1.0, 3.0, 3.5),
            Seq(0.0, 2.0, 5.0, None),  # one-token request: no decode
            Seq(0.0, 0.0, None, None),  # never produced a token: skipped
        ]
    )
    assert split["samples"] == 2
    assert split["queue_wait"]["p95"] == pytest.approx(2.0)
    assert split["prefill"]["p95"] == pytest.approx(3.0)
    assert split["first_decode"]["p95"] == pytest.approx(0.5)
    assert decompose_ttft([]) is None


def test_render_waterfall_stage_table_and_ascii_bars():
    from activemonitor_tpu.__main__ import render_waterfall

    block = criticalpath.aggregate_waterfalls(
        [
            build_waterfall(
                [
                    FakeSpan("dequeue", 0.0, 4.0),
                    FakeSpan("poll", 4.0, 5.0),
                ]
            )
        ]
    )
    out = render_waterfall({"key": "health/hc-x", "critical_path": block})
    assert "dominant=queue_wait" in out
    assert "STAGE" in out and "P95" in out
    assert "queue_wait" in out and "4.00s" in out
    # the last-run ASCII waterfall: offset-indented bars
    assert "last run (trace" in out
    lines = out.splitlines()
    qw_bar = next(l for l in lines if l.strip().startswith("queue_wait") and "|" in l)
    poll_bar = next(l for l in lines if l.strip().startswith("poll") and "|" in l)
    assert "#" in qw_bar and "#" in poll_bar
    # poll starts after queue_wait on the timeline
    assert poll_bar.index("#") > qw_bar.index("#")
    # a check with no evidence renders a structured explanation
    assert "no critical-path evidence" in render_waterfall(
        {"key": "health/hc-y", "critical_path": None}
    )


# ---------------------------------------------------------------------
# acceptance: front door -> coalesced run -> one waterfall everywhere,
# queue-wait degradation flips the dominant stage, one bounded capture
# ---------------------------------------------------------------------

CONTRACT_DOC = json.dumps(
    {
        "metrics": [
            {"name": "probe-bw-gbps", "value": 123.0, "metrictype": "gauge"}
        ],
        "timings": {"allreduce": 0.25, "compile": 0.25},
    }
)
OUTPUTS = {"parameters": [{"name": "metrics", "value": CONTRACT_DOC}]}


def make_hc(name, repeat=600, slo=None):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 60,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if slo:
        spec["slo"] = slo
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


def scripted_engine(script, clock=None):
    """FakeEngine whose Nth SUBMITTED workflow follows the Nth script
    entry: pending until the scripted poll count, then the scripted
    verdict (successes carry the metrics+timings contract). Setting
    ``engine.submit_delay`` makes the NEXT submit await that many fake
    seconds (consumed once) — submits run inside the reconcile worker,
    so a slow one pins the worker and injects real queue wait."""
    engine = FakeWorkflowEngine()
    queue = collections.deque(script)
    assigned = {}
    real_submit = engine.submit
    engine.submit_delay = 0.0

    async def submit(manifest):
        delay, engine.submit_delay = engine.submit_delay, 0.0
        if delay and clock is not None:
            await clock.sleep(delay)
        name = await real_submit(manifest)
        if queue:
            assigned[name] = queue.popleft()
        return name

    engine.submit = submit

    def completer(wf, count):
        entry = assigned.get(wf["metadata"]["name"])
        if entry is None:
            return None
        polls, ok = entry
        if count < polls:
            return None
        if ok:
            return {"phase": PHASE_SUCCEEDED, "outputs": OUTPUTS}
        return {"phase": PHASE_FAILED, "message": "scripted failure"}

    engine._default_completer = completer
    return engine


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


async def drive(clock, polls):
    await settle()
    for _ in range(polls):
        await clock.advance(1.0)
    await settle()


class FakeCapture:
    """Injected capture factory: stands in for jax.profiler.trace and
    writes one artifact so the capture dir is non-empty."""

    calls: list = []

    def __init__(self, path):
        self.path = path

    def __enter__(self):
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, "trace.pb"), "w") as f:
            f.write("profile")
        FakeCapture.calls.append(self.path)
        return self

    def __exit__(self, *exc):
        return False


SCRIPT = [
    (2, True),  # hc-cp boot run
    (2, True),  # hc-cp front-door coalesced run
    (2, False),  # hc-cp failure: burn rate 3.33 arms the profiler
    (31, True),  # hc-busy: its SLOW submit pins the worker (injection)
    (2, True),  # hc-cp queue-delayed run: CAPTURED, queue_wait dominates
    (2, True),  # hc-cp follow-up: still burning, but inside the cooldown
]


@pytest.mark.asyncio
async def test_acceptance_waterfall_everywhere_and_one_bounded_capture(
    tmp_path, capsys
):
    import aiohttp

    from activemonitor_tpu.__main__ import _waterfall, render_waterfall

    FakeCapture.calls = []
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    engine = scripted_engine(SCRIPT, clock=clock)
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    door = FrontDoor(
        reconciler.fleet.history,
        AdmissionController(
            default_quota=TenantQuota(rate_per_minute=6000.0), clock=clock
        ),
        clock=clock,
        metrics=metrics,
        resilience=reconciler.resilience,
        default_freshness=30.0,
    )
    capture_dir = tmp_path / "captures"
    manager = Manager(
        client=client,
        reconciler=reconciler,
        max_parallel=1,  # ONE worker, so a busy check delays the queue
        frontdoor=door,
        profile_on_anomaly_dir=str(capture_dir),
    )
    manager._profiler.capture_factory = FakeCapture
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        key = "health/hc-cp"
        hc = make_hc("hc-cp", slo={"objective": 0.9, "windowSeconds": 3600})
        await client.apply(hc)
        await drive(clock, 2)  # boot run (ok)

        # --- front-door submit coalescing into ONE scheduled run ------
        await clock.advance(31.0)  # age the boot result past freshness
        run_ticket = door.submit("tenant-a", key)
        join_ticket = door.submit("tenant-b", key)
        assert run_ticket.outcome == OUTCOME_RUN
        assert join_ticket.outcome == OUTCOME_JOINED
        # the ticket lifecycle rides the waterfall's evidence chain
        assert [ev for ev, _t in run_ticket.lifecycle] == [
            "admit", "demand-fire", "enqueue",
        ]
        assert [ev for ev, _t in join_ticket.lifecycle] == [
            "admit", "coalesce-join",
        ]
        await drive(clock, 2)
        result = await run_ticket.wait()
        joined = await join_ticket.wait()
        assert result.ok and result.trace_id == run_ticket.trace_id
        assert joined is result  # ONE run fanned out to both tenants
        assert join_ticket.trace_id == run_ticket.trace_id

        # --- surface 1: /statusz ---------------------------------------
        port = manager._http_runners[0].addresses[0][1]

        async def fetch_statusz():
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    f"http://127.0.0.1:{port}/statusz"
                ) as r:
                    assert r.status == 200
                    return await r.json()

        payload = await fetch_statusz()
        [entry] = payload["checks"]
        block = entry["critical_path"]
        assert block["runs"] == 2 and block["skewed_runs"] == 0
        last = block["last"]
        # the coalesced submit produced ONE waterfall, on the shared trace
        assert last["trace_id"] == run_ticket.trace_id
        # conservation: stage durations sum to the trace's wall span
        # (untracked included) to within 1e-9
        assert set(last["stages"]) == set(STAGES)
        assert sum(last["stages"].values()) == pytest.approx(
            last["wall_seconds"], abs=1e-9
        )
        # the front door's admission span landed in the run's trace
        assert last["stages"]["admission"] >= 0.0
        assert block["dominant_stage"] != "queue_wait"  # healthy so far
        assert payload["fleet"]["critical_path"]["runs"] == 2

        # --- surface 2: the pinned gauges (synced by the statusz build)
        for stage in STAGES:
            gauge = metrics.sample_value(
                "healthcheck_critical_path_seconds",
                {
                    "healthcheck_name": "hc-cp",
                    "namespace": "health",
                    "stage": stage,
                    "quantile": "p95",
                },
            )
            assert gauge == pytest.approx(block["stages"][stage]["p95"])

        # --- surface 3: `am-tpu waterfall` over the live endpoint ------
        args = argparse.Namespace(
            url=[f"http://127.0.0.1:{port}/statusz"],
            token="",
            name="hc-cp",
            namespace=None,
            output="json",
        )
        assert await _waterfall(args) == 0
        cli_doc = json.loads(capsys.readouterr().out)
        assert cli_doc["key"] == key
        assert cli_doc["critical_path"]["last"]["stages"] == pytest.approx(
            last["stages"]
        )
        rendered = render_waterfall(entry)
        assert "hc-cp" in rendered and "#" in rendered

        # --- inject a queue-wait degradation ---------------------------
        # run 3, demanded off-schedule (the front door's demand-fire
        # path, minus its freshness cache): the scripted failure
        await drive(clock, 0)
        reconciler.demand("health", "hc-cp")
        manager.enqueue("health", "hc-cp")
        await drive(clock, 2)
        assert not reconciler.fleet.history.last(key).ok
        # the burn-rate breach armed exactly one capture of the next run
        assert manager._profiler._armed == {key: "burn_rate"}
        assert FakeCapture.calls == []

        # hc-busy's SLOW submit pins the single reconcile worker for
        # 30 fake seconds (watches poll in detached tasks, so only the
        # submit path can occupy a worker); hc-cp then waits its whole
        # enqueue-to-dequeue gap in the queue
        engine.submit_delay = 30.0
        await client.apply(make_hc("hc-busy"))
        await settle()
        reconciler.demand("health", "hc-cp")
        trace_id = manager.enqueue("health", "hc-cp")
        assert trace_id  # the pre-minted trace the dequeue span joins
        for _ in range(31):
            await clock.advance(1.0)
        await settle()
        await drive(clock, 2)  # hc-cp's own (captured) run

        payload = await fetch_statusz()
        entry = next(c for c in payload["checks"] if c["key"] == key)
        block = entry["critical_path"]
        # the dominant stage flipped to queue_wait, in the window AND
        # in the newest run's own decomposition — conservation holds
        assert block["dominant_stage"] == "queue_wait"
        assert block["last"]["dominant_stage"] == "queue_wait"
        assert block["last"]["trace_id"] == trace_id
        assert block["last"]["stages"]["queue_wait"] >= 30.0
        assert sum(block["last"]["stages"].values()) == pytest.approx(
            block["last"]["wall_seconds"], abs=1e-9
        )
        assert payload["fleet"]["critical_path"]["dominant_stage"] == (
            "queue_wait"
        )
        rendered = render_waterfall(entry)
        assert "dominant=queue_wait" in rendered

        # --- exactly ONE bounded capture -------------------------------
        assert len(FakeCapture.calls) == 1
        capture_path = FakeCapture.calls[0]
        assert os.path.isfile(os.path.join(capture_path, "trace.pb"))
        assert (
            metrics.sample_value(
                "healthcheck_profile_captures_total", {"reason": "burn_rate"}
            )
            == 1.0
        )
        # the capture index journals the capture for offline tooling
        index_lines = (
            (capture_dir / "captures.jsonl").read_text().splitlines()
        )
        assert len(index_lines) == 1
        index_doc = json.loads(index_lines[0])
        assert index_doc["check"] == key
        assert index_doc["reason"] == "burn_rate"
        assert index_doc["path"] == capture_path
        # the flight bundle carries BOTH the waterfall and the path
        [bundle] = reconciler.flightrec.bundles(
            kind="profile-capture", check=key
        )
        assert bundle["extra"]["capture_path"] == capture_path
        assert bundle["extra"]["captured"] is True
        assert bundle["waterfall"] is not None
        assert bundle["waterfall"]["dominant_stage"] == "queue_wait"
        assert sum(bundle["waterfall"]["stages"].values()) == pytest.approx(
            bundle["waterfall"]["wall_seconds"], abs=1e-9
        )

        # the captured run's own record re-fired the trigger (its burn
        # rate is still hot) — the cooldown absorbed it: nothing armed
        assert manager._profiler._armed == {}
        # and a whole further run fires no second capture
        reconciler.demand("health", "hc-cp")
        manager.enqueue("health", "hc-cp")
        await drive(clock, 2)
        assert len(FakeCapture.calls) == 1
        assert (
            metrics.sample_value(
                "healthcheck_profile_captures_total", {"reason": "burn_rate"}
            )
            == 1.0
        )
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_profiler_disabled_by_default_never_arms():
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine([(2, False)]),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=1)
    assert not manager._profiler.enabled
    assert reconciler.profile_hook is None
    assert reconciler.fleet.profile_hook is None
    assert manager._profiler.arm("health/hc-x", "burn_rate") is False


def test_profile_capture_directory_size_cap(tmp_path):
    """The shared size cap: oldest captures are pruned once the
    directory exceeds --profile-max-bytes; the newest always survives."""
    from activemonitor_tpu.controller.manager import ProfileOnAnomaly

    clock = FakeClock()
    prof = ProfileOnAnomaly(
        clock=clock,
        directory=str(tmp_path),
        cooldown=0.0,
        max_bytes=1500,
        capture_factory=FakeCapture,
    )
    for i in range(3):
        assert prof.arm(f"health/hc-{i}", "degraded")
        with prof.capture(f"health/hc-{i}"):
            pass
        # each fake capture holds a 7-byte file; pad it past the cap
        newest = prof._capture_paths[-1]
        with open(os.path.join(newest, "pad.bin"), "w") as f:
            f.write("x" * 1000)
    surviving = [p for p in prof._capture_paths if os.path.isdir(p)]
    # the cap pruned the oldest captures; the newest is always kept
    assert surviving and surviving[-1].endswith("-000003")
    assert len(surviving) < 3
