"""The examples corpus is a behavioral spec (SURVEY.md §2 #16): every
example must load, validate, and produce a submittable manifest."""

import glob
from pathlib import Path

import pytest
import yaml

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import parse_workflow_from_healthcheck

EXAMPLES = sorted(
    p
    for p in glob.glob("examples/**/*.yaml", recursive=True)
    # workflows/ are Argo Workflow bodies, federation-config is a
    # controller config document — neither is a HealthCheck manifest
    if "workflows/" not in p and "federation-config" not in p
)


def load_healthchecks(path):
    for doc in yaml.safe_load_all(Path(path).read_text()):
        if isinstance(doc, dict) and doc.get("kind") == "HealthCheck":
            yield HealthCheck.from_dict(doc)


def test_examples_exist():
    assert len(EXAMPLES) >= 12


@pytest.mark.parametrize("path", EXAMPLES)
def test_example_validates(path):
    checks = list(load_healthchecks(path))
    assert checks, f"{path} contains no HealthCheck"
    for hc in checks:
        assert hc.metadata.name
        assert hc.spec.level in ("cluster", "namespace", "")


@pytest.mark.parametrize("path", EXAMPLES)
def test_inline_examples_produce_submittable_manifests(path):
    for hc in load_healthchecks(path):
        if hc.spec.workflow.resource.source.inline is None:
            continue  # url/file sources need live endpoints
        wf = parse_workflow_from_healthcheck(hc)
        assert wf["kind"] == "Workflow"
        assert wf["spec"]["templates"]


def test_feature_matrix_coverage():
    """The corpus must cover the reference's feature matrix plus the
    TPU extensions."""
    all_checks = [hc for p in EXAMPLES for hc in load_healthchecks(p)]
    assert any(hc.spec.repeat_after_sec > 0 for hc in all_checks)  # interval
    assert any(hc.spec.schedule.cron for hc in all_checks)  # cron
    assert any(hc.spec.level == "namespace" for hc in all_checks)
    assert any(hc.spec.level == "cluster" for hc in all_checks)
    assert any(  # pause
        hc.spec.repeat_after_sec <= 0 and not hc.spec.schedule.cron
        for hc in all_checks
    )
    assert any(hc.spec.workflow.resource.source.url for hc in all_checks)
    assert any(hc.spec.workflow.resource.source.file for hc in all_checks)
    assert any(not hc.spec.remedy_workflow.is_empty() for hc in all_checks)
    assert any(hc.spec.remedy_runs_limit > 0 for hc in all_checks)  # gated remedy
    assert any(hc.spec.backoff_max > 0 for hc in all_checks)  # custom backoff
    assert any(hc.spec.workflow.tpu is not None for hc in all_checks)  # TPU
    tpu_checks = [hc for hc in all_checks if hc.spec.workflow.tpu]
    assert any(hc.spec.workflow.tpu.chips == 8 for hc in tpu_checks)
    # Argo loops pass through the spec mutator intact (reference:
    # examples/inlineLoops.yaml)
    assert any(
        "withItems" in (hc.spec.workflow.resource.source.inline or "")
        for hc in all_checks
    )
    # baseline & anomaly detection opt-in (docs/analysis.md)
    assert any(hc.spec.analysis is not None for hc in all_checks)
    # bucket-targeted remedies (ISSUE 18: closed-loop goodput control)
    assert any(hc.spec.remedy_workflow.by_bucket for hc in all_checks)
    # capability requirements for federation routing (ISSUE 19)
    assert any(hc.spec.requires is not None for hc in all_checks)


def test_federation_config_example_builds_a_plane():
    """examples/federation/federation-config.yaml is the
    --federation-config contract: it must build a working plane with
    the capability cards the rated tables imply."""
    import yaml as _yaml

    from activemonitor_tpu.federation import FederationPlane
    from activemonitor_tpu.utils.clock import FakeClock

    doc = _yaml.safe_load(
        Path("examples/federation/federation-config.yaml").read_text()
    )
    plane = FederationPlane.from_config(doc, clock=FakeClock())
    assert plane.registry.names() == ["us-east1-v5e", "us-west1-v5p"]
    west = plane.registry.get("us-west1-v5p")
    assert west.generation == "v5p"
    assert west.dcn_gbps == 100.0  # the explicit per-host override wins
    east = plane.registry.get("us-east1-v5e")
    assert east.dcn_gbps == 25.0  # the rated tier applies when omitted
    assert east.slices == ("edge-pod",)


def test_federation_check_declares_v5p_mesh_requirement():
    """The v5p-mesh example's `requires` block must parse into a
    Requirement the router honors: generation-pinned, 64-chip mesh."""
    from activemonitor_tpu.federation import Requirement

    [hc] = load_healthchecks("examples/federation/v5p-mesh-check.yaml")
    assert hc.spec.requires is not None
    assert hc.spec.requires.generation == "v5p"
    assert hc.spec.requires.topology == "4x4x4"
    req = Requirement.from_spec(hc.spec.requires)
    assert req.chips_needed() == 64
    assert not req.empty()


def test_bucket_remedy_example_selects_by_attribution():
    """The byBucket example must honor the selection contract: mapped
    buckets get their targeted workflow, unmapped buckets fall back to
    the plain remedy, and every selected workflow still parses into a
    submittable manifest inheriting the fallback's serviceAccount when
    it declares none of its own."""
    from activemonitor_tpu.controller import parse_remedy_workflow_from_healthcheck

    (hc,) = load_healthchecks("examples/remedy/bucket-remedy.yaml")
    remedy = hc.spec.remedy_workflow
    assert set(remedy.by_bucket) == {"ici", "control_plane"}
    # the RBAC contract: the plain fallback carries resource + SA
    assert remedy.resource is not None
    assert remedy.resource.service_account == "activemonitor-remedy-sa"

    ici = remedy.select_for_bucket("ici")
    assert ici is remedy.by_bucket["ici"]
    wf = parse_remedy_workflow_from_healthcheck(hc, remedy=ici)
    assert wf["kind"] == "Workflow"
    # no serviceAccount of its own → inherits the plain remedy's
    assert wf["spec"]["serviceAccountName"] == "activemonitor-remedy-sa"

    cp = remedy.select_for_bucket("control_plane")
    wf = parse_remedy_workflow_from_healthcheck(hc, remedy=cp)
    assert wf["spec"]["serviceAccountName"] == "activemonitor-remedy-admin-sa"

    # unmapped bucket → the plain remedy itself
    assert remedy.select_for_bucket("hbm") is remedy
    assert remedy.select_for_bucket("") is remedy


def test_analysis_baseline_example_declares_the_full_block():
    (hc,) = load_healthchecks("examples/tpu/analysis-baseline.yaml")
    analysis = hc.spec.analysis
    assert analysis is not None
    assert analysis.cohort == "v5e-pool-a"
    assert analysis.warmup_runs == 5
    assert analysis.z_threshold == 3.0
    assert "mxu-matmul-tflops" in analysis.metrics
    assert analysis.trigger_on_degraded is False
    # the example still parses into a submittable manifest
    wf = parse_workflow_from_healthcheck(hc)
    assert wf["kind"] == "Workflow"


def test_loops_example_passes_withitems_through():
    (hc,) = load_healthchecks("examples/inline-loops.yaml")
    wf = parse_workflow_from_healthcheck(hc)
    steps = wf["spec"]["templates"][0]["steps"]
    assert steps[0][0]["withItems"] == [
        "kubernetes.default.svc",
        "metrics-server.kube-system.svc",
    ]


def test_tpu_example_gets_placement_injected():
    (hc,) = load_healthchecks("examples/tpu/tpu-ici-allreduce.yaml")
    wf = parse_workflow_from_healthcheck(hc)
    sel = wf["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    limits = wf["spec"]["templates"][0]["container"]["resources"]["limits"]
    assert limits["google.com/tpu"] == 8


def test_generated_crd_manifest_is_current():
    """config/crd must match what the code generates (drift guard)."""
    from activemonitor_tpu.api.crd import crd_yaml

    on_disk = Path("config/crd/activemonitor.keikoproj.io_healthchecks.yaml").read_text()
    assert yaml.safe_load(on_disk) == yaml.safe_load(crd_yaml())


def test_deploy_manifest_parses():
    docs = list(
        yaml.safe_load_all(Path("deploy/deploy-active-monitor-tpu.yaml").read_text())
    )
    kinds = [d["kind"] for d in docs]
    assert kinds == [
        "Namespace",
        "ServiceAccount",
        "ClusterRole",
        "ClusterRoleBinding",
        "Deployment",
    ]


def test_argo_install_wires_the_instance_id_contract():
    """deploy/install-argo.{sh,yaml} must configure Argo with the SAME
    instance id the spec mutator stamps on every submitted workflow —
    a mismatch would make Argo silently ignore all probes."""
    from activemonitor_tpu.controller import WF_INSTANCE_ID

    docs = list(yaml.safe_load_all(Path("deploy/install-argo.yaml").read_text()))
    configmaps = [d for d in docs if d and d.get("kind") == "ConfigMap"]
    assert any(
        cm["data"].get("instanceID") == WF_INSTANCE_ID for cm in configmaps
    ), "workflow-controller-configmap must carry the framework's instanceID"

    script = Path("deploy/install-argo.sh").read_text()
    assert WF_INSTANCE_ID in script
    assert "install.yaml" in script  # pinned upstream distribution
    import os

    assert os.access("deploy/install-argo.sh", os.X_OK)


def test_manager_clusterrole_covers_every_api_the_controller_uses():
    """Manager-role parity (reference: config/rbac/role.yaml): each
    group/resource the runtime touches must be grantable from the
    deploy manifest's ClusterRole."""
    docs = list(
        yaml.safe_load_all(Path("deploy/deploy-active-monitor-tpu.yaml").read_text())
    )
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    granted = {
        (group, resource)
        for rule in role["rules"]
        for group in rule["apiGroups"]
        for resource in rule["resources"]
    }
    needed = {
        ("activemonitor.keikoproj.io", "healthchecks"),  # client_k8s.py
        ("activemonitor.keikoproj.io", "healthchecks/status"),
        ("argoproj.io", "workflows"),  # engine/argo.py
        ("", "serviceaccounts"),  # rbac.py KubernetesRBACBackend
        ("rbac.authorization.k8s.io", "roles"),
        ("rbac.authorization.k8s.io", "rolebindings"),
        ("rbac.authorization.k8s.io", "clusterroles"),
        ("rbac.authorization.k8s.io", "clusterrolebindings"),
        ("", "events"),  # events.py KubernetesEventRecorder
        ("coordination.k8s.io", "leases"),  # leader.py
    }
    missing = needed - granted
    assert not missing, f"deploy ClusterRole missing grants: {missing}"


def test_kustomize_tree_matches_deploy():
    """config/ (kubectl apply -k config/default) and the one-shot
    deploy manifest must install identical object SETS — keyed by
    (kind, name), compared both directions, so an object added to
    either tree alone fails here. config/ is the source of truth: the
    deploy file is generated by hack/gen_deploy.py (CI drift-checks)."""

    def doc_set(paths):
        docs = {}
        for path in paths:
            if path.endswith("kustomization.yaml"):
                continue
            for doc in yaml.safe_load_all(Path(path).read_text()):
                if doc:
                    key = (doc["kind"], doc["metadata"]["name"])
                    assert key not in docs, f"duplicate {key} in {path}"
                    docs[key] = doc
        return docs

    deploy_docs = doc_set(["deploy/deploy-active-monitor-tpu.yaml"])
    tree_docs = doc_set(
        glob.glob("config/rbac/*.yaml") + glob.glob("config/manager/*.yaml")
    )
    assert set(tree_docs) == set(deploy_docs), (
        "object sets drifted between config/ and deploy/: "
        f"{set(tree_docs) ^ set(deploy_docs)}"
    )
    for key, doc in tree_docs.items():
        assert doc == deploy_docs[key], f"{key} drifted between config/ and deploy/"


def test_deploy_manifest_is_generated_from_config_tree():
    """The committed deploy file must be exactly what the generator
    renders from config/ (same check CI runs)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "hack/gen_deploy.py", "--check"], capture_output=True
    )
    assert proc.returncode == 0, proc.stderr.decode()


def test_kustomization_resources_resolve():
    """Every kustomization's resource entries must exist on disk — and a
    directory resource must itself be a kustomize base (contain a
    kustomization.yaml), or `kubectl apply -k` fails at install time."""
    kfiles = glob.glob("config/**/kustomization.yaml", recursive=True)
    assert len(kfiles) >= 5  # crd, rbac, manager, default, samples
    for kfile in kfiles:
        base = Path(kfile).parent
        doc = yaml.safe_load(Path(kfile).read_text())
        for res in doc["resources"]:
            target = base / res
            assert target.exists(), f"{kfile}: missing resource {res}"
            if target.is_dir():
                assert (target / "kustomization.yaml").exists(), (
                    f"{kfile}: resource {res} is not a kustomize base"
                )
    default = yaml.safe_load(Path("config/default/kustomization.yaml").read_text())
    assert set(default["resources"]) == {"../crd", "../rbac", "../manager"}


def test_config_sample_healthcheck_validates():
    checks = list(load_healthchecks("config/samples/healthcheck_sample.yaml"))
    assert checks
    wf = parse_workflow_from_healthcheck(checks[0])
    assert wf["kind"] == "Workflow"


def test_config_sample_matches_example():
    """config/samples mirrors examples/inline-hello.yaml (same object,
    kind/name/namespace included) — guard the pair like config↔deploy,
    or they silently diverge and collide on apply."""
    sample = next(
        d
        for d in yaml.safe_load_all(
            Path("config/samples/healthcheck_sample.yaml").read_text()
        )
        if d
    )
    example = next(
        d
        for d in yaml.safe_load_all(Path("examples/inline-hello.yaml").read_text())
        if d
    )
    assert sample == example, "config/samples drifted from examples/inline-hello.yaml"
