"""MXU matmul probe.

Times large bf16 matmuls — the op the systolic array exists for — and
compares the best achieved TFLOP/s against the chip's rated bf16 peak.
A chip delivering well under rated peak on a clean square matmul is
throttled, misconfigured, or sick.

A small dimension sweep, not one size: which dim the compiler tiles
best varies by chip generation (on v5e, 4096 consistently lands nearer
peak than 8192), and the probe's job is to measure what the chip CAN
do — the max over dims is the right health signal, with the per-dim
numbers kept in the details.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds

log = logging.getLogger("activemonitor.probes")


def _measure(dim: int, iters: int, dtype: str = "bf16") -> float:
    """Achieved dense matmul T(FL)OP/s at one dimension. ``dtype`` is
    "bf16" or "int8" (the MXU's two throughput modes on v5e+; int8 runs
    at 2x the bf16 rate on paper and exercises a distinct data path)."""
    if dtype == "int8":
        a = jax.random.randint(jax.random.key(0), (dim, dim), -127, 127, jnp.int8)
        b = jax.random.randint(jax.random.key(1), (dim, dim), -127, 127, jnp.int8)
        # accumulate in int32 (the MXU's int8 contract); the wrap back
        # to int8 keeps the chain data-dependent
        accum, operand = jnp.int32, jnp.int8
    else:
        a = jax.random.normal(jax.random.key(0), (dim, dim), jnp.bfloat16)
        b = jax.random.normal(jax.random.key(1), (dim, dim), jnp.bfloat16)
        accum, operand = jnp.bfloat16, jnp.bfloat16

    def make_chain(k):
        @jax.jit
        def chain(a, b):
            x = b
            for _ in range(k):  # data-dependent: each feeds the next
                x = jnp.dot(a, x, preferred_element_type=accum).astype(operand)
            return x.astype(jnp.float32).sum()

        return chain

    # wide k spread: the delta must tower over per-sample overhead
    # variance, or the min-based estimate can overshoot physically
    # impossible FLOP rates (>1.0 of rated) as easily as undershoot
    seconds = chain_delta_seconds(make_chain, a, b, k1=4, k2=16, iters=iters)
    return 2 * dim**3 / seconds / 1e12


def run(
    dim: Optional[int] = None,
    iters: int = 10,
    threshold: float = 0.75,
    dims: Sequence[int] = (4096, 8192),
    dtype: str = "bf16",
    roofline: bool = True,
) -> ProbeResult:
    if dtype not in ("bf16", "int8"):
        raise ValueError(f"dtype must be bf16 or int8, got {dtype!r}")
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if dim is not None:
        dims = (dim,)  # explicit dim: no sweep (CLI --dim)
    requested_dims = tuple(sorted(set(dims)))
    dims = requested_dims
    if not on_tpu:
        # any large dim is downsized off-TPU (a 4096 bf16 chain takes
        # minutes on CPU and there is no rated comparison there) —
        # loudly, and recorded in the details below, so numbers are
        # never silently compared across the clamp
        dims = tuple(sorted({1024 if d > 2048 else d for d in requested_dims}))
        if dims != requested_dims:
            log.warning(
                "matmul dims %s downsized to %s off-TPU; numbers are NOT "
                "comparable to a TPU run", requested_dims, dims,
            )

    per_dim = {d: _measure(d, iters, dtype=dtype) for d in dims}
    dim, tflops = max(per_dim.items(), key=lambda kv: kv[1])
    seconds = 2 * dim**3 / tflops / 1e12
    unit = "TOP/s" if dtype == "int8" else "TFLOP/s"

    rated = rated_for(device.device_kind)
    if dtype == "int8":
        metrics = [
            ProbeMetric("mxu-int8-matmul-tops", tflops, help="Achieved int8 matmul TOP/s")
        ]
        rated_peak = rated.int8_tops if rated is not None else 0.0
        fraction_name = "mxu-int8-fraction-of-rated"
        fraction_help = "Achieved / rated int8 peak"
    else:
        metrics = [
            ProbeMetric("mxu-matmul-tflops", tflops, help="Achieved bf16 matmul TFLOP/s")
        ]
        rated_peak = rated.bf16_tflops if rated is not None else 0.0
        fraction_name = "mxu-fraction-of-rated"
        fraction_help = "Achieved / rated bf16 peak"
    per_dim_key = "per_dim_tops" if dtype == "int8" else "per_dim_tflops"
    details = {
        "dim": dim,
        "dtype": dtype,
        per_dim_key: {d: round(v, 1) for d, v in per_dim.items()},
        "seconds_per_op": seconds,
        "device_kind": device.device_kind,
    }
    if tuple(dims) != requested_dims:
        details["requested_dims"] = list(requested_dims)  # downsized off-TPU
    # roofline evidence under the fraction (obs/roofline.py): a square
    # matmul sits far right of the ridge, so the verdict should read
    # compute-bound with the ceiling at the flat peak — anything else
    # (or a low fraction) says the MXU itself is sick, not the memory
    # system. XLA's compiled cost is captured over ONE chain op (the
    # dot + the dtype wrap that keeps the chain data-dependent);
    # int8 runs are classified against the int8 roofline.
    if dtype == "int8":
        accum, operand = jnp.int32, jnp.int8
    else:
        accum, operand = jnp.bfloat16, jnp.bfloat16

    def one_op(a, b):
        return jnp.dot(a, b, preferred_element_type=accum).astype(operand)

    itemsize = jnp.dtype(operand).itemsize
    roofline_prefix = "mxu-int8" if dtype == "int8" else "mxu"
    roofline_spec = None
    if rated is not None and rated_peak > 0:
        import dataclasses

        # the generation's peak for THIS throughput mode (int8 is rated
        # 2x bf16 on v5e+, which also doubles the ridge point)
        roofline_spec = dataclasses.replace(rated, bf16_tflops=rated_peak)
    if not roofline:
        roofline_capture = roofline_model.skip_capture(
            roofline_prefix, "disabled (--no-roofline)"
        )
    elif rated is not None and rated_peak <= 0:
        # the generation has no such mode (int8 on v4): there is NO
        # roofline to stand this run on — an explicit skip, because
        # letting capture() fall back to the device spec would judge
        # the int8 kernel against the bf16 ceiling and flag a healthy
        # chip as a confirmed rated degradation
        roofline_capture = roofline_model.skip_capture(
            roofline_prefix,
            f"no rated {dtype} roofline for {rated.generation}",
        )
    else:
        shape = jax.ShapeDtypeStruct((dim, dim), operand)
        roofline_capture = roofline_model.capture(
            roofline_prefix,
            seconds=seconds,
            fn=one_op,
            args=(shape, shape),
            model_flops=2.0 * dim**3,
            model_bytes=3.0 * dim * dim * itemsize,
            spec=roofline_spec,
            enabled=roofline,
        )

    ok = True
    # rated_peak == 0 means the generation has no such mode (int8 on
    # v4): informational pass rather than a division by zero
    if rated is not None and on_tpu and rated_peak > 0:
        fraction = tflops / rated_peak
        metrics.append(ProbeMetric(fraction_name, fraction, help=fraction_help))
        details["rated_tops" if dtype == "int8" else "rated_tflops"] = rated_peak
        details["fraction"] = round(fraction, 3)
        ok = fraction >= threshold
        summary = f"{dtype} matmul {tflops:.0f} {unit} = {fraction:.0%} of rated {rated_peak:.0f}"
    else:
        summary = f"{dtype} matmul {tflops:.2f} {unit} on {device.platform} (no rated comparison)"
    result = ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
    roofline_model.apply(result, roofline_capture)
    return result
