"""Host↔device transfer probe — the data-feed path.

Input pipelines live or die on host→device bandwidth (PCIe on TPU VMs):
a training job whose h2d feed is degraded shows up as idle MXUs that
every other probe scores healthy. This probe measures both directions:

- h2d: ``jax.device_put`` of a host buffer, completion forced by a
  jitted single-element read (any op on the array must wait for the
  full transfer to land — a one-element readback costs nothing while a
  full sum would add an HBM pass to the number);
- d2h: ``np.asarray`` of a device buffer (the bytes arriving in host
  memory cannot lie, tunneled or not).

Fixed per-call overhead (dispatch, tunnel round-trips) is cancelled by
the size-delta method — time a 2x payload and divide the difference —
with the two payloads sampled ALTERNATELY (drift cannot land on one
side of the difference) and the payload grown until the delta towers
over the noise floor: the same discipline utils/timing.py applies to
op chains. A delta still inside the noise after growth is reported as
noise-limited instead of a fabricated bandwidth, and fails any
``--min-gbps`` gate (unmeasurable ≠ certified).

There is no rated denominator (host PCIe topology varies; behind a
remote PJRT tunnel this measures the tunnel, which is then genuinely
the feed path the device has) — gauges are informational, with an
optional ``--min-gbps`` floor for deployments that know their fabric.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def _interleaved_min_pair(fn1, fn2, iters: int, warmup: int = 1) -> tuple:
    """(min t1, min t2) sampled alternately — utils/timing.py's rule:
    phase-separated sampling lets drift (tunnel congestion, host load)
    land entirely on one side of the difference."""
    for _ in range(warmup):
        fn1()
        fn2()
    t1s, t2s = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn1()
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fn2()
        t2s.append(time.perf_counter() - t0)
    return min(t1s), min(t2s)


def _delta_gbps(make_fn, nbytes: int, iters: int, retries: int = 2) -> tuple:
    """(GB/s, payload bytes used, noise_limited) for a transfer
    direction. ``make_fn(nbytes)`` returns a zero-arg callable moving
    that payload. The payload is grown when the 2x-1x delta sits inside
    the noise floor rather than reporting a fabricated rate."""
    from activemonitor_tpu.utils.timing import needs_longer_chain

    for attempt in range(retries + 1):
        t1, t2 = _interleaved_min_pair(
            make_fn(nbytes), make_fn(2 * nbytes), iters
        )
        if not needs_longer_chain(t1, t2):
            return nbytes / (t2 - t1) / 1e9, nbytes, False
        if attempt < retries:
            nbytes *= 4
    return nbytes / max(t2 - t1, 1e-9) / 1e9, nbytes, True


def _make_h2d(device):
    @jax.jit
    def first_element(x):
        return x[0, 0]

    def factory(nbytes: int):
        host = np.ones((nbytes // 4 // 1024, 1024), np.float32)

        def put():
            x = jax.device_put(host, device)
            float(first_element(x))  # forces the whole buffer onto the device

        return put

    return factory


def _make_d2h(device):
    @jax.jit
    def bump(x):
        return x + 1.0

    def factory(nbytes: int):
        x = jax.device_put(
            jnp.ones((nbytes // 4 // 1024, 1024), jnp.float32), device
        )
        x = jax.block_until_ready(bump(x))

        def get():
            # jax.Array caches its host copy after the first np.asarray
            # — reading the SAME array again times the cache, not the
            # wire (observed: "32 PB/s" through a tunnel). Reading a
            # fresh device-computed array per call forces a real
            # transfer; the device-side bump is an HBM-bound op whose
            # cost scales with size, so the size-delta folds it out of
            # the fixed overhead and it only shades the estimate by
            # ~HBM/PCIe-ratio percent.
            np.asarray(bump(x))

        return get

    return factory


def run(
    size_mb: float = 64.0,
    iters: int = 5,
    min_gbps: float = 0.0,
) -> ProbeResult:
    # local device: jax.devices()[0] is non-addressable on processes
    # other than 0 in multi-host runs — each host measures its own feed
    device = jax.local_devices()[0]
    nbytes = int(size_mb * 1e6)
    nbytes -= nbytes % (4 * 1024)

    h2d_gbps, h2d_bytes, h2d_noise = _delta_gbps(_make_h2d(device), nbytes, iters)
    d2h_gbps, d2h_bytes, d2h_noise = _delta_gbps(_make_d2h(device), nbytes, iters)
    noise_limited = h2d_noise or d2h_noise

    metrics = [
        ProbeMetric(
            "transfer-h2d-gbps", h2d_gbps, help="Host-to-device bandwidth, GB/s"
        ),
        ProbeMetric(
            "transfer-d2h-gbps", d2h_gbps, help="Device-to-host bandwidth, GB/s"
        ),
    ]
    details = {
        "h2d_payload_mb": h2d_bytes / 1e6,
        "d2h_payload_mb": d2h_bytes / 1e6,
        "device_kind": device.device_kind,
        "platform": device.platform,
    }
    if noise_limited:
        details["noise_limited"] = sorted(
            d for d, n in (("h2d", h2d_noise), ("d2h", d2h_noise)) if n
        )
    ok = True
    if min_gbps > 0:
        # a noise-limited reading cannot certify the floor — fail closed
        ok = (
            not noise_limited
            and h2d_gbps >= min_gbps
            and d2h_gbps >= min_gbps
        )
        details["min_gbps"] = min_gbps
    summary = (
        f"h2d {h2d_gbps:.2f} GB/s, d2h {d2h_gbps:.2f} GB/s"
        + (f" (floor {min_gbps:.1f})" if min_gbps > 0 else "")
        + (" [noise-limited]" if noise_limited else "")
    )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
