"""Paged KV cache — fixed-size blocks with per-sequence block tables.

The serving runtime's memory system (ROADMAP item 5). Static-batch
decode gives every sequence a contiguous ``[B, S]`` cache slab sized
for the worst case, so admission is all-or-nothing and the slack in
short sequences is dead HBM. Continuous batching instead pools K/V in
fixed-size BLOCKS: a sequence owns an ordered block table, admission is
a free-list question, retirement returns blocks for immediate reuse,
and the only waste is the measurable slack inside each sequence's last
partially-filled block (the vLLM PagedAttention idea, sized for the
probe model).

Three layers, same file so the layout story has one home:

- :class:`KVBlockManager` — the pure-Python allocator: free list,
  per-sequence block tables, allocate/append/free, and EXPLICIT
  fragmentation accounting (:meth:`~KVBlockManager.fragmentation_ratio`
  — reserved-but-unwritten slots over reserved slots). Deficits are
  structured refusals (``None``/``False``), never exceptions: the
  admission scheduler turns them into queueing decisions, and an
  out-of-blocks storm must not crash the serving loop.
- the jax storage — :func:`init_paged_kv` allocates
  ``[n_layers, n_blocks, kv_heads, block_size, head_dim]`` pools whose
  layout is expressed as PARTITION RULES (:func:`kv_partition_rules`)
  resolved through ``parallel/partition.py`` like every other op:
  kv heads shard over the tensor-parallel axis, the block pool is
  replicated, re-meshing is an edit to a rules tuple, a rule naming an
  axis the mesh lacks raises up front, and scalar leaves never
  partition.
- the compute — :func:`bank_prompt` scatters a prefilled sequence's
  K/V into its blocks; :func:`paged_decode_step` is ``decode_step``'s
  paged sibling: per-sequence positions (a continuous batch has no
  single scalar ``pos``), K/V gathered through the block tables, new
  K/V scattered to each sequence's (block, offset). The serving probe
  pins its logits against the static per-sequence path — the two
  implementations must not drift.

Slot-padding convention for fixed-shape batches: callers reserve one
block index OUTSIDE the manager's pool as a trash block (the serving
engine allocates ``n_blocks + 1`` storage blocks and points every
inactive slot's table at the last one), so inactive batch slots scatter
into garbage no live sequence reads instead of corrupting block 0.

No wall-clock reads here (``hack/lint.py`` bans them: the manager's
whole state is allocation arithmetic and the compute is pure) — any
timing belongs to the caller's injectable timer.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.models.probe_model import ProbeModelConfig, _rmsnorm


def kv_bytes_per_token(cfg: ProbeModelConfig) -> float:
    """HBM bytes one generated token ADDS to the cache (K and V, every
    layer) — the single bytes-per-token figure both the static decode
    probe (``decode-kv-bytes-per-token``) and the serving probe's
    memory-bound ceiling derive from, so the two roofline inputs cannot
    drift apart."""
    return float(
        2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )


# ---------------------------------------------------------------------
# the allocator (pure Python — no jax, no clock)
# ---------------------------------------------------------------------


class KVBlockManager:
    """Free-list block allocator with per-sequence block tables.

    Capacity is reserved whole at :meth:`allocate` (admission time) and
    consumed by :meth:`append` as tokens bank their K/V — so a sequence
    admitted under the block budget can never hit a mid-flight
    out-of-memory; the only refusal point is admission itself, where
    the scheduler can queue. Freed blocks return to the free list LIFO,
    so a retirement's blocks are the very next admission's grant
    (locality + a deterministic reuse order tests can pin).
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need n_blocks >= 1 and block_size >= 1, got "
                f"{n_blocks}/{block_size}"
            )
        self.n_blocks = n_blocks
        self.block_size = block_size
        # stack: pop() grants from the END, so seed it reversed (first
        # grant is block 0) and append frees for LIFO reuse
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        self._lengths: Dict[int, int] = {}  # tokens appended (banked K/V)
        # structured-refusal counters (ISSUE 20 small fix): the silent
        # return-value contracts below stay — the scheduler depends on
        # them — but each refusal now lands in a named bucket that
        # stats() surfaces, so a migration bug that frees a handed-off
        # sequence twice or appends past its reservation is attributable
        # instead of a quietly ignored no-op
        self.refusal_counts: Dict[str, int] = {
            "free_unknown_seq": 0,
            "append_unknown_seq": 0,
            "append_over_capacity": 0,
        }

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` K/V entries."""
        return -(-max(0, n_tokens) // self.block_size)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def banked_tokens(self) -> int:
        """Total K/V entries written across live sequences — the live
        cache footprint the serving roofline's bytes model reads."""
        return sum(self._lengths.values())

    def can_allocate(self, capacity_tokens: int) -> bool:
        return self.blocks_for(capacity_tokens) <= len(self._free)

    def allocate(self, seq_id: int, capacity_tokens: int) -> Optional[List[int]]:
        """Reserve blocks for a sequence's full K/V capacity. Returns
        the granted block table, or ``None`` when the free list cannot
        cover it — the structured admission refusal, never a raise.
        Re-allocating a live sequence id IS a raise: that is a caller
        bug, not a capacity condition."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already holds blocks")
        need = self.blocks_for(capacity_tokens)
        if need > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = blocks
        self._lengths[seq_id] = 0
        return list(blocks)

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def length(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def append(self, seq_id: int, n_tokens: int = 1) -> bool:
        """Advance a sequence's banked-token count. ``False`` (the
        structured refusal) when the reserved capacity cannot hold the
        new tokens — the caller under-reserved at admission."""
        if seq_id not in self._tables:
            self.refusal_counts["append_unknown_seq"] += 1
            return False
        capacity = len(self._tables[seq_id]) * self.block_size
        if self._lengths[seq_id] + n_tokens > capacity:
            self.refusal_counts["append_over_capacity"] += 1
            return False
        self._lengths[seq_id] += n_tokens
        return True

    def free(self, seq_id: int) -> int:
        """Return a retired sequence's blocks to the free list (LIFO —
        the next allocation reuses them first). Returns the number of
        blocks released; freeing an unknown id is 0, not a raise (but
        the refusal is counted — a double-free is a migration bug)."""
        blocks = self._tables.pop(seq_id, None)
        if blocks is None:
            self.refusal_counts["free_unknown_seq"] += 1
            return 0
        del self._lengths[seq_id]
        self._free.extend(blocks)
        return len(blocks)

    def transfer_prefix(self, seq_id: int, n_blocks: int, owner_id: int) -> List[int]:
        """Move the first ``n_blocks`` FULL blocks of ``seq_id``'s
        table — banked tokens included — to ``owner_id`` (a
        :class:`PrefixCache` entry's pseudo-sequence). Ownership
        bookkeeping only: no block ids change and no K/V moves, so a
        sequence reading through ``[shared..., private...]`` tables sees
        identical storage before and after. The transferred blocks must
        be fully banked (a partially-written block has no stable
        content hash to share under). Caller bugs here ARE raises:
        this is cache plumbing, not a capacity condition."""
        if seq_id not in self._tables:
            raise KeyError(f"sequence {seq_id} holds no blocks")
        if owner_id in self._tables:
            raise ValueError(f"owner {owner_id} already holds blocks")
        if n_blocks < 1 or n_blocks > len(self._tables[seq_id]):
            raise ValueError(
                f"cannot transfer {n_blocks} of "
                f"{len(self._tables[seq_id])} blocks"
            )
        moved_tokens = n_blocks * self.block_size
        if self._lengths[seq_id] < moved_tokens:
            raise ValueError(
                f"prefix blocks not fully banked: {self._lengths[seq_id]} "
                f"tokens over {n_blocks} blocks of {self.block_size}"
            )
        moved = self._tables[seq_id][:n_blocks]
        self._tables[seq_id] = self._tables[seq_id][n_blocks:]
        self._lengths[seq_id] -= moved_tokens
        self._tables[owner_id] = list(moved)
        self._lengths[owner_id] = moved_tokens
        return list(moved)

    def fragmentation_ratio(self) -> float:
        """Reserved-but-unwritten K/V slots over all reserved slots —
        the explicit fragmentation account: block-granular reservation
        means every sequence carries up to ``block_size - 1`` slack
        slots plus whatever capacity it reserved but has not banked
        yet. 0.0 with nothing allocated (no reservation, no waste)."""
        reserved = self.used_blocks * self.block_size
        if reserved == 0:
            return 0.0
        used = sum(self._lengths.values())
        return (reserved - used) / reserved

    def stats(self) -> dict:
        return {
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "free_blocks": self.free_blocks,
            "used_blocks": self.used_blocks,
            "sequences": len(self._tables),
            "fragmentation_ratio": self.fragmentation_ratio(),
            "refusals": dict(self.refusal_counts),
        }


# ---------------------------------------------------------------------
# content-addressed prefix cache (pure Python — no jax, no clock)
# ---------------------------------------------------------------------


class _PrefixEntry:
    """One cached full block of shared prompt K/V."""

    __slots__ = ("key", "owner_id", "block", "refcount", "last_used")

    def __init__(self, key: str, owner_id: int, block: int, tick: int):
        self.key = key
        self.owner_id = owner_id  # the manager pseudo-sequence holding it
        self.block = block
        self.refcount = 0
        self.last_used = tick


class PrefixCache:
    """Content-addressed, ref-counted index over shared prompt blocks.

    The KV analog of the front door's request coalescing (ISSUE 20):
    prompt-token prefixes hash at BLOCK granularity — entry *i*'s key
    is the chained hash of tokens ``[0, (i+1)·block_size)`` — so a hot
    shared system prompt banks once and every later sequence opening
    with the same tokens reads the same blocks. Same tokens ⇒ same
    model ⇒ bitwise-identical K/V, which is why sharing is safe and
    the serving consistency gate covers it for free.

    Ownership: cached blocks live in the SAME :class:`KVBlockManager`
    pool as live sequences, held under negative pseudo-sequence ids
    (one per entry, so eviction is one ``free``). A sequence's
    effective block table is ``held_blocks(rid) + manager.table(rid)``
    — the shared prefix in acquisition order, then its private tail.

    Safety contract (the satellite's property tests): an entry is
    evictable ONLY at refcount zero, eviction order is LRU over a
    logical tick (no wall clock here — hack/lint.py bans it), and
    entries free through their own pseudo-id exactly once (the
    manager's ``free_unknown_seq`` counter is the double-free
    tripwire).

    Conservation ledger, exact per tenant: every admitted prompt books
    ``prompt_tokens == prefix_hits + prefill_tokens`` — hits counted at
    :meth:`acquire` (event time), prefill counted at :meth:`publish`
    (when the caller reports the remainder actually prefilled) — two
    independent accounts the ledger cross-checks, the same discipline
    as the scheduler's per-tenant tallies.
    """

    def __init__(self, manager: KVBlockManager, max_entries: Optional[int] = None):
        self.manager = manager
        self.block_size = manager.block_size
        self.max_entries = max_entries
        self._entries: "OrderedDict[str, _PrefixEntry]" = OrderedDict()
        self._held: Dict[int, List[str]] = {}  # rid -> entry keys (in order)
        self._tick = 0  # logical LRU clock
        self._next_owner = -1  # pseudo-sequence ids count down from -1
        self.counters: Dict[str, int] = {
            "hits": 0,  # block-granular lookups served from the index
            "misses": 0,  # block-granular lookups that fell through
            "inserted": 0,  # blocks published into the index
            "evictions": 0,  # zero-ref blocks reclaimed (LRU)
            "hit_tokens": 0,  # prompt tokens NOT re-prefilled
        }
        self._tenants: Dict[str, Dict[str, int]] = {}

    # -- hashing -------------------------------------------------------
    @staticmethod
    def chain_key(prev: str, block_tokens: Sequence[int]) -> str:
        """The content address of one more full block: hash of the
        previous block's key plus this block's token ids — O(1) per
        block, and equal prefixes get equal chains by induction."""
        payload = prev + ":" + ",".join(str(int(t)) for t in block_tokens)
        return hashlib.sha256(payload.encode()).hexdigest()

    def _keys_for(self, tokens: Sequence[int]) -> List[str]:
        keys: List[str] = []
        prev = ""
        for i in range(len(tokens) // self.block_size):
            block = tokens[i * self.block_size : (i + 1) * self.block_size]
            prev = self.chain_key(prev, block)
            keys.append(prev)
        return keys

    # -- queries -------------------------------------------------------
    def lookup(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Pure read: (shared block ids, hit token count) for the
        longest cached full-block prefix of ``tokens``. Takes no refs,
        books no ledger — admission uses it to size the private
        reservation BEFORE committing."""
        blocks: List[int] = []
        for key in self._keys_for(tokens):
            entry = self._entries.get(key)
            if entry is None:
                break
            blocks.append(entry.block)
        return blocks, len(blocks) * self.block_size

    @property
    def entries(self) -> int:
        return len(self._entries)

    def held_blocks(self, rid: int) -> List[int]:
        """The shared blocks sequence ``rid`` holds refs on, in prompt
        order — the front of its effective block table."""
        return [self._entries[k].block for k in self._held.get(rid, [])]

    def refcount(self, tokens: Sequence[int]) -> List[int]:
        """Refcounts along ``tokens``' cached prefix (tests/debugging)."""
        out = []
        for key in self._keys_for(tokens):
            entry = self._entries.get(key)
            if entry is None:
                break
            out.append(entry.refcount)
        return out

    # -- the acquire / publish / release lifecycle ---------------------
    def _tenant_row(self, tenant: str) -> Dict[str, int]:
        return self._tenants.setdefault(
            tenant, {"prompt_tokens": 0, "prefix_hits": 0, "prefill_tokens": 0}
        )

    def acquire(self, rid: int, tenant: str, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Take refs on ``tokens``' cached prefix for sequence ``rid``
        and book the per-tenant ledger's admission side. Returns
        (shared block ids, hit tokens); the caller reserves and
        prefills only ``len(tokens) - hit`` privately."""
        if rid in self._held:
            raise ValueError(f"sequence {rid} already holds prefix refs")
        self._tick += 1
        keys = self._keys_for(tokens)
        held: List[str] = []
        for key in keys:
            entry = self._entries.get(key)
            if entry is None:
                break
            entry.refcount += 1
            entry.last_used = self._tick
            self._entries.move_to_end(key)
            held.append(key)
        self._held[rid] = held
        hit_tokens = len(held) * self.block_size
        self.counters["hits"] += len(held)
        self.counters["misses"] += len(keys) - len(held)
        self.counters["hit_tokens"] += hit_tokens
        row = self._tenant_row(tenant)
        row["prompt_tokens"] += len(tokens)
        row["prefix_hits"] += hit_tokens
        return [self._entries[k].block for k in held], hit_tokens

    def publish(self, rid: int, tenant: str, tokens: Sequence[int]) -> int:
        """The caller prefilled ``rid``'s non-shared remainder: book
        the ledger's prefill side and adopt the newly banked FULL
        blocks into the index (ownership transfer out of the
        sequence's table — no data moves, the ids are unchanged, so
        the sequence's effective table is stable). Partial tail blocks
        stay private. Returns the number of blocks published."""
        held = self._held.get(rid)
        if held is None:
            raise ValueError(f"sequence {rid} never acquired (admission bug)")
        keys = self._keys_for(tokens)
        hit_tokens = len(held) * self.block_size
        row = self._tenant_row(tenant)
        row["prefill_tokens"] += len(tokens) - hit_tokens
        published = 0
        self._tick += 1
        for key in keys[len(held) :]:
            if key in self._entries:
                # a concurrent admission published the same content
                # first — share it? No: this sequence's OWN copy stays
                # private (its table already points there); adopting a
                # duplicate would strand the existing entry's block.
                break
            owner = self._next_owner
            self._next_owner -= 1
            moved = self.manager.transfer_prefix(rid, 1, owner)
            entry = _PrefixEntry(key, owner, moved[0], self._tick)
            entry.refcount = 1  # held by rid until release
            self._entries[key] = entry
            held.append(key)
            published += 1
            self.counters["inserted"] += 1
        if self.max_entries is not None:
            overflow = len(self._entries) - self.max_entries
            if overflow > 0:
                self.evict(blocks_needed=overflow)
        return published

    def release(self, rid: int) -> int:
        """Sequence ``rid`` left the prefill pool (migrated or
        retired): drop its refs. Entries stay cached at refcount zero
        — that is the whole point — until LRU eviction needs the
        blocks. Unknown/double release is a counted no-op (0), the
        same structured-refusal posture as the manager."""
        held = self._held.pop(rid, None)
        if held is None:
            return 0
        for key in held:
            self._entries[key].refcount -= 1
        return len(held)

    def evict(self, blocks_needed: int = 1) -> int:
        """Reclaim up to ``blocks_needed`` blocks from LRU entries at
        refcount ZERO (a live shared block is never evicted). Returns
        blocks actually freed — the caller retries its allocation and
        takes the structured refusal if the cache could not help."""
        freed = 0
        while freed < blocks_needed:
            victim = None
            for key, entry in self._entries.items():  # oldest-first
                if entry.refcount == 0:
                    victim = key
                    break
            if victim is None:
                break
            entry = self._entries.pop(victim)
            freed += self.manager.free(entry.owner_id)
            self.counters["evictions"] += 1
        return freed

    # -- accounting ----------------------------------------------------
    def ledger(self) -> dict:
        """The per-tenant conservation ledger: ``prompt_tokens ==
        prefix_hits + prefill_tokens`` EXACT for every tenant (hits
        booked at acquire, prefill at publish — two event-time
        accounts), plus the global counters. ``ok`` gates the serving
        probe exactly like the scheduler's conservation bit."""
        tenants_ok = all(
            row["prompt_tokens"] == row["prefix_hits"] + row["prefill_tokens"]
            for row in self._tenants.values()
        )
        return {
            "tenants": {t: dict(r) for t, r in sorted(self._tenants.items())},
            "counters": dict(self.counters),
            "entries": len(self._entries),
            "live_refs": sum(e.refcount for e in self._entries.values()),
            "ok": tenants_ok,
        }

    def stats(self) -> dict:
        lookups = self.counters["hits"] + self.counters["misses"]
        return {
            "entries": len(self._entries),
            "shared_blocks": len(self._entries),
            "live_refs": sum(e.refcount for e in self._entries.values()),
            "hit_ratio": self.counters["hits"] / lookups if lookups else 0.0,
            "counters": dict(self.counters),
        }


# ---------------------------------------------------------------------
# the storage + its partition rules
# ---------------------------------------------------------------------


def init_paged_kv(
    cfg: ProbeModelConfig, n_blocks: int, block_size: int
) -> Dict[str, jax.Array]:
    """The pooled K/V storage: ``[L, n_blocks, Hkv, block_size, Dh]``
    per tensor, compute-dtyped. Block-major so one sequence's gather is
    a take along dim 1; heads on dim 2 so the tensor-parallel shard is
    whole kv heads (the same GQA memory story as ``init_kv_cache``)."""
    shape = (cfg.n_layers, n_blocks, cfg.kv_heads, block_size, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def kv_partition_rules(tp_axis: str = "model"):
    """The paged-cache layout as DATA (parallel/partition.py): kv heads
    shard over ``tp_axis`` — each shard owns whole heads of every block
    — and the block pool itself is replicated across the axis, the same
    megatron split the probe model's attention weights use. Re-meshing
    the cache is an edit to this tuple, never to the compute."""
    return ((r"^k$|^v$", P(None, None, tp_axis, None, None)),)


def paged_kv_specs(
    cfg: ProbeModelConfig,
    n_blocks: int,
    block_size: int,
    tp_axis: str = "model",
    mesh: Optional[Mesh] = None,
):
    """The rules resolved over the abstract storage tree. Passing
    ``mesh`` validates up front: a rules tuple naming an axis the mesh
    does not carry is a ValueError here, never a tracer crash inside
    the serving loop — and scalar leaves resolve to ``P()`` like
    everywhere else."""
    from activemonitor_tpu.parallel.partition import match_partition_rules

    abstract = jax.eval_shape(lambda: init_paged_kv(cfg, n_blocks, block_size))
    return match_partition_rules(
        kv_partition_rules(tp_axis), abstract, mesh=mesh
    )


def shard_paged_kv(
    storage: Dict[str, jax.Array],
    cfg: ProbeModelConfig,
    mesh: Mesh,
    tp_axis: str = "model",
):
    """Place the storage on its resolved shardings (validated). Returns
    the sharded tree; the specs come from the same rules tuple, so a
    wrong layout raises before any device_put."""
    from activemonitor_tpu.parallel.partition import make_shard_fns

    n_blocks, block_size = storage["k"].shape[1], storage["k"].shape[3]
    specs = paged_kv_specs(cfg, n_blocks, block_size, tp_axis, mesh=mesh)
    fns = make_shard_fns(specs, mesh)
    return jax.tree.map(lambda fn, x: fn(x), fns, storage)


# ---------------------------------------------------------------------
# the compute: bank a prefilled prompt, step a continuous batch
# ---------------------------------------------------------------------


def bank_prompt(
    storage: Dict[str, jax.Array],
    prompt_k: jax.Array,
    prompt_v: jax.Array,
    blocks: jax.Array,
) -> Dict[str, jax.Array]:
    """Scatter one prefilled sequence's K/V (``[L, Hkv, S, Dh]``,
    heads-major like the contiguous cache) into its block table. The
    tail of the last block stays zero — inert slack the position mask
    never exposes, and exactly what the fragmentation ratio counts."""
    n_layers, heads, seq, head_dim = prompt_k.shape
    blocks = jnp.asarray(blocks, jnp.int32)
    block_size = storage["k"].shape[3]
    cap = int(blocks.shape[0]) * block_size
    pad = [(0, 0), (0, 0), (0, cap - seq), (0, 0)]

    def blocked(x: jax.Array) -> jax.Array:
        x = jnp.pad(x, pad)  # [L, Hkv, cap, Dh]
        x = x.reshape(n_layers, heads, blocks.shape[0], block_size, head_dim)
        return jnp.moveaxis(x, 1, 2)  # [L, n_blk, Hkv, bs, Dh]

    return {
        "k": storage["k"].at[:, blocks].set(blocked(prompt_k)),
        "v": storage["v"].at[:, blocks].set(blocked(prompt_v)),
    }


def migrate_blocks(
    src: Dict[str, jax.Array],
    dst: Dict[str, jax.Array],
    src_blocks: jax.Array,
    dst_blocks: jax.Array,
) -> Dict[str, jax.Array]:
    """Copy one sequence's banked K/V blocks from the prefill pool's
    storage into the decode pool's — the data half of the KV handoff
    (ISSUE 20). A pure gather/scatter along the block dim: the source
    table may interleave shared prefix-cache blocks with private ones
    (the copy private-izes them on the decode side — decode pools do
    not share), and block contents move verbatim, so the consistency
    gate's logit check spans the pool boundary. Transfer COST is the
    migration channel's α/B model (scheduler/pools.py), not measured
    here — on one host this is a memcpy; the model prices the ICI/DCN
    wire."""
    src_blocks = jnp.asarray(src_blocks, jnp.int32)
    dst_blocks = jnp.asarray(dst_blocks, jnp.int32)
    if src_blocks.shape != dst_blocks.shape:
        raise ValueError(
            f"block table shapes differ: {src_blocks.shape} vs "
            f"{dst_blocks.shape} — a handoff must map 1:1"
        )
    return {
        "k": dst["k"].at[:, dst_blocks].set(src["k"][:, src_blocks]),
        "v": dst["v"].at[:, dst_blocks].set(src["v"][:, src_blocks]),
    }


def paged_decode_step(
    params: Dict,
    storage: Dict[str, jax.Array],
    token: jax.Array,
    pos: jax.Array,
    block_tables: jax.Array,
    cfg: ProbeModelConfig,
):
    """One decode step over a continuous batch of paged sequences.

    ``token``: ``[B]`` int32; ``pos``: ``[B]`` int32 — each sequence's
    own write position (a continuous batch has no shared scalar pos);
    ``block_tables``: ``[B, max_blocks]`` int32, inactive slots padded
    with a trash block id (module docstring). Returns
    ``(logits [B, V], storage)``. Static shapes throughout: the batch
    width and table width are fixed, so the step jits once and reruns
    for the whole soak — the same contract as ``decode_step``, whose
    per-position math this must match within numeric tolerance (the
    serving probe's correctness gate)."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[token]  # [B, D]
    batch = token.shape[0]
    block_size = storage["k"].shape[3]
    cap = block_tables.shape[1] * block_size
    visible = jnp.arange(cap)[None, :] <= pos[:, None]  # [B, S]
    group = cfg.n_heads // cfg.kv_heads
    write_block = jnp.take_along_axis(
        block_tables, (pos // block_size)[:, None], axis=1
    )[:, 0]  # [B]
    offset = pos % block_size  # [B]
    for li, layer in enumerate(params["layers"]):
        h = _rmsnorm(x, layer["ln1"]["scale"])
        if "wqkv" in layer:
            qkv = jnp.einsum("bd,dthk->tbhk", h, layer["wqkv"].astype(dt))
            q, k_new, v_new = qkv[0], qkv[1], qkv[2]  # [B, H, K]
        else:  # GQA: q over n_heads, k/v over the narrower kv_heads
            q = jnp.einsum("bd,dhk->bhk", h, layer["wq"].astype(dt))
            kv = jnp.einsum("bd,dthk->tbhk", h, layer["wkv"].astype(dt))
            k_new, v_new = kv[0], kv[1]  # [B, Hkv, K]
        # scatter each sequence's new K/V to its own (block, offset)
        storage["k"] = storage["k"].at[li, write_block, :, offset].set(k_new)
        storage["v"] = storage["v"].at[li, write_block, :, offset].set(v_new)
        # gather the batch's caches through the block tables:
        # [B, n_blk, Hkv, bs, Dh] -> heads-major contiguous [B, Hkv, S, Dh]
        keys = jnp.moveaxis(storage["k"][li][block_tables], 2, 1).reshape(
            batch, cfg.kv_heads, cap, cfg.head_dim
        )
        values = jnp.moveaxis(storage["v"][li][block_tables], 2, 1).reshape(
            batch, cfg.kv_heads, cap, cfg.head_dim
        )
        qg = q.reshape(batch, cfg.kv_heads, group, cfg.head_dim)
        scores = jnp.einsum("bhgk,bhsk->bhgs", qg, keys) / jnp.sqrt(
            jnp.asarray(cfg.head_dim, dt)
        )
        scores = jnp.where(
            visible[:, None, None, :], scores, jnp.asarray(-1e9, dt)
        )
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        attn = jnp.einsum("bhgs,bhsk->bhgk", probs, values).reshape(
            batch, cfg.n_heads, cfg.head_dim
        )
        x = x + jnp.einsum("bhk,hkd->bd", attn, layer["wo"].astype(dt))
        h = _rmsnorm(x, layer["ln2"]["scale"])
        up = jax.nn.gelu(jnp.einsum("bd,df->bf", h, layer["w_up"].astype(dt)))
        x = x + jnp.einsum("bf,fd->bd", up, layer["w_down"].astype(dt))
    x = _rmsnorm(x, params["final_ln"]["scale"])
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(dt))
    return logits.astype(jnp.float32), storage
