"""Request-coalescing cache keyed (check identity, freshness window).

The goodput lever of the front door (PAPERS.md: *ML Productivity
Goodput* — every deduplicated run is measurement capacity returned to
real work; FlowMesh calls the same move request coalescing): N tenants
asking "is slice X healthy?" inside one freshness window share ONE
probe run. Three outcomes per lookup:

- **hit** — the check's result ring (:class:`~activemonitor_tpu.obs.
  history.ResultHistory`) holds a result younger than the freshness
  window: served immediately, no run, no queue.
- **in-flight join** — a run for the check is already in flight
  (triggered by an earlier front-door request OR by the check's own
  schedule — the watch path's run coalesces front-door traffic too):
  the request fans IN onto it and fans back OUT on completion.
- **miss** — neither: the caller triggers exactly one run and becomes
  the in-flight entry every duplicate joins.

Fan-out rides the history's record-time subscription: the reconciler
records the run's :class:`CheckResult` (status write path — the single
place every run converges, including synthesized timeouts, so a hung
engine can never strand waiters forever), and every waiter's future
resolves with that SAME result object — same ``trace_id``, so the N
fanned-out responses are joinable to the one underlying reconcile
cycle at ``/debug/traces``.

State is single-owner on the event loop (the same discipline as the
manager's queue sets): lookup → begin has no await point, so a
duplicate can never slip between them. All freshness math runs on the
injected Clock — the SAME clock the history stamps results with —
and ``hack/lint.py`` bans wall-clock reads here.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.obs.history import CheckResult, ResultHistory
from activemonitor_tpu.utils.clock import Clock

# a cached result younger than this many seconds satisfies a request
# that didn't name its own freshness window
DEFAULT_FRESHNESS_SECONDS = 30.0

LOOKUP_HIT = "hit"
LOOKUP_INFLIGHT = "inflight"
LOOKUP_MISS = "miss"


@dataclass
class InFlightRun:
    """One probe run in flight with every request fanned in on it."""

    key: str
    started: float  # clock.monotonic() at begin
    waiters: List[asyncio.Future] = field(default_factory=list)

    def join(self) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.append(fut)
        return fut


class CoalescingCache:
    """Freshness-window lookups over the result rings plus the
    in-flight fan-in/fan-out registry."""

    def __init__(
        self,
        history: ResultHistory,
        *,
        clock: Optional[Clock] = None,
        default_freshness: float = DEFAULT_FRESHNESS_SECONDS,
    ):
        self.history = history
        self.clock = clock or Clock()
        self.default_freshness = max(0.0, float(default_freshness))
        # degraded-mode ceiling (resilience/adapt.py): while the
        # adaptive loop confirms a control-plane burn, the effective
        # staleness ceiling stretches ABOVE the operator default so
        # cached answers absorb demand. None = normal mode. Always >=
        # default_freshness — the two-ceiling rule widens, never
        # narrows, so the documented "per-request windows only narrow"
        # contract stays honest in both modes.
        self.degraded_ceiling: Optional[float] = None
        self._inflight: Dict[str, InFlightRun] = {}
        # running fan-in count, so waiter_count() is O(1) on the
        # submit hot path instead of a walk over every in-flight run
        self._waiters = 0
        history.subscribe(self._on_result)

    # -- freshness ceilings ----------------------------------------------
    def set_degraded_ceiling(self, ceiling: Optional[float]) -> None:
        """Engage (or with None, release) the degraded-mode staleness
        ceiling. Clamped up to the operator default: a degraded ceiling
        below it would turn the widening lever into a narrowing one."""
        if ceiling is None:
            self.degraded_ceiling = None
        else:
            self.degraded_ceiling = max(self.default_freshness, float(ceiling))

    def freshness_ceiling(self) -> float:
        """The staleness ceiling currently in force: the degraded-mode
        ceiling while engaged, else the operator default."""
        if self.degraded_ceiling is not None:
            return self.degraded_ceiling
        return self.default_freshness

    def clamp(self, freshness: Optional[float]) -> dict:
        """The two-ceiling freshness rule, as a structured decision the
        ledger can surface instead of a silent narrow. A per-request
        window may only NARROW the ceiling in force (the documented
        contract: the ceiling is the staleness bound — a request asking
        for a wider window clamps down to it); which ceiling is in
        force depends on degraded mode. Returns ``window`` (the
        effective seconds), ``asked`` (the request's own window or
        None), ``ceiling``, ``mode`` (``"degraded"``/``"default"``) and
        ``clamped`` (True when the request asked for more staleness
        than the ceiling allows)."""
        ceiling = self.freshness_ceiling()
        mode = "degraded" if self.degraded_ceiling is not None else "default"
        if freshness is None:
            window = ceiling
            clamped = False
        else:
            asked = float(freshness)
            window = min(asked, ceiling)
            clamped = asked > ceiling
        return {
            "window": window,
            "asked": freshness,
            "ceiling": ceiling,
            "mode": mode,
            "clamped": clamped,
        }

    # -- lookups ---------------------------------------------------------
    def fresh_result(
        self, key: str, freshness: Optional[float] = None
    ) -> Optional[CheckResult]:
        """The check's newest recorded result if it is younger than the
        effective freshness window (:meth:`clamp`), else None.
        Freshness is judged on the SAME clock the history stamped the
        result with, so fake-clock tests script exact expiry edges."""
        window = self.clamp(freshness)["window"]
        last = self.history.last(key)
        if last is None or window <= 0:
            return None
        age = (self.clock.now() - last.ts).total_seconds()
        return last if age < window else None

    def lookup(
        self, key: str, freshness: Optional[float] = None
    ) -> Tuple[str, Optional[CheckResult]]:
        """(outcome, fresh result|None): ``hit`` beats ``inflight``
        beats ``miss`` — a fresh-enough result serves even while a
        newer run is in flight (the requester asked for freshness, not
        for the newest possible answer; that tradeoff is the documented
        coalescing-vs-staleness contract in docs/operations.md)."""
        fresh = self.fresh_result(key, freshness)
        if fresh is not None:
            return LOOKUP_HIT, fresh
        if key in self._inflight:
            return LOOKUP_INFLIGHT, None
        return LOOKUP_MISS, None

    # -- in-flight registry ----------------------------------------------
    def begin(self, key: str) -> InFlightRun:
        """Register the one in-flight run every duplicate joins. The
        caller triggers the actual probe; begin() only claims the slot
        (a second begin for a live key is a programming error — the
        service always looks up first, with no await in between)."""
        if key in self._inflight:
            raise RuntimeError(f"run already in flight for {key}")
        run = InFlightRun(key=key, started=self.clock.monotonic())
        self._inflight[key] = run
        return run

    def join(self, key: str) -> asyncio.Future:
        """Fan a request in on the in-flight run (the triggering
        request itself joins its own run the same way)."""
        run = self._inflight.get(key)
        if run is None:
            raise KeyError(f"no run in flight for {key}")
        self._waiters += 1
        return run.join()

    def inflight_keys(self) -> List[str]:
        return list(self._inflight)

    def stale_inflight(self, cutoff_monotonic: float) -> List[str]:
        """Keys whose run has been in flight since before ``cutoff``
        (the reap sweep's candidates)."""
        return [
            key
            for key, run in self._inflight.items()
            if run.started < cutoff_monotonic
        ]

    def waiter_count(self) -> int:
        """Requests currently fanned in on in-flight runs (O(1))."""
        return self._waiters

    def forget(self, key: str) -> None:
        """Drop a deleted check's in-flight entry; waiters are cancelled
        (the check is gone — there is no result to fan out)."""
        run = self._inflight.pop(key, None)
        if run is not None:
            self._waiters -= len(run.waiters)
            for fut in run.waiters:
                if not fut.done():
                    fut.cancel()

    # -- fan-out ---------------------------------------------------------
    def _on_result(self, key: str, result: CheckResult) -> None:
        """History recorded a run for ``key``: resolve every fanned-in
        waiter with the SAME result (shared trace_id) and retire the
        in-flight entry. Runs the reconciler's own record call, so it
        must never raise (the subscribe contract) and never block."""
        run = self._inflight.pop(key, None)
        if run is None:
            return
        self._waiters -= len(run.waiters)
        for fut in run.waiters:
            if not fut.done():
                fut.set_result(result)
