"""Pipeline-parallelism tests on the 8-stage CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    apply_block,
    init_params,
)
from activemonitor_tpu.ops.pipeline import pipeline_forward_blocks, stack_layer_params
from activemonitor_tpu.parallel.mesh import make_1d_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = ProbeModelConfig(
        vocab_size=64,
        d_model=32,
        n_heads=2,
        n_layers=8,
        d_ff=64,
        max_seq_len=32,
        dtype=jnp.float32,  # exact comparison; bf16 differs by summation order
    )
    params = init_params(jax.random.key(0), cfg)
    mesh = make_1d_mesh("pp")
    x = jax.random.normal(jax.random.key(1), (8, 16, cfg.d_model), jnp.float32)
    ref = x
    for layer in params["layers"]:
        ref = apply_block(ref, layer, cfg)
    return cfg, params, mesh, x, ref


@pytest.mark.parametrize("microbatches", [2, 4, 8])
def test_pipeline_matches_dense(setup, microbatches):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    got = pipeline_forward_blocks(
        stacked, x, cfg, mesh, "pp", num_microbatches=microbatches
    )
    assert jnp.max(jnp.abs(got - ref)) < 1e-4


@pytest.mark.parametrize("microbatches", [2, 4, 8])
def test_pipeline_overlap_matches_dense(setup, microbatches):
    # the overlapped schedule (activation pre-rotation under stage
    # compute, M + 2(S-1) ticks) changes WHEN activations ride the
    # links, never the math — same numbers as the dense reference
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    got = pipeline_forward_blocks(
        stacked, x, cfg, mesh, "pp", num_microbatches=microbatches, overlap=True
    )
    assert jnp.max(jnp.abs(got - ref)) < 1e-4


def test_pipeline_overlap_matches_serial_schedule(setup):
    # overlapped and serial schedules run the same stage computes on
    # the same microbatches — their outputs agree to float32 exactness
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    serial = pipeline_forward_blocks(
        stacked, x, cfg, mesh, "pp", num_microbatches=4
    )
    overlap = pipeline_forward_blocks(
        stacked, x, cfg, mesh, "pp", num_microbatches=4, overlap=True
    )
    assert jnp.max(jnp.abs(overlap - serial)) < 1e-5


def test_pipeline_overlap_jits(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    out = jax.jit(
        lambda layers, x: pipeline_forward_blocks(
            layers, x, cfg, mesh, "pp", num_microbatches=4, overlap=True
        )
    )(stacked, x)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_jits(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    fn = jax.jit(
        lambda layers, x: pipeline_forward_blocks(
            layers, x, cfg, mesh, "pp", num_microbatches=4
        )
    )
    out = fn(stacked, x)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_validates_divisibility(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward_blocks(stacked, x, cfg, mesh, "pp", num_microbatches=3)
    bad = ProbeModelConfig(n_layers=6)
    bad_params = init_params(jax.random.key(0), bad)
    bad_stacked = stack_layer_params(bad_params["layers"])
    with pytest.raises(ValueError, match="stages"):
        pipeline_forward_blocks(bad_stacked, x, bad, mesh, "pp")


def test_stack_layer_params_shapes(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    assert stacked["wqkv"].shape[0] == cfg.n_layers
    assert stacked["ln1"]["scale"].shape == (cfg.n_layers, cfg.d_model)


# -- composed dp×tp×pp -------------------------------------------------

# partially-manual shard_map (manual "pp", compiler-managed data/model)
# is unsupported by the legacy lowering — axis_index becomes a
# PartitionId the SPMD partitioner rejects (utils/compat.py)
from activemonitor_tpu.utils.compat import SUPPORTS_PARTIAL_MANUAL

needs_partial_manual = pytest.mark.skipif(
    not SUPPORTS_PARTIAL_MANUAL,
    reason="legacy shard_map cannot lower partially-manual meshes",
)


def _composed_mesh():
    from activemonitor_tpu.parallel.mesh import make_mesh

    return make_mesh(("data", "model", "pp"), (2, 2, 2))


@needs_partial_manual
def test_pipeline_composed_matches_dense(setup):
    # manual only over "pp", data/model compiler-managed: the numbers
    # must still match the sequential reference exactly (f32). Jitted:
    # partially-manual shard_map has no eager path.
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    cmesh = _composed_mesh()
    got = jax.jit(
        lambda layers, x: pipeline_forward_blocks(
            layers, x, cfg, cmesh, "pp", num_microbatches=4, composed=True
        )
    )(stacked, x)
    assert jnp.max(jnp.abs(got - ref)) < 1e-4


@needs_partial_manual
def test_pipeline_composed_overlap_matches_dense(setup):
    # overlap composes with the partially-manual mesh: the pre-rotated
    # schedule still hands XLA the data/model shardings to manage
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    cmesh = _composed_mesh()
    got = jax.jit(
        lambda layers, x: pipeline_forward_blocks(
            layers, x, cfg, cmesh, "pp",
            num_microbatches=4, composed=True, overlap=True,
        )
    )(stacked, x)
    assert jnp.max(jnp.abs(got - ref)) < 1e-4


@needs_partial_manual
def test_composed_train_step_matches_2d_loss():
    # the dp×tp×pp step must compute the same first-step loss as the
    # plain dp×tp step on the same params/tokens — the pipeline axis is
    # an execution schedule, not a different model
    from activemonitor_tpu.parallel.mesh import make_2d_mesh
    from activemonitor_tpu.probes.training_step import (
        build_composed_train_step,
        build_sharded_train_step,
    )

    cfg = ProbeModelConfig(
        vocab_size=64,
        d_model=32,
        n_heads=2,
        n_layers=2,
        d_ff=64,
        max_seq_len=32,
        dtype=jnp.float32,
    )
    mesh3 = _composed_mesh()
    step3, p3, o3, sh3 = build_composed_train_step(cfg, mesh3)
    tokens = jax.random.randint(jax.random.key(3), (4, 17), 0, cfg.vocab_size)
    _, _, loss3 = step3(p3, o3, jax.device_put(tokens, sh3))

    mesh2 = make_2d_mesh(shape=(4, 2))  # model axis must divide n_heads=2
    step2, p2, o2, sh2 = build_sharded_train_step(cfg, mesh2)
    _, _, loss2 = step2(p2, o2, jax.device_put(tokens, sh2))
    assert abs(float(loss3) - float(loss2)) < 1e-4


def test_composed_train_step_rejects_bad_mesh():
    from activemonitor_tpu.probes.training_step import build_composed_train_step

    cfg = ProbeModelConfig(n_layers=2)
    with pytest.raises(ValueError, match="'pp' axis"):
        from activemonitor_tpu.parallel.mesh import make_2d_mesh

        build_composed_train_step(cfg, make_2d_mesh(shape=(2, 4)))
