"""Workflow manifest mutation tests (reference test model:
healthcheck_controller_unit_test.go:102-256 parse/type-safety cases)."""

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.api.types import TPUPlacement
from activemonitor_tpu.controller import (
    WF_INSTANCE_ID,
    WF_INSTANCE_ID_LABEL_KEY,
    WorkflowSpecError,
    parse_remedy_workflow_from_healthcheck,
    parse_workflow_from_healthcheck,
)

BASE_WF = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata:
  generateName: hello-world-
spec:
  entrypoint: whalesay
  templates:
    - name: whalesay
      container:
        image: docker/whalesay
        command: [cowsay]
"""


def make_hc(inline=BASE_WF, remedy_inline=None, repeat=60, timeout=0, sa="check-sa"):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "workflow": {
            "generateName": "check-",
            "workflowtimeout": timeout,
            "resource": {
                "namespace": "health",
                "serviceAccount": sa,
                "source": {"inline": inline},
            },
        },
    }
    if remedy_inline is not None:
        spec["remedyworkflow"] = {
            "generateName": "remedy-",
            "resource": {
                "namespace": "health",
                "serviceAccount": "remedy-sa",
                "source": {"inline": remedy_inline},
            },
        }
    return HealthCheck.from_dict(
        {"metadata": {"name": "hc-a", "namespace": "health", "uid": "uid-9"}, "spec": spec}
    )


def test_injects_gvk_metadata_and_owner_reference():
    hc = make_hc()
    wf = parse_workflow_from_healthcheck(hc)
    assert wf["apiVersion"] == "argoproj.io/v1alpha1"
    assert wf["kind"] == "Workflow"
    assert wf["metadata"]["namespace"] == "health"
    assert wf["metadata"]["generateName"] == "check-"
    ref = wf["metadata"]["ownerReferences"][0]
    assert ref["uid"] == "uid-9"
    assert ref["controller"] is True
    assert ref["kind"] == "HealthCheck"


def test_default_instance_id_label_when_no_labels():
    wf = parse_workflow_from_healthcheck(make_hc())
    assert wf["metadata"]["labels"] == {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID}


def test_manifest_labels_used_when_present():
    inline = BASE_WF.replace(
        "metadata:\n  generateName: hello-world-",
        "metadata:\n  labels:\n    team: sre\n  generateName: hello-world-",
    )
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["metadata"]["labels"] == {"team": "sre"}


def test_labels_do_not_leak_between_checks():
    """The reference's shared workflowLabels map leaks labels across
    HealthChecks (SURVEY.md §2 defect); per-check computation must not."""
    inline = BASE_WF.replace(
        "metadata:\n  generateName: hello-world-",
        "metadata:\n  labels:\n    team: sre\n  generateName: hello-world-",
    )
    parse_workflow_from_healthcheck(make_hc(inline=inline))
    wf2 = parse_workflow_from_healthcheck(make_hc())  # no labels in manifest
    assert wf2["metadata"]["labels"] == {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID}


def test_pod_gc_defaulted():
    wf = parse_workflow_from_healthcheck(make_hc())
    assert wf["spec"]["podGC"] == {"strategy": "OnPodCompletion"}


def test_pod_gc_preserved_if_present():
    inline = BASE_WF + "  podGC:\n    strategy: OnWorkflowSuccess\n"
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["spec"]["podGC"] == {"strategy": "OnWorkflowSuccess"}


def test_service_account_injected():
    wf = parse_workflow_from_healthcheck(make_hc())
    assert wf["spec"]["serviceAccountName"] == "check-sa"


def test_timeout_defaults_to_repeat_after_sec():
    # reference: healthcheck_controller.go:981-986 (mutates the spec)
    hc = make_hc(repeat=45, timeout=0)
    wf = parse_workflow_from_healthcheck(hc)
    assert hc.spec.workflow.timeout == 45
    assert wf["spec"]["activeDeadlineSeconds"] == 45


def test_explicit_timeout_wins():
    hc = make_hc(repeat=45, timeout=20)
    wf = parse_workflow_from_healthcheck(hc)
    assert wf["spec"]["activeDeadlineSeconds"] == 20


def test_manifest_active_deadline_preserved():
    inline = BASE_WF + "  activeDeadlineSeconds: 99\n"
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["spec"]["activeDeadlineSeconds"] == 99


def test_missing_spec_is_error():
    with pytest.raises(WorkflowSpecError, match="missing spec"):
        parse_workflow_from_healthcheck(make_hc(inline="apiVersion: v1\nkind: Workflow"))


def test_non_map_spec_is_error():
    with pytest.raises(WorkflowSpecError, match="spec is not a map"):
        parse_workflow_from_healthcheck(
            make_hc(inline="apiVersion: v1\nspec: just-a-string")
        )


def test_non_map_manifest_is_error():
    with pytest.raises(WorkflowSpecError, match="invalid spec file"):
        parse_workflow_from_healthcheck(make_hc(inline="- a\n- b"))


def test_non_map_metadata_treated_as_unset():
    # reference: :930-932 type-assertion safety
    inline = "metadata: just-a-string\nspec:\n  entrypoint: x\n"
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["metadata"]["labels"] == {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID}


def test_non_map_labels_fall_back_to_default():
    inline = "metadata:\n  labels: nope\nspec:\n  entrypoint: x\n"
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["metadata"]["labels"] == {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID}


def test_annotations_preserved():
    inline = "metadata:\n  annotations:\n    note: keep-me\nspec:\n  entrypoint: x\n"
    wf = parse_workflow_from_healthcheck(make_hc(inline=inline))
    assert wf["metadata"]["annotations"] == {"note": "keep-me"}


# -- remedy variant ----------------------------------------------------


def test_remedy_deadline_defaults_to_repeat_after_sec():
    hc = make_hc(remedy_inline=BASE_WF, repeat=30)
    wf = parse_remedy_workflow_from_healthcheck(hc)
    assert wf["spec"]["activeDeadlineSeconds"] == 30
    assert hc.spec.remedy_workflow.timeout == 30
    assert wf["spec"]["serviceAccountName"] == "remedy-sa"


def test_remedy_numeric_deadline_sets_timeout():
    # reference: :1113-1115
    hc = make_hc(remedy_inline=BASE_WF + "  activeDeadlineSeconds: 77\n", repeat=30)
    parse_remedy_workflow_from_healthcheck(hc)
    assert hc.spec.remedy_workflow.timeout == 77


def test_remedy_non_numeric_deadline_falls_back():
    # reference: :1116-1119
    hc = make_hc(remedy_inline=BASE_WF + "  activeDeadlineSeconds: soon\n", repeat=30)
    parse_remedy_workflow_from_healthcheck(hc)
    assert hc.spec.remedy_workflow.timeout == 30


def test_remedy_nil_resource_is_error():
    hc = make_hc()
    with pytest.raises(WorkflowSpecError, match="Resource is nil"):
        parse_remedy_workflow_from_healthcheck(hc)


# -- TPU placement injection (framework extension) ----------------------


def test_tpu_placement_injected():
    hc = make_hc()
    hc.spec.workflow.tpu = TPUPlacement(accelerator="tpu-v5-lite-podslice", topology="2x4", chips=8)
    wf = parse_workflow_from_healthcheck(hc)
    sel = wf["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x4"
    assert wf["spec"]["tolerations"][0]["key"] == "google.com/tpu"
    limits = wf["spec"]["templates"][0]["container"]["resources"]["limits"]
    assert limits["google.com/tpu"] == 8


def test_tpu_placement_respects_existing_selectors():
    inline = BASE_WF + "  nodeSelector:\n    cloud.google.com/gke-tpu-topology: 4x4\n"
    hc = make_hc(inline=inline)
    hc.spec.workflow.tpu = TPUPlacement(accelerator="a", topology="2x4")
    wf = parse_workflow_from_healthcheck(hc)
    # user's explicit topology wins (setdefault semantics)
    assert wf["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"


def test_no_tpu_block_means_no_injection():
    wf = parse_workflow_from_healthcheck(make_hc())
    assert "nodeSelector" not in wf["spec"]
    assert "tolerations" not in wf["spec"]


def test_remedy_tpu_placement_injected():
    hc = make_hc(remedy_inline=BASE_WF, repeat=30)
    hc.spec.remedy_workflow.tpu = TPUPlacement(
        accelerator="tpu-v5-lite-podslice", topology="2x4", chips=8
    )
    wf = parse_remedy_workflow_from_healthcheck(hc)
    assert (
        wf["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
        == "tpu-v5-lite-podslice"
    )
    limits = wf["spec"]["templates"][0]["container"]["resources"]["limits"]
    assert limits["google.com/tpu"] == 8
