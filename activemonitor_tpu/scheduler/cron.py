"""Cron expression parsing and next-fire computation.

Grammar-compatible with the robfig/cron "standard" parser the reference
uses (reference: healthcheck_controller.go:34,253 cron.ParseStandard;
spec docs linked from healthcheck_types.go:149):

- five fields: minute hour day-of-month month day-of-week
- ``*`` and ``?`` wildcards, lists ``a,b,c``, ranges ``a-b``, steps
  ``*/n``, ``a-b/n``, ``a/n`` (a to max by n)
- month and weekday names (``JAN``-``DEC``, ``SUN``-``SAT``), 7 ≡ Sunday
- descriptors ``@yearly``/``@annually``, ``@monthly``, ``@weekly``,
  ``@daily``/``@midnight``, ``@hourly``
- ``@every <duration>`` with Go duration syntax
- ``TZ=<zone>`` / ``CRON_TZ=<zone>`` prefix: the schedule's wall-clock
  fields are interpreted in that IANA zone (robfig ParseStandard
  behavior), e.g. ``CRON_TZ=Asia/Tokyo 0 6 * * *``

Standard-cron quirk preserved: when **both** day-of-month and
day-of-week are restricted, a time matches if **either** matches.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import FrozenSet

from activemonitor_tpu.utils.duration import parse_go_duration

_MONTH_NAMES = {
    name: i + 1
    for i, name in enumerate(
        ["JAN", "FEB", "MAR", "APR", "MAY", "JUN", "JUL", "AUG", "SEP", "OCT", "NOV", "DEC"]
    )
}
_DOW_NAMES = {
    name: i for i, name in enumerate(["SUN", "MON", "TUE", "WED", "THU", "FRI", "SAT"])
}

_DESCRIPTORS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

# Search horizon for next(): far beyond the longest gap any valid
# expression can produce (Feb 29 recurs within 8 years).
_MAX_YEARS_AHEAD = 9


class CronParseError(ValueError):
    """The expression is not valid standard cron."""


@dataclass(frozen=True)
class EverySchedule:
    """Constant-delay schedule from ``@every <duration>``."""

    interval_seconds: float

    def next(self, after: datetime.datetime) -> datetime.datetime:
        # robfig's ConstantDelaySchedule truncates the delay to a whole
        # second (min 1 s) and fires at t + delay truncated to the second.
        delay = max(1.0, float(int(self.interval_seconds)))
        fired = after + datetime.timedelta(seconds=delay)
        return fired.replace(microsecond=0)


@dataclass(frozen=True)
class CronSchedule:
    """Field-set schedule for standard five-field expressions."""

    minutes: FrozenSet[int]
    hours: FrozenSet[int]
    days_of_month: FrozenSet[int]
    months: FrozenSet[int]
    days_of_week: FrozenSet[int]
    dom_star: bool
    dow_star: bool

    def _day_matches(self, t: datetime.datetime) -> bool:
        dom_ok = t.day in self.days_of_month
        # Python weekday(): Monday=0; cron: Sunday=0.
        dow_ok = (t.weekday() + 1) % 7 in self.days_of_week
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # both restricted: OR (standard cron)

    def next(self, after: datetime.datetime) -> datetime.datetime:
        # Next minute boundary strictly after `after`.
        t = after.replace(second=0, microsecond=0) + datetime.timedelta(minutes=1)
        limit = after.replace(
            year=after.year + _MAX_YEARS_AHEAD, month=1, day=1,
            hour=0, minute=0, second=0, microsecond=0,
        )
        while t < limit:
            if t.month not in self.months:
                # jump to first instant of the next month
                if t.month == 12:
                    t = t.replace(year=t.year + 1, month=1, day=1, hour=0, minute=0)
                else:
                    t = t.replace(month=t.month + 1, day=1, hour=0, minute=0)
                continue
            if not self._day_matches(t):
                t = (t + datetime.timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if t.hour not in self.hours:
                t = (t + datetime.timedelta(hours=1)).replace(minute=0)
                continue
            if t.minute not in self.minutes:
                t = t + datetime.timedelta(minutes=1)
                continue
            return t
        raise CronParseError("expression never fires within the search horizon")


@dataclass(frozen=True)
class ZonedSchedule:
    """Wraps a CronSchedule so its wall-clock fields are evaluated in a
    specific IANA zone (the ``TZ=``/``CRON_TZ=`` prefix)."""

    inner: CronSchedule
    zone: datetime.tzinfo

    def next(self, after: datetime.datetime) -> datetime.datetime:
        if after.tzinfo is None:
            after = after.replace(tzinfo=datetime.timezone.utc)
        fire = self.inner.next(after.astimezone(self.zone))
        # DST canonicalization: a fire computed inside the spring-
        # forward gap (e.g. 02:30 on the skip day) is a NONEXISTENT
        # wall time that zoneinfo renders with the pre-transition
        # offset. Round-tripping through UTC maps it to the true
        # instant's canonical rendering (02:30 EST -> 03:30 EDT), the
        # same normalization Go's time.Date gives the reference's cron.
        # Idempotent for every real wall time, and it keeps chained
        # next(next(...)) calls monotonic in UTC across the gap.
        return fire.astimezone(datetime.timezone.utc).astimezone(self.zone)


def _parse_value(token: str, names: dict, what: str) -> int:
    token = token.strip()
    if token.upper() in names:
        return names[token.upper()]
    try:
        v = int(token)
    except ValueError:
        raise CronParseError(f"invalid {what} value {token!r}")
    return v


def _parse_field(field: str, lo: int, hi: int, names: dict, what: str) -> FrozenSet[int]:
    values: set[int] = set()
    for part in field.split(","):
        part = part.strip()
        if not part:
            raise CronParseError(f"empty {what} list item in {field!r}")
        step = 1
        if "/" in part:
            rng, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"invalid step {step_s!r} in {what}")
            if step <= 0:
                raise CronParseError(f"step must be positive in {what}")
        else:
            rng = part
        if rng in ("*", "?"):
            start, end = lo, hi
        elif "-" in rng:
            a, _, b = rng.partition("-")
            start = _parse_value(a, names, what)
            end = _parse_value(b, names, what)
        else:
            start = _parse_value(rng, names, what)
            # "a/n" means a..max by n (robfig semantics); bare "a" is a singleton
            end = hi if "/" in part else start
        if start < lo or end > hi or start > end:
            raise CronParseError(
                f"{what} value out of range [{lo},{hi}]: {part!r}"
            )
        values.update(range(start, end + 1, step))
    if not values:
        raise CronParseError(f"empty {what} field")
    return frozenset(values)


def parse_cron(expr: str):
    """Parse a cron expression; returns an object with ``.next(after)``."""
    expr = expr.strip()
    if not expr:
        raise CronParseError("empty cron expression")
    if expr.startswith(("TZ=", "CRON_TZ=")):
        prefix, _, rest = expr.partition(" ")
        zone_name = prefix.split("=", 1)[1]
        if not zone_name or not rest.strip():
            raise CronParseError(f"malformed timezone prefix in {expr!r}")
        try:
            from zoneinfo import ZoneInfo

            zone = ZoneInfo(zone_name)
        except Exception:
            raise CronParseError(f"unknown timezone {zone_name!r}")
        if rest.lstrip().startswith(("TZ=", "CRON_TZ=")):
            # robfig strips exactly one prefix; a second one is part of
            # the field list and fails to parse — never a silent nesting
            raise CronParseError(f"multiple timezone prefixes in {expr!r}")
        schedule = parse_cron(rest)
        if isinstance(schedule, EverySchedule):
            return schedule  # constant interval: zone is irrelevant
        return ZonedSchedule(inner=schedule, zone=zone)
    if expr in _DESCRIPTORS:
        expr = _DESCRIPTORS[expr]
    elif expr.startswith("@every "):
        try:
            seconds = parse_go_duration(expr[len("@every "):])
        except ValueError as e:
            raise CronParseError(str(e))
        if seconds <= 0:
            raise CronParseError(f"@every duration must be positive: {expr!r}")
        return EverySchedule(seconds)
    elif expr.startswith("@"):
        raise CronParseError(f"unrecognized descriptor {expr!r}")

    fields = expr.split()
    if len(fields) != 5:
        raise CronParseError(
            f"expected 5 fields, got {len(fields)} in {expr!r}"
        )
    minutes = _parse_field(fields[0], 0, 59, {}, "minute")
    hours = _parse_field(fields[1], 0, 23, {}, "hour")
    dom = _parse_field(fields[2], 1, 31, {}, "day-of-month")
    months = _parse_field(fields[3], 1, 12, _MONTH_NAMES, "month")
    # bounds 0-7: 7 is accepted and folded onto Sunday (0) below
    dow = _parse_field(fields[4], 0, 7, _DOW_NAMES, "day-of-week")
    dow = frozenset(0 if v == 7 else v for v in dow)
    return CronSchedule(
        minutes=minutes,
        hours=hours,
        days_of_month=dom,
        months=months,
        days_of_week=dow,
        dom_star=_has_star(fields[2]),
        dow_star=_has_star(fields[4]),
    )


def _has_star(field: str) -> bool:
    """robfig sets a field's star bit when any list item's range portion
    is a wildcard — including step-on-wildcard forms like ``*/2``."""
    return any(
        part.strip().partition("/")[0].strip() in ("*", "?")
        for part in field.split(",")
    )


def seconds_until_next(expr: str, now: datetime.datetime) -> int:
    """Delta to the next cron fire, as the reference computes it
    (reference: healthcheck_controller.go:259-262 — int truncation of the
    sub-second remainder loses up to a second, so +1s keeps the fire
    time at-or-after the schedule point)."""
    schedule = parse_cron(expr)
    if now.tzinfo is None:
        # TZ-prefixed schedules return aware datetimes; keep the delta
        # arithmetic uniform by promoting a naive now to UTC
        now = now.replace(tzinfo=datetime.timezone.utc)
    delta = (schedule.next(now) - now).total_seconds()
    return int(delta) + 1
