"""Expert-parallel MoE tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.moe import (
    init_moe_params,
    moe_ffn_expert_parallel,
    moe_ffn_reference,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_1d_mesh("ep")


@pytest.mark.parametrize("n_experts", [8, 16])
def test_expert_parallel_matches_dense(mesh, n_experts):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=n_experts)
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    got = moe_ffn_expert_parallel(params, x, mesh, "ep")
    want = moe_ffn_reference(params, x)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


def test_expert_parallel_jits(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=8)
    x = jax.random.normal(jax.random.key(1), (32, 32), jnp.float32)
    fn = jax.jit(lambda p, x: moe_ffn_expert_parallel(p, x, mesh, "ep"))
    out = fn(params, x)
    assert jnp.max(jnp.abs(out - moe_ffn_reference(params, x))) < 1e-5


def test_expert_count_must_divide(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=6)
    x = jnp.zeros((16, 32), jnp.float32)
    with pytest.raises(ValueError, match="experts"):
        moe_ffn_expert_parallel(params, x, mesh, "ep")


def test_token_count_must_divide(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=8)
    x = jnp.zeros((17, 32), jnp.float32)
    with pytest.raises(ValueError, match="tokens"):
        moe_ffn_expert_parallel(params, x, mesh, "ep")


def test_all_experts_used_somewhere(mesh):
    """Sanity: with enough random tokens, routing spreads across experts
    (a degenerate router would silently under-test expert parallelism)."""
    params = init_moe_params(jax.random.key(2), d_model=32, d_ff=64, n_experts=8)
    x = jax.random.normal(jax.random.key(3), (512, 32), jnp.float32)
    expert = jnp.argmax(x @ params["router"], axis=-1)
    assert len(jnp.unique(expert)) >= 6


def test_expert_parallel_gradients_match_reference(mesh):
    """Expert parallelism is a TRAINING capability: gradients flow
    through the all_gather/psum_scatter dispatch collectives (their
    autodiff transposes) and match the dense single-device oracle for
    every parameter and the tokens."""
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=16)
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)

    def loss(fn):
        return lambda p, x: jnp.sum(fn(p, x).astype(jnp.float32) ** 2)

    g_ep = jax.jit(
        jax.grad(
            loss(lambda p, x: moe_ffn_expert_parallel(p, x, mesh, "ep")),
            argnums=(0, 1),
        )
    )(params, x)
    g_ref = jax.jit(
        jax.grad(loss(moe_ffn_reference), argnums=(0, 1))
    )(params, x)
    errs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g_ep[0], g_ref[0]
    )
    assert max(errs.values()) < 1e-4, errs
    assert float(jnp.max(jnp.abs(g_ep[1] - g_ref[1]))) < 1e-4


def test_expert_parallel_sgd_reduces_loss(mesh):
    """A few SGD steps through the sharded MoE drive a regression loss
    down — the end-to-end trainability check, not just one gradient."""
    import optax

    params = init_moe_params(jax.random.key(4), d_model=16, d_ff=32, n_experts=8)
    x = jax.random.normal(jax.random.key(5), (64, 16), jnp.float32)
    target = jax.random.normal(jax.random.key(6), (64, 16), jnp.float32)
    opt = optax.sgd(1e-1)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss(p):
            out = moe_ffn_expert_parallel(p, x, mesh, "ep")
            return jnp.mean((out - target) ** 2)

        value, grads = jax.value_and_grad(loss)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, value

    losses = []
    for _ in range(8):
        params, state, value = step(params, state)
        losses.append(float(value))
    # fitting noise with one top-1 MoE layer moves slowly; the gate is
    # a meaningful overall decrease, not per-step monotonicity (SGD
    # crossing an argmax routing boundary can raise a single step, and
    # platform numerics can flip near-tie routings)
    assert losses[-1] < losses[0] - 1e-2, losses
