"""DCN (cross-host) all-reduce probe — the multi-slice/multi-host check.

Runs on every host of a multi-host slice (or multislice topology) with
jax.distributed initialized, builds the hierarchical (dcn, ici) mesh,
and measures the all-reduce over the cross-host axis — traffic that
rides DCN between slices (or the host interconnect within one) rather
than intra-host ICI. A correctness gate (psum of a known payload over
all hosts) catches broken cross-host collectives outright.

Every worker of the workflow runs the same command; exit codes combine
through the workflow's parallel steps:

    python -m activemonitor_tpu.probes --distributed dcn-allreduce

(GKE multi-host TPU pods need no explicit coordinator — JAX
auto-detects; elsewhere pass --coordinator host:port --num-processes N
--process-id I.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel.collectives import all_reduce_bandwidth
from activemonitor_tpu.parallel.mesh import make_multihost_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def run(size_mb: float = 16.0, iters: int = 4) -> ProbeResult:
    n_proc = jax.process_count()
    if n_proc < 2:
        return ProbeResult(
            ok=True,
            summary=(
                "single process — no cross-host axis to measure "
                "(initialize jax.distributed across hosts first)"
            ),
            metrics=[
                ProbeMetric(
                    "dcn-hosts", 1, help="Number of hosts in the distributed run"
                )
            ],
            details={"processes": 1},
        )

    mesh = make_multihost_mesh()

    # correctness: psum over the dcn axis of a rank-tagged payload must
    # equal the sum over all hosts, identically on every host
    from activemonitor_tpu.parallel.partition import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P("dcn", None),
        out_specs=P(None, None),
        check_vma=False,
    )
    def cross_host_sum(x):
        return jax.lax.psum(x, "dcn")

    local = mesh.shape["ici"]
    x = jnp.arange(n_proc * local, dtype=jnp.float32).reshape(n_proc, local)
    got = cross_host_sum(x)
    expected = jnp.broadcast_to(x.sum(axis=0), (1, local))
    correct = bool(jnp.allclose(got, expected))

    # bandwidth is measured over ONE device per host: on the full
    # (dcn, ici) mesh the payload would be replicated across the ici
    # axis and every local device would run an identical concurrent
    # psum group, contending for the same NICs while the accounting
    # counted only one group's bytes — understating busbw by the
    # per-host device count.
    representatives = [mesh.devices[p, 0] for p in range(n_proc)]
    from activemonitor_tpu.parallel.mesh import make_1d_mesh

    bw_mesh = make_1d_mesh("dcn", devices=representatives)
    result = all_reduce_bandwidth(bw_mesh, size_mb=size_mb, iters=iters, axis="dcn")
    metrics = [
        ProbeMetric("dcn-hosts", n_proc, help="Number of hosts in the distributed run"),
        ProbeMetric(
            "dcn-allreduce-busbw-gbps",
            result.busbw_gbps,
            help="Cross-host all-reduce bus bandwidth, GB/s",
        ),
        ProbeMetric(
            "dcn-allreduce-correct",
            1.0 if correct else 0.0,
            help="1 when the cross-host psum result is correct",
        ),
    ]
    return ProbeResult(
        ok=correct,
        summary=(
            f"cross-host all-reduce over {n_proc} hosts: "
            f"{result.busbw_gbps:.2f} GB/s busbw, "
            f"correctness {'OK' if correct else 'MISMATCH'}"
        ),
        metrics=metrics,
        details={
            "processes": n_proc,
            "local_devices": local,
            "payload_mb": result.payload_bytes / 1e6,
            "seconds_per_op": result.seconds_per_op,
        },
    )
