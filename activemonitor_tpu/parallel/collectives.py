"""Timed collectives — the measurement core of the ICI bandwidth probes.

The communication backend is XLA collectives over ICI/DCN
(`psum` / `all_gather` / `ppermute` under `shard_map` on a Mesh) — the
TPU-native equivalent of the NCCL/MPI backends the mandate describes;
the reference itself has none (SURVEY.md §5.8).

Measurement discipline (SURVEY.md §7 hard part (d)): time the
collective, not the compile and not the dispatch — each benchmark jits
a chain of k data-dependent collectives and takes the (2k−k) wall-clock
difference through a forced host readback, so compile, tunnel
roundtrips, and dispatch overhead cancel
(see utils/timing.chain_delta_seconds).

Bandwidth conventions follow NCCL-tests:

- *algbw* = payload bytes / time
- *busbw* = algbw × 2(n-1)/n for all-reduce (ring transfer volume),
  algbw × (n-1)/n for all-gather — the number comparable against rated
  link bandwidth.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.utils.timing import chain_delta_seconds


@dataclass(frozen=True)
class CollectiveResult:
    name: str
    payload_bytes: int
    n_devices: int
    seconds_per_op: float
    algbw_gbps: float  # GB/s, payload/time
    busbw_gbps: float  # GB/s, NCCL busbw convention


def _payload(size_mb: float, dtype) -> tuple[int, int, int]:
    itemsize = jnp.dtype(dtype).itemsize
    cols = 1024
    rows = max(8, int(size_mb * 1e6 / itemsize) // cols)
    return rows, cols, rows * cols * itemsize


def _sharded_chain(mesh: Mesh, body, k: int, axis: str):
    """jit(shard_map(chain of k body applications)) ending in a scalar."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(None),
        check_vma=False,
    )
    def chain(x):
        for _ in range(k):
            x = body(x)
        # full-reduction readback: psum so every shard contributes
        return jax.lax.psum(x.astype(jnp.float32).sum(), axis)[None]

    return lambda x: chain(x)[0]


def all_reduce_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained psum all-reduce over ``axis`` (default: the mesh's first
    axis — pass "dcn" on a multihost mesh to measure the cross-host
    direction; the other axes stay replicated)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, payload_bytes = _payload(size_mb, dtype)
    inv_n = jnp.asarray(1.0 / n, dtype)

    def body(x):
        return jax.lax.psum(x, axis) * inv_n  # mean keeps magnitude stable

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    algbw = payload_bytes / seconds / 1e9
    busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
    return CollectiveResult(
        name="all_reduce",
        payload_bytes=payload_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )


def all_gather_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained all-gather; each round gathers all shards then reduces
    back to shard shape (the reduce keeps rounds data-dependent — its
    local cost is included, so this slightly understates pure comm bw)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, shard_bytes = _payload(size_mb, dtype)
    inv_n = jnp.asarray(1.0 / n, dtype)

    def body(x):
        g = jax.lax.all_gather(x, axis)  # [n, rows, cols]
        return jnp.sum(g, axis=0) * inv_n

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    total_bytes = shard_bytes * n
    algbw = total_bytes / seconds / 1e9
    busbw = algbw * ((n - 1) / n) if n > 1 else algbw
    return CollectiveResult(
        name="all_gather",
        payload_bytes=total_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )


def reduce_scatter_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained psum-scatter; each round reduce-scatters the shard then
    tiles the result back to shard shape (a local copy that keeps rounds
    data-dependent and shape-stable — its HBM cost is included, so this
    slightly understates pure comm bw, mirroring all_gather above)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, shard_bytes = _payload(size_mb, dtype)
    # rows must divide by n so the scattered shard keeps a static shape
    rows = max(n, rows - rows % n)
    shard_bytes = rows * cols * jnp.dtype(dtype).itemsize
    inv_n = jnp.asarray(1.0 / n, dtype)

    def body(x):
        s = jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        return jnp.concatenate([s] * n, axis=0) * inv_n

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    algbw = shard_bytes / seconds / 1e9
    busbw = algbw * ((n - 1) / n) if n > 1 else algbw
    return CollectiveResult(
        name="reduce_scatter",
        payload_bytes=shard_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )


def all_to_all_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained tiled all-to-all (the expert-parallel dispatch pattern,
    ops/moe.py) — shape-preserving, so the chain is pure communication;
    each round every device exchanges (n-1)/n of its shard."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, shard_bytes = _payload(size_mb, dtype)
    rows = max(n, rows - rows % n)
    shard_bytes = rows * cols * jnp.dtype(dtype).itemsize

    def body(x):
        return jax.lax.all_to_all(
            x, axis, split_axis=0, concat_axis=0, tiled=True
        )

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    algbw = shard_bytes / seconds / 1e9
    busbw = algbw * ((n - 1) / n) if n > 1 else algbw
    return CollectiveResult(
        name="all_to_all",
        payload_bytes=shard_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )


def ppermute_ring_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained neighbor-shift over a ring — isolates single-hop ICI link
    speed (the building block of ring attention / pipelined collectives)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, payload_bytes = _payload(size_mb, dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, axis, perm)

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    algbw = payload_bytes / seconds / 1e9
    return CollectiveResult(
        name="ppermute_ring",
        payload_bytes=payload_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=algbw,
    )
