"""Message-size autotuner over the collective algorithm zoo.

The zoo (parallel/schedules.py) gives 2–3 schedules per collective,
each winning a distinct latency-vs-bandwidth regime (Demystifying
NCCL); this module picks the winner per **(collective, axis size,
payload bucket, dtype)** from *measured* busbw — the PR-5 discipline:
the decision table is evidence, serialized into the sweep probe's
details and the bench artifact, never an asserted preference.

Layers:

- ``record()`` / ``lookup()`` — the in-process decision table. Keys
  bucket payload bytes by powers of two (one decision per octave, so a
  64 MB tuning point serves 48..96 MB gradients).
- ``tune()`` — run every schedule across a payload grid on a live mesh
  and record winners. The measurement function is injectable so unit
  tests script fake timings and watch the decision flip across the
  crossover without hardware.
- ``crossover_points()`` — where the winner changes along a swept
  grid (the per-topology crossovers the sweep probe reports).
- ``all_reduce()`` / ``all_gather()`` — the tuned surface for
  shard_map bodies: ``schedule="auto"`` consults the table at trace
  time (decisions bake into the jitted computation; retune → retrace).

No wall clocks here: the table stores busbw handed in by callers, so
fake-timing tests stay deterministic.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel import schedules as zoo
from activemonitor_tpu.utils.compat import axis_size


@dataclass(frozen=True)
class TuneKey:
    collective: str  # "allreduce" | "allgather"
    axis_n: int  # devices along the reduced mesh axis
    bucket: int  # floor(log2(payload bytes))
    dtype: str  # canonical dtype name ("bfloat16", "float32", ...)


@dataclass
class Decision:
    schedule: str  # winning schedule token ("xla", "rsag", ...)
    busbw_gbps: float
    runner_up: str = ""
    margin: float = 1.0  # winner busbw / runner-up busbw (≥ 1)
    per_schedule: Dict[str, float] = field(default_factory=dict)


_TABLE: Dict[TuneKey, Decision] = {}


def payload_bucket(payload_bytes: int) -> int:
    """Power-of-two octave of the payload: one decision per doubling."""
    return max(0, int(math.floor(math.log2(max(1, payload_bytes)))))


def clear() -> None:
    _TABLE.clear()


def record(
    collective: str,
    axis_n: int,
    payload_bytes: int,
    dtype,
    busbw_by_schedule: Dict[str, float],
) -> Decision:
    """Fold one measurement point into the table and return the
    decision. ``busbw_by_schedule`` maps schedule token → busbw GB/s
    (the NCCL-convention number, comparable across schedules)."""
    if not busbw_by_schedule:
        raise ValueError("no schedules measured")
    ranked = sorted(
        busbw_by_schedule.items(), key=lambda kv: kv[1], reverse=True
    )
    winner, best = ranked[0]
    runner_up, second = ranked[1] if len(ranked) > 1 else ("", 0.0)
    decision = Decision(
        schedule=winner,
        busbw_gbps=best,
        runner_up=runner_up,
        margin=(best / second) if second > 0 else 1.0,
        per_schedule=dict(busbw_by_schedule),
    )
    key = TuneKey(
        collective, int(axis_n), payload_bucket(payload_bytes),
        jnp.dtype(dtype).name,
    )
    _TABLE[key] = decision
    return decision


def lookup(
    collective: str,
    axis_n: int,
    payload_bytes: int,
    dtype,
    max_distance: int = 2,
) -> Optional[str]:
    """Winning schedule for the exact bucket, else the nearest tuned
    bucket within ``max_distance`` octaves for the same (collective,
    axis, dtype) — a 48 MB gradient should ride the 64 MB decision,
    but a 4 KB scalar-ish payload must NOT ride a 64 MB cell from the
    wrong side of the crossover; past the distance bound the caller
    falls back to the XLA builtin."""
    name = jnp.dtype(dtype).name
    bucket = payload_bucket(payload_bytes)
    exact = _TABLE.get(TuneKey(collective, int(axis_n), bucket, name))
    if exact is not None:
        return exact.schedule
    near = [
        k
        for k in _TABLE
        if k.collective == collective and k.axis_n == int(axis_n)
        and k.dtype == name and abs(k.bucket - bucket) <= max_distance
    ]
    if not near:
        return None
    # equidistant octaves tie-break toward the smaller payload's
    # decision (the latency-safe side of the crossover)
    best = min(near, key=lambda k: (abs(k.bucket - bucket), k.bucket))
    return _TABLE[best].schedule


def table_as_dict(keys: Optional[Sequence[TuneKey]] = None) -> dict:
    """JSON-serializable snapshot — the evidence block the sweep probe
    and bench.py stamp into their artifacts. ``keys`` restricts the
    snapshot (e.g. to the cells ONE tune() run measured, so a
    long-lived process never stamps stale cells from earlier tunes as
    this run's evidence)."""
    selected = _TABLE if keys is None else {
        k: _TABLE[k] for k in keys if k in _TABLE
    }
    out: dict = {}
    for key, d in sorted(
        selected.items(),
        key=lambda kv: (kv[0].collective, kv[0].axis_n, kv[0].bucket),
    ):
        out[f"{key.collective}/n{key.axis_n}/2^{key.bucket}B/{key.dtype}"] = {
            "schedule": d.schedule,
            "busbw_gbps": round(d.busbw_gbps, 3),
            "runner_up": d.runner_up,
            "margin": round(d.margin, 3),
            "per_schedule_busbw_gbps": {
                s: round(v, 3) for s, v in d.per_schedule.items()
            },
        }
    return out


def crossover_points(
    points: Iterable[Tuple[float, str]],
) -> List[dict]:
    """Where the winner flips along a swept payload grid.

    ``points``: (payload_mb, winning schedule), any order. Returns one
    entry per flip with the bracketing payloads — "rsag takes over from
    tree between 4 and 16 MB" is the per-topology crossover the NCCL
    paper catalogs."""
    ordered = sorted(points)
    flips = []
    for (lo_mb, lo_s), (hi_mb, hi_s) in zip(ordered, ordered[1:]):
        if lo_s != hi_s:
            flips.append(
                {
                    "below_mb": lo_mb,
                    "above_mb": hi_mb,
                    "from": lo_s,
                    "to": hi_s,
                }
            )
    return flips


# measurement functions per (collective, schedule token); injectable in
# tune() so fake-timing tests can script regime flips
def _default_benches() -> Dict[Tuple[str, str], Callable]:
    from activemonitor_tpu.parallel import collectives as xla

    return {
        ("allreduce", "xla"): xla.all_reduce_bandwidth,
        ("allreduce", "rsag"): zoo.all_reduce_rsag_bandwidth,
        ("allreduce", "recdouble"): zoo.all_reduce_recdouble_bandwidth,
        ("allreduce", "tree"): zoo.all_reduce_tree_bandwidth,
        ("allgather", "xla"): xla.all_gather_bandwidth,
        ("allgather", "ring"): zoo.all_gather_ring_bandwidth,
        ("allgather", "recdouble"): zoo.all_gather_recdouble_bandwidth,
    }


# log-spaced payload grid ≈ 256 KB → 256 MB — the regimes the NCCL
# paper's crossovers live in. Single source of truth: the sweep probe
# re-exports this; edit it here.
DEFAULT_SWEEP_SIZES_MB = (0.25, 1.0, 4.0, 16.0, 64.0, 256.0)


@dataclass
class TuneRun:
    """One tune() invocation: raw busbw per (collective, size,
    schedule) plus the exact table keys it recorded — the slice of the
    global table that is THIS run's evidence."""

    results: Dict[str, Dict[float, Dict[str, float]]]
    keys: List[TuneKey]


def tune(
    mesh,
    axis: str = "",
    collectives: Sequence[str] = ("allreduce",),
    sizes_mb: Sequence[float] = DEFAULT_SWEEP_SIZES_MB,
    dtype=jnp.bfloat16,
    iters: int = 3,
    bench: Optional[Callable] = None,
) -> TuneRun:
    """Measure every schedule at every payload size and record winners.

    ``bench(collective, schedule, mesh, axis, size_mb, dtype, iters)``
    must return an object with ``busbw_gbps`` and ``payload_bytes``
    (CollectiveResult shape) — tests inject a fake to script timings.
    The decision table is updated as a side effect; the returned
    ``TuneRun.keys`` identify exactly the cells this run wrote."""
    schedules_for = {
        "allreduce": zoo.ALL_REDUCE_SCHEDULES,
        "allgather": zoo.ALL_GATHER_SCHEDULES,
    }
    unknown = [c for c in collectives if c not in schedules_for]
    if unknown:
        raise ValueError(
            f"unknown collectives {unknown}; pick from "
            f"{tuple(schedules_for)}"
        )
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    benches = _default_benches()

    def run_one(collective, schedule, size_mb):
        if bench is not None:
            return bench(collective, schedule, mesh, axis, size_mb, dtype, iters)
        return benches[(collective, schedule)](
            mesh, size_mb=size_mb, dtype=dtype, iters=iters, axis=axis
        )

    raw: dict = {}
    keys: List[TuneKey] = []
    for collective in collectives:
        raw[collective] = {}
        for size_mb in sizes_mb:
            busbw: Dict[str, float] = {}
            payload = int(size_mb * 1e6)
            for schedule in schedules_for[collective]:
                result = run_one(collective, schedule, size_mb)
                busbw[schedule] = result.busbw_gbps
                payload = result.payload_bytes
            record(collective, n, payload, dtype, busbw)
            keys.append(
                TuneKey(
                    collective, int(n), payload_bucket(payload),
                    jnp.dtype(dtype).name,
                )
            )
            raw[collective][size_mb] = busbw
    return TuneRun(results=raw, keys=keys)


# ---------------------------------------------------------------------------
# the tuned surface — called INSIDE shard_map bodies
# ---------------------------------------------------------------------------

_ALL_REDUCE_IMPL = {
    "rsag": zoo.all_reduce_rsag,
    "recdouble": zoo.all_reduce_recdouble,
    "tree": zoo.all_reduce_tree,
}

_ALL_GATHER_IMPL = {
    "ring": zoo.all_gather_ring,
    "recdouble": zoo.all_gather_recdouble,
}


def all_reduce(x, axis_name: str, schedule: str = "auto", n: int | None = None):
    """psum with a schedule knob, for shard_map bodies. ``"auto"``
    consults the decision table (trace-time: the choice bakes into the
    jitted computation) and falls back to the XLA builtin when nothing
    is tuned within 2 octaves of this (axis size, payload, dtype) —
    or when the input has no leading axis to chunk (scalars always
    ride the builtin)."""
    n = int(n) if n is not None else axis_size(axis_name)
    if schedule == "auto":
        if x.ndim == 0:
            schedule = "xla"  # nothing to chunk/rotate on a scalar
        else:
            payload = x.size * jnp.dtype(x.dtype).itemsize
            schedule = lookup("allreduce", n, payload, x.dtype) or "xla"
    if schedule == "xla":
        return jax.lax.psum(x, axis_name)
    try:
        impl = _ALL_REDUCE_IMPL[schedule]
    except KeyError:
        raise ValueError(
            f"unknown all-reduce schedule {schedule!r}; pick from "
            f"{('auto',) + zoo.ALL_REDUCE_SCHEDULES}"
        ) from None
    return impl(x, axis_name, n)


def all_gather(x, axis_name: str, schedule: str = "auto", n: int | None = None):
    """Tiled all-gather with a schedule knob (output [n·rows, ...] in
    device order, like ``lax.all_gather(..., tiled=True)``)."""
    n = int(n) if n is not None else axis_size(axis_name)
    if schedule == "auto":
        if x.ndim == 0:
            schedule = "xla"  # no leading axis to tile
        else:
            payload = x.size * jnp.dtype(x.dtype).itemsize * n
            schedule = lookup("allgather", n, payload, x.dtype) or "xla"
    if schedule == "xla":
        return jax.lax.all_gather(x, axis_name, tiled=True)
    try:
        impl = _ALL_GATHER_IMPL[schedule]
    except KeyError:
        raise ValueError(
            f"unknown all-gather schedule {schedule!r}; pick from "
            f"{('auto',) + zoo.ALL_GATHER_SCHEDULES}"
        ) from None
    return impl(x, axis_name, n)
