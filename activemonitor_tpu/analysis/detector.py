"""Pluggable degradation detectors + the anti-flap hysteresis machine.

Every detector maps one sample (plus its metric's baseline) to a raw
level — ``ok`` (0), ``warning`` (1), ``degraded`` (2) — or ``None``
when it has no opinion about that metric. The worst raw level across
detectors is fed to :class:`Hysteresis`, which owns the REPORTED state:
raw levels are per-run evidence, the reported state only moves after
``confirm_runs`` consecutive runs of evidence (and only one step per
run), so a single noisy run can never flip a check to degraded — the
ReFrame lesson (PAPERS.md): regression alarms that fire on point noise
get muted, alarms that fire on confirmed drift get fixed.

Detectors:

- :class:`RobustZScoreDetector` — |robust z| against the baseline's
  median/MAD scale; warm-up gated (the engine only consults it once the
  baseline has ``warmupRuns`` samples).
- :class:`RatedFractionDetector` — probes already divide by the rated
  spec tables (probes/rated.py), exporting ``*-fraction-of-rated``
  gauges; those are ABSOLUTE health fractions, comparable on run one,
  so this detector is not warm-up gated: a slice delivering 60 % of
  rated is degraded even if it has always delivered 60 %.
- :class:`TrendDetector` — least-squares slope over the recent ring,
  normalized by the center: catches the slow creep that stays inside
  the z-score band run over run but drifts far over the window.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from activemonitor_tpu.analysis.baseline import MetricBaseline

LEVEL_OK = 0
LEVEL_WARNING = 1
LEVEL_DEGRADED = 2

# reported-state vocabulary: label values of healthcheck_anomaly_state
# and the /statusz analysis block (lowercase like the check_state trio)
ANOMALY_STATES = ("ok", "warning", "degraded")


def level_name(level: int) -> str:
    return ANOMALY_STATES[max(LEVEL_OK, min(LEVEL_DEGRADED, level))]


@dataclass(frozen=True)
class DetectorConfig:
    """Per-check tuning, built from ``spec.analysis``."""

    z_threshold: float = 3.0  # |z| >= this -> warning; >= 2x -> degraded
    rated_warn: float = 0.85  # fraction-of-rated below this -> warning
    rated_degraded: float = 0.70  # ... below this -> degraded
    trend_min_samples: int = 8  # slope fits need a real window
    trend_warn: float = 0.10  # |relative drift across window| >= -> warning
    trend_degraded: float = 0.25


class RobustZScoreDetector:
    """Deviation of THIS sample from the learned center, in robust
    sigmas. Symmetric on purpose: a metric suddenly reading far above
    baseline (a broken timer, a dropped denominator) is as anomalous as
    one far below."""

    name = "zscore"
    needs_baseline = True

    def evaluate(
        self, metric: str, value: float, baseline: MetricBaseline, config: DetectorConfig
    ) -> Optional[int]:
        z = abs(baseline.zscore(value))
        if z >= 2 * config.z_threshold:
            return LEVEL_DEGRADED
        if z >= config.z_threshold:
            return LEVEL_WARNING
        return LEVEL_OK


def is_rated_fraction_metric(metric: str) -> bool:
    """The probes' rated-comparison gauges (docs/probes.md metric
    table) all carry the ``fraction-of-rated`` suffix — the contract
    names use dashes, the exported series underscores; accept both.
    Roofline fractions (``*-roofline-fraction``, obs/roofline.py) are
    the same kind of absolute health ratio with a SHARPER denominator —
    achieved over the kernel's own ceiling rather than the flat peak —
    so the rated-floor detector floors them too: a memory-bound kernel
    at 0.6 of flat rated reads healthy (its roofline fraction is near
    1.0), while 0.6 of its own ceiling is a confirmed degradation on
    either suffix."""
    normalized = metric.replace("-", "_")
    return (
        "fraction_of_rated" in normalized or "roofline_fraction" in normalized
    )


class RatedFractionDetector:
    """Absolute floor for ``*-fraction-of-rated`` AND
    ``*-roofline-fraction`` metrics: the rated tables (probes/rated.py)
    — flat or roofline-derived — are the denominator the probe already
    applied, so the value IS health; no baseline needed, which also
    means no warm-up blindness for an always-sick slice."""

    name = "rated"
    needs_baseline = False

    def evaluate(
        self, metric: str, value: float, baseline: Optional[MetricBaseline], config: DetectorConfig
    ) -> Optional[int]:
        if not is_rated_fraction_metric(metric):
            return None
        if value < config.rated_degraded:
            return LEVEL_DEGRADED
        if value < config.rated_warn:
            return LEVEL_WARNING
        return LEVEL_OK


def slope(values: Sequence[float]) -> float:
    """Least-squares slope per run index over ``values``."""
    n = len(values)
    if n < 2:
        return 0.0
    mean_x = (n - 1) / 2.0
    mean_y = sum(values) / n
    num = sum((i - mean_x) * (v - mean_y) for i, v in enumerate(values))
    den = sum((i - mean_x) ** 2 for i in range(n))
    return num / den if den else 0.0


class TrendDetector:
    """Relative drift across the recent window: ``slope * (n-1)``
    (total drift the fit attributes to the window) over the center's
    magnitude. Catches creep the z-score misses because each step stays
    inside the noise band."""

    name = "trend"
    needs_baseline = True

    def evaluate(
        self, metric: str, value: float, baseline: MetricBaseline, config: DetectorConfig
    ) -> Optional[int]:
        window: List[float] = list(baseline.recent) + [float(value)]
        if len(window) < max(2, config.trend_min_samples):
            return None
        center = abs(baseline.median) or abs(baseline.mean)
        if center <= 0:
            return None
        drift = abs(slope(window) * (len(window) - 1)) / center
        if drift >= config.trend_degraded:
            return LEVEL_DEGRADED
        if drift >= config.trend_warn:
            return LEVEL_WARNING
        return LEVEL_OK


def default_detectors() -> tuple:
    return (RatedFractionDetector(), RobustZScoreDetector(), TrendDetector())


class Hysteresis:
    """The reported anomaly state for one (check, metric).

    Raw detector levels are evidence; the state only escalates after
    ``confirm_runs`` consecutive runs whose raw level exceeds it, only
    de-escalates after ``calm_runs`` consecutive runs below it, and
    moves ONE step per transition (ok → warning → degraded and back) —
    so a single outlier run changes nothing, and recovery is as
    deliberate as escalation. Streaks reset on every transition.

    ``jump_to_raw=True`` (the scenario-matrix contract,
    analysis/matrix.py): a confirmed escalation moves directly to the
    WEAKEST raw level the streak sustained instead of one step — so
    two confirming degraded rounds report degraded, while a lone noisy
    round still never moves the state, and recovery stays one
    deliberate step per calm streak either way."""

    __slots__ = (
        "level", "up_streak", "down_streak", "confirm_runs", "calm_runs",
        "jump_to_raw", "up_floor",
    )

    def __init__(
        self,
        confirm_runs: int = 2,
        calm_runs: int = 3,
        jump_to_raw: bool = False,
    ):
        self.level = LEVEL_OK
        self.up_streak = 0
        self.down_streak = 0
        self.confirm_runs = max(1, confirm_runs)
        self.calm_runs = max(1, calm_runs)
        self.jump_to_raw = jump_to_raw
        # weakest raw level seen during the CURRENT up streak — the
        # level a confirmed jump_to_raw escalation lands on (a streak
        # of [degraded, warning] confirms only warning)
        self.up_floor = LEVEL_OK

    def update(self, raw_level: int) -> Optional[Tuple[int, int]]:
        """Feed one run's raw level; returns ``(old, new)`` on a state
        transition, else None."""
        raw_level = max(LEVEL_OK, min(LEVEL_DEGRADED, int(raw_level)))
        if raw_level > self.level:
            self.up_floor = (
                raw_level
                if self.up_streak == 0
                else min(self.up_floor, raw_level)
            )
            self.up_streak += 1
            self.down_streak = 0
            if self.up_streak >= self.confirm_runs:
                old = self.level
                if self.jump_to_raw:
                    self.level = max(self.level + 1, self.up_floor)
                else:
                    self.level += 1
                self.up_streak = 0
                self.up_floor = LEVEL_OK
                return (old, self.level)
        elif raw_level < self.level:
            self.down_streak += 1
            self.up_streak = 0
            # a broken up streak must clear its floor, or the stale
            # nonzero value serializes into every later blob
            self.up_floor = LEVEL_OK
            if self.down_streak >= self.calm_runs:
                old = self.level
                self.level -= 1
                self.down_streak = 0
                return (old, self.level)
        else:
            self.up_streak = 0
            self.down_streak = 0
            self.up_floor = LEVEL_OK
        return None

    # -- persistence (rides .status.analysis) ---------------------------
    def to_dict(self) -> dict:
        doc = {"level": self.level, "up": self.up_streak, "down": self.down_streak}
        if self.up_floor:
            # only mid-streak state needs the floor; omitting the zero
            # keeps pre-existing blobs byte-identical
            doc["floor"] = self.up_floor
        return doc

    @classmethod
    def from_dict(
        cls,
        data: dict,
        confirm_runs: int = 2,
        calm_runs: int = 3,
        jump_to_raw: bool = False,
    ) -> "Hysteresis":
        state = cls(confirm_runs, calm_runs, jump_to_raw)
        try:
            state.level = max(LEVEL_OK, min(LEVEL_DEGRADED, int(data.get("level", 0))))
            state.up_streak = max(0, int(data.get("up", 0)))
            state.down_streak = max(0, int(data.get("down", 0)))
            state.up_floor = max(
                LEVEL_OK, min(LEVEL_DEGRADED, int(data.get("floor", 0)))
            )
        except (TypeError, ValueError):
            return cls(confirm_runs, calm_runs, jump_to_raw)
        return state


def combine_raw_levels(levels: Sequence[Optional[int]]) -> int:
    """Worst opinion wins; detectors with no opinion abstain."""
    voted = [lvl for lvl in levels if lvl is not None]
    return max(voted) if voted else LEVEL_OK


def finite(value) -> Optional[float]:
    """A float usable for analysis, or None (NaN/inf/garbage)."""
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if math.isfinite(value) else None
