"""Disaggregated serving pools: prefill/decode split with KV handoff.

The pool half of ISSUE 20. PR 14's continuous-batching scheduler
colocates prefill (compute-bound) and decode (memory-bound) in one
pool, so each phase steals the other's roofline ceiling
(obs/roofline.py states both). This module splits them:

- :class:`PoolTopology` names the shape — a prefill pool and a decode
  pool with their own batch slots and block managers, or the
  ``colocated`` fallback, which delegates to the PR 14 scheduler
  verbatim (same object, same draw-for-draw event order), so its
  conservation ledger and trace are bitwise-identical to today's —
  the disagg tests pin that equality.
- :class:`MigrationChannel` prices the KV handoff on the PR 13
  latency-path collectives' α/B regime (``parallel/schedules.
  hier_all_reduce_latency``): a banked block table is a SMALL message,
  so below the crossover the per-hop launch latency α dominates and
  the wire term is bytes/B on the tier it rides — ICI intra-slice,
  DCN cross-slice. Every transfer's bytes, hops, tier and modeled
  seconds are recorded (the probe exports them), and the channel's
  ledger cross-checks tokens-out against tokens-in exactly.
- :class:`DisaggregatedScheduler` runs the lifecycle: admit into the
  prefill pool (FIFO, full prompt reservation, structured refusals —
  the PR 14 posture), prefill produces the first token (TTFT lives in
  the prefill pool), then the sequence *hands off*: the decode pool
  reserves prompt+output capacity, the channel prices the block-table
  transfer, the prefill side releases its blocks (and its prefix-cache
  refs), and the sequence decodes to completion in the decode pool.
  A decode pool with no room backpressures the handoff queue FIFO —
  the sequence keeps its prefill slot, so prefill stalls honestly
  instead of leaking.

Prefix caching (ops/kv_cache.PrefixCache) plugs into the prefill pool
only — that is where prompts bank; the decode side is private by
construction (the handoff copy private-izes shared blocks).
Speculative decoding plugs into the decode pool:
:meth:`DisaggregatedScheduler.record_speculative_step` books a
draft/verify round's emitted tokens and the draft acceptance ledger
the probe exports as a rated-fraction metric.

Pure policy, like the module it extends: no jax, no wall clock
(hack/lint.py bans clock calls here) — every timestamp arrives as an
argument, and the channel's seconds are MODEL outputs, not sleeps.

Token-exact conservation across the pool boundary is the contract:
``admitted == completed + in_flight`` (sequences and tokens, per
tenant — same schema as the colocated ledger) AND
``handed_off_tokens == received_tokens`` with per-transfer receipts
(:meth:`DisaggregatedScheduler.migration_ledger`).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence

from activemonitor_tpu.ops.kv_cache import KVBlockManager, PrefixCache
from activemonitor_tpu.scheduler.serving import (
    ContinuousBatchingScheduler,
    Request,
    SequenceState,
)


@dataclass(frozen=True)
class MigrationModel:
    """The α/B transfer-cost model for one KV handoff, mirroring the
    latency-path collectives' regime (parallel/schedules.py): seconds =
    hops·α + bytes/B. Defaults are the v5e rated figures
    (probes/rated.py: 45 GB/s unidirectional ICI per link, 25 GB/s
    DCN per host) with one ICI hop intra-slice and two DCN hops
    (host→spine→host) cross-slice."""

    alpha_s: float = 2e-6  # per-hop launch latency (the LL-regime α)
    ici_gbps: float = 45.0
    dcn_gbps: float = 25.0
    ici_hops: int = 1
    dcn_hops: int = 2

    @classmethod
    def from_rated(cls, spec) -> "MigrationModel":
        """Price the channel off a probes/rated.py RatedSpec (its
        ``ici_unidir_gbps`` / ``dcn_gbps`` columns)."""
        return cls(
            ici_gbps=float(spec.ici_unidir_gbps),
            dcn_gbps=float(spec.dcn_gbps) or cls.dcn_gbps,
        )


class MigrationChannel:
    """The priced pipe between the pools, with a per-transfer receipt
    ledger. ``cross_slice`` picks the tier: pools on one slice hand
    off over ICI; pools on different slices ride DCN."""

    def __init__(
        self,
        model: Optional[MigrationModel] = None,
        cross_slice: bool = False,
    ):
        self.model = model or MigrationModel()
        self.cross_slice = bool(cross_slice)
        self.transfers: List[dict] = []
        self.tokens_total = 0
        self.bytes_total = 0.0
        self.seconds_total = 0.0

    def transfer(self, rid: int, n_tokens: int, bytes_per_token: float) -> dict:
        """Price one handoff and book its receipt: tier, hops, bytes,
        modeled seconds. The policy layer never sleeps — the engine
        charges the seconds on its virtual clock."""
        tier = "dcn" if self.cross_slice else "ici"
        hops = self.model.dcn_hops if self.cross_slice else self.model.ici_hops
        gbps = self.model.dcn_gbps if self.cross_slice else self.model.ici_gbps
        n_bytes = float(n_tokens) * float(bytes_per_token)
        seconds = hops * self.model.alpha_s + n_bytes / max(gbps * 1e9, 1e-9)
        record = {
            "rid": rid,
            "tokens": int(n_tokens),
            "bytes": n_bytes,
            "tier": tier,
            "hops": hops,
            "seconds": seconds,
        }
        self.transfers.append(record)
        self.tokens_total += int(n_tokens)
        self.bytes_total += n_bytes
        self.seconds_total += seconds
        return record

    def ledger(self) -> dict:
        by_tier: Dict[str, Dict[str, float]] = {}
        for rec in self.transfers:
            row = by_tier.setdefault(
                rec["tier"], {"transfers": 0, "bytes": 0.0, "hops": 0}
            )
            row["transfers"] += 1
            row["bytes"] += rec["bytes"]
            row["hops"] += rec["hops"]
        return {
            "transfers": len(self.transfers),
            "tokens_total": self.tokens_total,
            "bytes_total": self.bytes_total,
            "seconds_total": self.seconds_total,
            "by_tier": by_tier,
        }


@dataclass(frozen=True)
class PoolTopology:
    """The serving pool shape. ``colocated`` is PR 14's single pool
    (``decode_slots`` is its batch ceiling; ``prefill_slots`` unused);
    ``disaggregated`` gives each phase its own slots, block budget and
    roofline regime."""

    mode: str = "colocated"  # "colocated" | "disaggregated"
    prefill_slots: int = 0
    decode_slots: int = 4
    cross_slice: bool = False

    def __post_init__(self):
        if self.mode not in ("colocated", "disaggregated"):
            raise ValueError(f"unknown pool mode {self.mode!r}")
        if self.decode_slots < 1:
            raise ValueError(f"decode_slots must be >= 1, got {self.decode_slots}")
        if self.mode == "disaggregated" and self.prefill_slots < 1:
            raise ValueError(
                f"disaggregated needs prefill_slots >= 1, got "
                f"{self.prefill_slots}"
            )

    @classmethod
    def colocated(cls, max_batch: int) -> "PoolTopology":
        return cls(mode="colocated", decode_slots=max_batch)

    @classmethod
    def disaggregated(
        cls,
        prefill_slots: int,
        decode_slots: int,
        cross_slice: bool = False,
    ) -> "PoolTopology":
        return cls(
            mode="disaggregated",
            prefill_slots=prefill_slots,
            decode_slots=decode_slots,
            cross_slice=cross_slice,
        )

    @property
    def disagg(self) -> bool:
        return self.mode == "disaggregated"


class DisaggregatedScheduler:
    """Pool-aware admission/handoff/retirement policy.

    Colocated mode IS the PR 14 scheduler — one inner
    :class:`ContinuousBatchingScheduler` every call delegates to, so
    ledger and trace are bitwise what today's scheduler produces.
    Disaggregated mode runs the split lifecycle::

        sched.pump_migrations(now)      # drain the handoff queue
        for seq in sched.admit(now):    # prefill-pool admissions
            ... prefill (remainder only on a prefix hit) ...
            sched.record_first_token(seq, token, now)
        records = sched.pump_migrations(now)  # newly priced handoffs
        ... copy blocks per record (ops/kv_cache.migrate_blocks) ...
        batch = sched.decode_batch(now)
        ... one paged decode step (or a draft/verify round) ...
        sched.record_decode_step(tokens_by_slot, now)
    """

    def __init__(
        self,
        requests: Sequence[Request],
        topology: PoolTopology,
        *,
        manager: Optional[KVBlockManager] = None,
        prefill_manager: Optional[KVBlockManager] = None,
        decode_manager: Optional[KVBlockManager] = None,
        bytes_per_token: float = 0.0,
        channel: Optional[MigrationChannel] = None,
        prefix_cache: Optional[PrefixCache] = None,
    ):
        self.topology = topology
        self.bytes_per_token = float(bytes_per_token)
        self.prefix_cache = prefix_cache
        self._inner: Optional[ContinuousBatchingScheduler] = None
        if not topology.disagg:
            if manager is None:
                raise ValueError("colocated mode needs `manager`")
            if prefix_cache is not None:
                raise ValueError(
                    "prefix caching rides the prefill pool — use the "
                    "disaggregated topology"
                )
            self._inner = ContinuousBatchingScheduler(
                requests, manager, topology.decode_slots
            )
            self.channel = channel or MigrationChannel(
                cross_slice=topology.cross_slice
            )
            return
        if prefill_manager is None or decode_manager is None:
            raise ValueError(
                "disaggregated mode needs prefill_manager AND decode_manager"
            )
        if prefix_cache is not None and prefix_cache.manager is not prefill_manager:
            raise ValueError("prefix_cache must index the PREFILL pool's manager")
        self.prefill_manager = prefill_manager
        self.decode_manager = decode_manager
        self.channel = channel or MigrationChannel(cross_slice=topology.cross_slice)
        self.waiting: Deque[Request] = deque(
            sorted(requests, key=lambda r: (r.arrival, r.rid))
        )
        self.prefill_active: Dict[int, SequenceState] = {}  # slot -> state
        self.decode_active: Dict[int, SequenceState] = {}
        self.completed: List[SequenceState] = []
        self._free_prefill_slots: List[int] = list(
            range(topology.prefill_slots - 1, -1, -1)
        )
        self._free_decode_slots: List[int] = list(
            range(topology.decode_slots - 1, -1, -1)
        )
        # sequences whose prefill finished, FIFO-waiting for decode-pool
        # capacity; they HOLD their prefill slot and blocks until the
        # handoff lands (honest backpressure, not a leak)
        self.migrating: Deque[SequenceState] = deque()
        self._admitted = 0
        self._tokens_emitted = 0
        self._tenant_admitted: Dict[str, int] = {}
        self._tenant_tokens: Dict[str, int] = {}
        self.refusals: Dict[str, int] = {
            "batch": 0,
            "blocks": 0,
            "migrate_slots": 0,
            "migrate_blocks": 0,
        }
        self.occupancy_samples: List[float] = []  # decode pool
        self.prefill_occupancy_samples: List[float] = []
        self.trace: List[tuple] = []
        # prefix-cache bookkeeping per live sequence
        self._hit_tokens: Dict[int, int] = {}
        # pool-boundary token ledger: two independent event-time
        # accounts the migration_ledger cross-checks
        self._handed_off_tokens = 0
        self._received_tokens = 0
        self._ready_at: Dict[int, float] = {}  # rid -> handoff completes
        # speculative-decoding acceptance ledger (decode pool)
        self._spec_drafted = 0
        self._spec_accepted = 0

    # -- queries ---------------------------------------------------------
    @property
    def done(self) -> bool:
        if self._inner is not None:
            return self._inner.done
        return not self.waiting and not self.prefill_active and not self.decode_active

    def next_arrival(self) -> Optional[float]:
        if self._inner is not None:
            return self._inner.next_arrival()
        return self.waiting[0].arrival if self.waiting else None

    def decode_batch(self, now: Optional[float] = None) -> List[SequenceState]:
        """Decode-pool sequences owing output whose handoff has landed
        (``ready_at <= now``; ``now=None`` skips the readiness filter),
        in slot order."""
        if self._inner is not None:
            return self._inner.decode_batch()
        out = []
        for slot in sorted(self.decode_active):
            seq = self.decode_active[slot]
            if seq.generated >= seq.req.output_tokens:
                continue
            if now is not None and self._ready_at.get(seq.req.rid, 0.0) > now:
                continue
            out.append(seq)
        return out

    def effective_table(self, rid: int) -> List[int]:
        """The prefill pool's full block table for ``rid``: shared
        prefix-cache blocks (acquisition order) then the private tail
        — what prefill compute gathers through and what the handoff
        copies from."""
        if self._inner is not None:
            return self._inner.manager.table(rid)
        shared = self.prefix_cache.held_blocks(rid) if self.prefix_cache else []
        return shared + self.prefill_manager.table(rid)

    def hit_tokens(self, rid: int) -> int:
        """Prompt tokens ``rid`` did NOT have to prefill (prefix-cache
        hits at admission)."""
        return self._hit_tokens.get(rid, 0)

    # -- delegation plumbing (colocated = PR 14 verbatim) ----------------
    def __getattr__(self, name: str):
        inner = self.__dict__.get("_inner")
        if inner is not None:
            return getattr(inner, name)
        raise AttributeError(name)

    # -- the step protocol ----------------------------------------------
    def admit(self, now: float) -> List[SequenceState]:
        """FIFO admission into the prefill pool: a slot plus a private
        reservation for the NON-CACHED prompt remainder (the prefill
        pool never decodes, so it reserves prompt capacity only; the
        decode pool reserves prompt+output at handoff). A blocked head
        stops admission — no skip-ahead — after one prefix-cache
        eviction attempt at refcount zero."""
        if self._inner is not None:
            return self._inner.admit(now)
        admitted: List[SequenceState] = []
        while self.waiting and self.waiting[0].arrival <= now:
            req = self.waiting[0]
            if not self._free_prefill_slots:
                self.refusals["batch"] += 1
                self.trace.append(("defer-batch", req.rid, now))
                break
            hit = 0
            if self.prefix_cache is not None and req.prompt_tokens is not None:
                _, hit = self.prefix_cache.lookup(req.prompt_tokens)
            need_tokens = req.prompt_len - hit
            need_blocks = self.prefill_manager.blocks_for(need_tokens)
            if need_blocks > self.prefill_manager.free_blocks:
                if self.prefix_cache is not None:
                    self.prefix_cache.evict(
                        need_blocks - self.prefill_manager.free_blocks
                    )
            blocks = self.prefill_manager.allocate(req.rid, need_tokens)
            if blocks is None:
                self.refusals["blocks"] += 1
                self.trace.append(("defer-blocks", req.rid, now))
                break
            if self.prefix_cache is not None and req.prompt_tokens is not None:
                _, hit = self.prefix_cache.acquire(
                    req.rid, req.tenant, req.prompt_tokens
                )
            self.waiting.popleft()
            self._hit_tokens[req.rid] = hit
            self.prefill_manager.append(req.rid, need_tokens)  # prompt banked
            seq = SequenceState(
                req=req, slot=self._free_prefill_slots.pop(), admitted_at=now
            )
            self.prefill_active[seq.slot] = seq
            self._admitted += 1
            self._tenant_admitted[req.tenant] = (
                self._tenant_admitted.get(req.tenant, 0) + 1
            )
            self.trace.append(("admit", req.rid, now))
            admitted.append(seq)
        return admitted

    def record_first_token(self, seq: SequenceState, token: int, now: float) -> None:
        """Prefill produced the first token (TTFT, in the prefill
        pool). Newly banked full blocks publish into the prefix cache;
        then the sequence either retires here (1-token requests never
        touch the decode pool) or queues for handoff."""
        if self._inner is not None:
            return self._inner.record_first_token(seq, token, now)
        seq.generated = 1
        seq.first_token_at = now
        seq.tokens.append(token)
        self._emit_token(seq)
        self.trace.append(("first-token", seq.req.rid, now))
        if self.prefix_cache is not None and seq.req.prompt_tokens is not None:
            self.prefix_cache.publish(
                seq.req.rid, seq.req.tenant, seq.req.prompt_tokens
            )
        if seq.generated >= seq.req.output_tokens:
            self._retire_from_prefill(seq, now)
            return
        self.migrating.append(seq)

    def pump_migrations(self, now: float) -> List[dict]:
        """Drain the handoff queue FIFO while the decode pool can take
        the head: reserve prompt+output there, price the transfer on
        the channel, release the prefill side. Returns the transfer
        receipts (with source/destination tables) so the engine can
        move the actual K/V and charge the modeled seconds."""
        if self._inner is not None:
            return []
        records: List[dict] = []
        while self.migrating:
            seq = self.migrating[0]
            rid = seq.req.rid
            if not self._free_decode_slots:
                self.refusals["migrate_slots"] += 1
                self.trace.append(("defer-migrate", rid, now))
                break
            capacity = seq.req.prompt_len + seq.req.output_tokens
            dst_blocks = self.decode_manager.allocate(rid, capacity)
            if dst_blocks is None:
                self.refusals["migrate_blocks"] += 1
                self.trace.append(("defer-migrate", rid, now))
                break
            self.migrating.popleft()
            src_blocks = self.effective_table(rid)
            record = self.channel.transfer(
                rid, seq.req.prompt_len, self.bytes_per_token
            )
            self._handed_off_tokens += seq.req.prompt_len
            self.decode_manager.append(rid, seq.req.prompt_len)
            self._received_tokens += self.decode_manager.length(rid)
            # source side releases: cache refs first (shared blocks stay
            # cached at refcount-1), then the private tail
            if self.prefix_cache is not None:
                self.prefix_cache.release(rid)
            self.prefill_manager.free(rid)
            del self.prefill_active[seq.slot]
            self._free_prefill_slots.append(seq.slot)
            self._hit_tokens.pop(rid, None)
            seq.slot = self._free_decode_slots.pop()
            self.decode_active[seq.slot] = seq
            record["src_blocks"] = src_blocks
            record["dst_blocks"] = dst_blocks
            record["ready_at"] = now + record["seconds"]
            self._ready_at[rid] = record["ready_at"]
            self.trace.append(("migrate", rid, now))
            records.append(record)
        return records

    def record_decode_step(
        self, tokens_by_slot: Dict[int, int], now: float
    ) -> List[SequenceState]:
        """One shared decode-pool step (same contract as PR 14's):
        each participating sequence banks its fed token's K/V and
        gains one generated token; finished sequences retire."""
        if self._inner is not None:
            return self._inner.record_decode_step(tokens_by_slot, now)
        finished: List[SequenceState] = []
        stepped = 0
        for slot, token in sorted(tokens_by_slot.items()):
            seq = self.decode_active.get(slot)
            if seq is None:
                continue
            self.decode_manager.append(seq.req.rid, 1)
            seq.generated += 1
            if seq.generated == 2 and seq.first_decode_at is None:
                seq.first_decode_at = now
            seq.tokens.append(token)
            self._emit_token(seq)
            stepped += 1
            if seq.generated >= seq.req.output_tokens:
                self._retire_from_decode(seq, now)
                finished.append(seq)
        self.occupancy_samples.append(stepped / self.topology.decode_slots)
        return finished

    def record_speculative_step(
        self,
        tokens_by_slot: Dict[int, List[int]],
        drafted_by_slot: Dict[int, int],
        accepted_by_slot: Dict[int, int],
        now: float,
    ) -> List[SequenceState]:
        """One draft/verify round on the decode pool: per slot, the
        verify pass confirmed ``tokens_by_slot[slot]`` (one target
        argmax per verify position — identical to what plain greedy
        decode would have emitted, so the consistency gate holds), of
        which ``accepted`` of ``drafted`` draft proposals matched.
        Books every confirmed token (K/V banks per token, same as a
        decode step) and the acceptance ledger."""
        if self._inner is not None:
            raise ValueError("speculative decoding needs the disaggregated pools")
        finished: List[SequenceState] = []
        stepped = 0
        for slot, tokens in sorted(tokens_by_slot.items()):
            seq = self.decode_active.get(slot)
            if seq is None or not tokens:
                continue
            drafted = int(drafted_by_slot.get(slot, 0))
            accepted = int(accepted_by_slot.get(slot, 0))
            if not 0 <= accepted <= drafted:
                raise ValueError(
                    f"slot {slot}: accepted {accepted} outside [0, {drafted}]"
                )
            self._spec_drafted += drafted
            self._spec_accepted += accepted
            for token in tokens:
                self.decode_manager.append(seq.req.rid, 1)
                seq.generated += 1
                if seq.generated == 2 and seq.first_decode_at is None:
                    seq.first_decode_at = now
                seq.tokens.append(token)
                self._emit_token(seq)
            stepped += 1
            self.trace.append(("spec", seq.req.rid, now))
            if seq.generated >= seq.req.output_tokens:
                self._retire_from_decode(seq, now)
                finished.append(seq)
        self.occupancy_samples.append(stepped / self.topology.decode_slots)
        return finished

    def sample_prefill_occupancy(self) -> None:
        self.prefill_occupancy_samples.append(
            len(self.prefill_active) / max(1, self.topology.prefill_slots)
        )

    # -- internals -------------------------------------------------------
    def _emit_token(self, seq: SequenceState) -> None:
        self._tokens_emitted += 1
        self._tenant_tokens[seq.req.tenant] = (
            self._tenant_tokens.get(seq.req.tenant, 0) + 1
        )

    def _retire_from_prefill(self, seq: SequenceState, now: float) -> None:
        seq.finished_at = now
        if self.prefix_cache is not None:
            self.prefix_cache.release(seq.req.rid)
        self.prefill_manager.free(seq.req.rid)
        del self.prefill_active[seq.slot]
        self._free_prefill_slots.append(seq.slot)
        self._hit_tokens.pop(seq.req.rid, None)
        self.completed.append(seq)
        self.trace.append(("retire", seq.req.rid, now))

    def _retire_from_decode(self, seq: SequenceState, now: float) -> None:
        seq.finished_at = now
        self.decode_manager.free(seq.req.rid)
        del self.decode_active[seq.slot]
        self._free_decode_slots.append(seq.slot)
        self._ready_at.pop(seq.req.rid, None)
        self.completed.append(seq)
        self.trace.append(("retire", seq.req.rid, now))

    # -- accounting ------------------------------------------------------
    def conservation(self) -> dict:
        """The PR 14 ledger schema over the split pools: in colocated
        mode this IS the inner scheduler's dict (bitwise — the
        fallback test pins it); disaggregated, in-flight spans both
        pools (handoff-queued sequences still hold their prefill
        slot, so nothing double-counts and nothing vanishes
        mid-boundary)."""
        if self._inner is not None:
            return self._inner.conservation()
        in_flight = list(self.prefill_active.values()) + list(
            self.decode_active.values()
        )
        tokens_completed = sum(s.generated for s in self.completed)
        tokens_in_flight = sum(s.generated for s in in_flight)
        tenants: Dict[str, Dict[str, int]] = {}
        for seq, bucket in [(s, "completed") for s in self.completed] + [
            (s, "in_flight") for s in in_flight
        ]:
            row = tenants.setdefault(
                seq.req.tenant, {"completed": 0, "in_flight": 0, "tokens": 0}
            )
            row[bucket] += 1
            row["tokens"] += seq.generated
        tenants_ok = True
        for tenant in set(tenants) | set(self._tenant_admitted) | set(
            self._tenant_tokens
        ):
            row = tenants.setdefault(
                tenant, {"completed": 0, "in_flight": 0, "tokens": 0}
            )
            row["admitted"] = self._tenant_admitted.get(tenant, 0)
            row["tokens_emitted"] = self._tenant_tokens.get(tenant, 0)
            tenants_ok = tenants_ok and (
                row["admitted"] == row["completed"] + row["in_flight"]
                and row["tokens_emitted"] == row["tokens"]
            )
        return {
            "admitted": self._admitted,
            "completed": len(self.completed),
            "in_flight": len(in_flight),
            "tokens_emitted": self._tokens_emitted,
            "tokens_completed": tokens_completed,
            "tokens_in_flight": tokens_in_flight,
            "tenants": tenants,
            "ok": (
                tenants_ok
                and self._admitted == len(self.completed) + len(in_flight)
                and self._tokens_emitted == tokens_completed + tokens_in_flight
            ),
        }

    def migration_ledger(self) -> dict:
        """The pool-boundary receipt: tokens handed off (prefill side,
        booked at transfer pricing) must equal tokens received (decode
        side, booked from the decode manager's banked length after the
        arrival append) must equal the channel's per-transfer sum —
        three independent accounts, exact to the token."""
        channel = self.channel.ledger()
        if self._inner is not None:
            return {**channel, "handed_off_tokens": 0, "received_tokens": 0, "ok": True}
        ok = (
            self._handed_off_tokens
            == self._received_tokens
            == channel["tokens_total"]
        )
        return {
            **channel,
            "handed_off_tokens": self._handed_off_tokens,
            "received_tokens": self._received_tokens,
            "ok": ok,
        }

    def speculation(self) -> dict:
        """The draft acceptance ledger: ``acceptance`` is the
        rated-fraction the probe exports (None before any draft ran —
        absence, not a fake 0.0 that would floor as degraded)."""
        drafted, accepted = self._spec_drafted, self._spec_accepted
        return {
            "drafted": drafted,
            "accepted": accepted,
            "acceptance": (accepted / drafted) if drafted else None,
            "ok": 0 <= accepted <= drafted,
        }

    def pool_stats(self) -> dict:
        if self._inner is not None:
            return {
                "mode": "colocated",
                "manager": self._inner.manager.stats(),
            }
        return {
            "mode": "disaggregated",
            "cross_slice": self.topology.cross_slice,
            "prefill": self.prefill_manager.stats(),
            "decode": self.decode_manager.stats(),
            "prefix_cache": (
                self.prefix_cache.stats() if self.prefix_cache else None
            ),
            "migrating": len(self.migrating),
        }
