"""The global front door: one submit surface over many clusters.

PR 15's :class:`~activemonitor_tpu.frontdoor.service.FrontDoor` stops
at one cluster: quota, coalescing, and the conservation ledger are all
per-cluster, so N tenants asking different clusters about the same pod
pay N runs and a hot tenant gets a fresh budget in every region. The
global door fixes both by composing, not replacing:

- **quota once, globally**: one :class:`~activemonitor_tpu.frontdoor.
  admission.AdmissionController` (the same token-bucket policy, the
  same structured refusal vocabulary) admits the tenant BEFORE routing.
  The per-cluster doors underneath admit the federation's traffic under
  :func:`federation_quota` — effectively unlimited, because paying
  quota twice would double-refuse — so a tenant's budget is one number
  no matter how many clusters serve it.
- **coalescing across clusters**: the capability router is
  deterministic (slice owner, tightest capability fit, or a stable
  hash), so every submission of one check lands on the SAME cluster's
  door, whose coalescing cache fans them in — N tenants in different
  regions share one run and one trace id, exactly the single-cluster
  guarantee lifted a level.
- **conservation, one level up**: every submitted request lands in
  exactly one of {cache_hit, joined, run, parked, refused, forwarded},
  booked per tenant PER CLUSTER, and :meth:`GlobalFrontDoor.
  conservation` cross-checks the outcome ledger against the global
  admission ledger — per cell and summed at the federation level — so
  a routing bug cannot hide demand between clusters.

``forwarded`` is the new column: a request routed to a cluster this
door has no in-process :meth:`attach` for is handed to that cluster's
forwarder hook (the manager wires an HTTP submit there). The ledger
books it at hand-off — the remote cluster's own door accounts for the
rest, in ITS ledger, under the federation tenant.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from activemonitor_tpu.federation.registry import ClusterRegistry
from activemonitor_tpu.federation.routing import CapabilityRouter, Requirement
from activemonitor_tpu.frontdoor.admission import (
    PRE_ADMISSION_REASONS,
    AdmissionController,
    TenantQuota,
)
from activemonitor_tpu.frontdoor.service import (
    OUTCOME_HIT,
    OUTCOME_JOINED,
    OUTCOME_PARKED,
    OUTCOME_REFUSED,
    OUTCOME_RUN,
    Ticket,
)
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.federation")

# the sixth outcome column, unique to the global ledger: handed to a
# remote cluster's own front door (accounted there from that point on)
OUTCOME_FORWARDED = "forwarded"

# the tenant name the global door uses on the per-cluster doors: quota
# is already paid globally, so the inner doors must always admit it
# (give it federation_quota() in their admission config)
FEDERATION_TENANT = "(federation)"

# post-admission refusal reasons minted at this level (the routing
# verdict's no_capable_cluster joins them via the router)
REFUSE_CLUSTER_UNATTACHED = "cluster_unattached"

# ledger column for requests refused before any cluster was chosen
UNROUTED_CLUSTER = "(none)"


def federation_quota() -> TenantQuota:
    """The quota the per-cluster doors grant :data:`FEDERATION_TENANT`:
    effectively unlimited, because the global door already charged the
    real tenant's bucket — a second, per-cluster charge would refuse
    traffic the federation admitted (and split one budget into N)."""
    return TenantQuota(rate_per_minute=1e12)


@dataclass
class GlobalTicket:
    """One globally-submitted request's decision: which cluster, how it
    was matched, and the per-cluster :class:`Ticket` underneath (None
    for refusals and forwards)."""

    rid: int
    tenant: str
    check: str
    cluster: str = ""
    outcome: str = OUTCOME_REFUSED
    matched: str = ""  # routing match kind (slice|capability|default)
    reason: str = ""  # refusal reason; "" otherwise
    ticket: Optional[Ticket] = None
    # the forwarder hook's return value (opaque: the manager's HTTP
    # forwarder returns the remote response, tests return sentinels)
    forwarded: object = None

    @property
    def trace_id(self) -> str:
        """The underlying run's trace id — SHARED by every tenant that
        coalesced onto it, across clusters (the global fan-in proof)."""
        return self.ticket.trace_id if self.ticket is not None else ""

    async def wait(self):
        """The fanned-out result (None for refusals and forwards)."""
        if self.ticket is None:
            return None
        return await self.ticket.wait()


@dataclass
class _Cell:
    """One (tenant, cluster) ledger cell — the global conservation
    table's unit."""

    submitted: int = 0
    cache_hits: int = 0
    joins: int = 0
    runs: int = 0
    parked: int = 0
    refused: int = 0
    forwarded: int = 0
    refusals: Dict[str, int] = field(default_factory=dict)

    def outcomes(self) -> int:
        return (
            self.cache_hits
            + self.joins
            + self.runs
            + self.parked
            + self.refused
            + self.forwarded
        )

    def to_dict(self) -> dict:
        return {
            "submitted": self.submitted,
            "cache_hits": self.cache_hits,
            "coalesced_joins": self.joins,
            "probe_runs": self.runs,
            "parked": self.parked,
            "refused": self.refused,
            "forwarded": self.forwarded,
            "refusals": dict(self.refusals),
            "ok": self.submitted == self.outcomes(),
        }


class GlobalFrontDoor:
    """One submit surface over the federation's per-cluster doors."""

    def __init__(
        self,
        registry: ClusterRegistry,
        router: CapabilityRouter,
        admission: AdmissionController,
        *,
        clock: Optional[Clock] = None,
        metrics=None,  # MetricsCollector (duck-typed; optional)
    ):
        self.clock = clock or Clock()
        self.registry = registry
        self.router = router
        self.admission = admission
        self.metrics = metrics
        # cluster name -> in-process FrontDoor (co-hosted / tests)
        self._doors: Dict[str, object] = {}
        # cluster name -> forwarder hook for remote clusters:
        # fn(tenant, check, freshness) -> opaque handle
        self._forwarders: Dict[str, Callable] = {}
        # tenant (booked) -> cluster -> ledger cell
        self._cells: Dict[str, Dict[str, _Cell]] = {}
        self._rid = 0

    # -- wiring ----------------------------------------------------------
    def attach(self, name: str, door) -> None:
        """Wire a cluster's in-process :class:`FrontDoor`. Its admission
        config must grant :data:`FEDERATION_TENANT` the
        :func:`federation_quota` — quota was already paid globally."""
        self._doors[name] = door

    def attach_forwarder(self, name: str, forward: Callable) -> None:
        """Wire a remote cluster's submit hook — called as
        ``forward(tenant, check, freshness)``; its return value rides
        the ticket opaquely. The ledger books ``forwarded`` at hand-off."""
        self._forwarders[name] = forward

    # -- the submit path -------------------------------------------------
    def submit(
        self,
        tenant: str,
        check: str,
        freshness: Optional[float] = None,
        requirement: Optional[Requirement] = None,
    ) -> GlobalTicket:
        """One request, decided synchronously: global quota first, then
        the capability route, then the chosen cluster's own door (whose
        decision — hit / join / run / parked / refused — mirrors into
        the global (tenant, cluster) cell)."""
        self._rid += 1
        rid = self._rid
        decision = self.admission.admit(tenant, check)
        booked = decision.booked
        if not decision.admitted:
            # pre-admission refusal (quota / unknown_tenant / capacity):
            # already in the admission ledger; no cluster was chosen
            ticket = GlobalTicket(
                rid=rid,
                tenant=tenant,
                check=check,
                cluster=UNROUTED_CLUSTER,
                outcome=OUTCOME_REFUSED,
                reason=decision.reason,
            )
            self._book(booked, ticket)
            return ticket
        route = self.router.route(check, requirement)
        if not route.routed:
            # post-admission: the token was paid, so the refusal books
            # through admission.refuse to keep the cross-check exact
            refusal = self.admission.refuse(tenant, route.reason, booked=booked)
            ticket = GlobalTicket(
                rid=rid,
                tenant=tenant,
                check=check,
                cluster=UNROUTED_CLUSTER,
                outcome=OUTCOME_REFUSED,
                reason=refusal.reason,
            )
            self._book(booked, ticket)
            return ticket
        cluster = route.cluster
        door = self._doors.get(cluster)
        if door is not None:
            inner = door.submit(FEDERATION_TENANT, check, freshness)
            ticket = GlobalTicket(
                rid=rid,
                tenant=tenant,
                check=check,
                cluster=cluster,
                outcome=inner.outcome,
                matched=route.matched,
                reason=inner.reason,
                ticket=inner,
            )
            if inner.outcome == OUTCOME_REFUSED:
                # the cluster's door refused an admitted request (full
                # parking lot, unrouted shard): a post-admission refusal
                # at this level too, same reason, same exact books
                self.admission.refuse(tenant, inner.reason, booked=booked)
            self._book(booked, ticket)
            return ticket
        forward = self._forwarders.get(cluster)
        if forward is not None:
            handle = forward(tenant, check, freshness)
            ticket = GlobalTicket(
                rid=rid,
                tenant=tenant,
                check=check,
                cluster=cluster,
                outcome=OUTCOME_FORWARDED,
                matched=route.matched,
                forwarded=handle,
            )
            self._book(booked, ticket)
            return ticket
        # routed to a cluster nothing is wired for: a structured
        # post-admission refusal naming the cluster, never an exception
        refusal = self.admission.refuse(
            tenant, REFUSE_CLUSTER_UNATTACHED, booked=booked
        )
        ticket = GlobalTicket(
            rid=rid,
            tenant=tenant,
            check=check,
            cluster=cluster,
            outcome=OUTCOME_REFUSED,
            matched=route.matched,
            reason=refusal.reason,
        )
        self._book(booked, ticket)
        return ticket

    # -- accounting ------------------------------------------------------
    def _book(self, booked: str, ticket: GlobalTicket) -> None:
        cell = self._cells.setdefault(booked, {}).setdefault(
            ticket.cluster, _Cell()
        )
        cell.submitted += 1
        if ticket.outcome == OUTCOME_HIT:
            cell.cache_hits += 1
        elif ticket.outcome == OUTCOME_JOINED:
            cell.joins += 1
        elif ticket.outcome == OUTCOME_RUN:
            cell.runs += 1
        elif ticket.outcome == OUTCOME_PARKED:
            cell.parked += 1
        elif ticket.outcome == OUTCOME_FORWARDED:
            cell.forwarded += 1
        else:
            cell.refused += 1
            cell.refusals[ticket.reason] = (
                cell.refusals.get(ticket.reason, 0) + 1
            )
        if self.metrics is not None:
            self.metrics.record_federation_request(
                ticket.cluster, ticket.outcome
            )
            if ticket.outcome == OUTCOME_REFUSED:
                self.metrics.record_federation_refusal(booked, ticket.reason)

    def conservation(self) -> dict:
        """The federation-level conservation table: per (tenant,
        cluster) cell

            submitted == cache_hits + joins + runs + parked
                         + refused + forwarded

        exactly, the per-tenant rows sum their cells, AND the summed
        outcome ledger must agree with the global admission
        controller's independent event-time ledger (submitted ==
        admitted + pre-admission refusals; admitted == non-refused
        outcomes + post-admission refusals) — so a routing bug cannot
        hide demand between clusters, and a quota bug cannot hide
        behind balanced per-cluster books."""
        tenants = sorted(
            set(self._cells)
            | set(self.admission.admitted)
            | set(self.admission.refused)
        )
        rows: Dict[str, dict] = {}
        all_ok = True
        for tenant in tenants:
            cells = self._cells.get(tenant, {})
            clusters = {
                cluster: cells[cluster].to_dict()
                for cluster in sorted(cells)
            }
            total = _Cell()
            for cell in cells.values():
                total.submitted += cell.submitted
                total.cache_hits += cell.cache_hits
                total.joins += cell.joins
                total.runs += cell.runs
                total.parked += cell.parked
                total.refused += cell.refused
                total.forwarded += cell.forwarded
                for reason, count in cell.refusals.items():
                    total.refusals[reason] = (
                        total.refusals.get(reason, 0) + count
                    )
            refused_by_reason = self.admission.refused.get(tenant, {})
            admitted = self.admission.admitted.get(tenant, 0)
            pre = sum(
                refused_by_reason.get(r, 0) for r in PRE_ADMISSION_REASONS
            )
            post = sum(refused_by_reason.values()) - pre
            row = total.to_dict()
            row["clusters"] = clusters
            row["admitted"] = admitted
            non_refused = (
                total.cache_hits
                + total.joins
                + total.runs
                + total.parked
                + total.forwarded
            )
            row["ok"] = (
                total.submitted == total.outcomes()
                and all(c["ok"] for c in clusters.values())
                and total.submitted == admitted + pre
                and admitted == non_refused + post
            )
            all_ok = all_ok and row["ok"]
            rows[tenant] = row
        return {
            "tenants": rows,
            "submitted": sum(r["submitted"] for r in rows.values()),
            "refused": sum(r["refused"] for r in rows.values()),
            "forwarded": sum(r["forwarded"] for r in rows.values()),
            "ok": all_ok,
        }

    def snapshot(self) -> dict:
        """The global door's half of the /statusz federation block."""
        conservation = self.conservation()
        per_cluster: Dict[str, Dict[str, int]] = {}
        for cells in self._cells.values():
            for cluster, cell in cells.items():
                agg = per_cluster.setdefault(
                    cluster,
                    {
                        "submitted": 0,
                        "cache_hits": 0,
                        "coalesced_joins": 0,
                        "probe_runs": 0,
                        "parked": 0,
                        "refused": 0,
                        "forwarded": 0,
                    },
                )
                agg["submitted"] += cell.submitted
                agg["cache_hits"] += cell.cache_hits
                agg["coalesced_joins"] += cell.joins
                agg["probe_runs"] += cell.runs
                agg["parked"] += cell.parked
                agg["refused"] += cell.refused
                agg["forwarded"] += cell.forwarded
        return {
            "attached": sorted(self._doors),
            "forwarders": sorted(self._forwarders),
            "conservation_ok": conservation["ok"],
            "requests": {
                "submitted": conservation["submitted"],
                "refused": conservation["refused"],
                "forwarded": conservation["forwarded"],
            },
            "per_cluster": {
                cluster: per_cluster[cluster]
                for cluster in sorted(per_cluster)
            },
            "tenants": {
                tenant: {
                    "submitted": row["submitted"],
                    "refused": row["refused"],
                    "forwarded": row["forwarded"],
                    "refusals": row["refusals"],
                    "ok": row["ok"],
                }
                for tenant, row in conservation["tenants"].items()
            },
        }
