"""Argo workflow engine — real Workflow CRs via the Kubernetes API.

Capability-parity backend for cluster deployments
(reference: healthcheck_controller.go:502-534 create, :617 dynamic-client
poll). Import of the ``kubernetes`` package is deferred to construction
so the rest of the framework works where it isn't installed.
"""

from __future__ import annotations

from typing import Optional

WF_GROUP = "argoproj.io"
WF_VERSION = "v1alpha1"
WF_PLURAL = "workflows"


class ArgoWorkflowEngine:
    def __init__(self, api_client=None):
        try:
            from kubernetes import client, config  # type: ignore
        except ImportError as e:  # pragma: no cover - depends on environment
            raise RuntimeError(
                "the 'kubernetes' package is required for ArgoWorkflowEngine; "
                "use LocalProcessEngine or FakeWorkflowEngine instead"
            ) from e
        if api_client is None:  # pragma: no cover - needs a cluster
            try:
                config.load_incluster_config()
            except Exception:
                config.load_kube_config()
        self._api = client.CustomObjectsApi(api_client)

    async def submit(self, manifest: dict) -> str:
        import asyncio

        namespace = manifest.get("metadata", {}).get("namespace", "default")
        created = await asyncio.to_thread(
            self._api.create_namespaced_custom_object,
            WF_GROUP,
            WF_VERSION,
            namespace,
            WF_PLURAL,
            manifest,
        )
        return created["metadata"]["name"]

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        import asyncio

        from kubernetes.client.rest import ApiException  # type: ignore

        try:
            return await asyncio.to_thread(
                self._api.get_namespaced_custom_object,
                WF_GROUP,
                WF_VERSION,
                namespace,
                WF_PLURAL,
                name,
            )
        except ApiException as e:
            if e.status == 404:
                return None
            raise
