"""Scenario-matrix observatory (ISSUE 12 — analysis/matrix.py).

Covers the declarative expansion (every impossible combination is a
structured per-cell skip, never a crash or a silent hole), the durable
BENCH_BASELINES.json sidecar (defensive restore), and the closed
regression loop: scripted timings seed a regression into one cell
across two rounds — the hysteresis verdict escalates to degraded, the
roofline stamp names the moved ceiling, exactly one auto-bisect re-run
and one flight bundle fire, and the verdict is visible in `am-tpu
matrix`, /statusz, and the pinned gauges; a lone-outlier round does
not flap. The quick 2-cell real-executor slice runs in tier-1; the
full default matrix rides the slow tier.
"""

import json

import pytest

from activemonitor_tpu.analysis import baseline as baseline_store
from activemonitor_tpu.analysis import matrix as matrix_mod
from activemonitor_tpu.analysis.detector import (
    Hysteresis,
    LEVEL_DEGRADED,
    LEVEL_OK,
    LEVEL_WARNING,
)
from activemonitor_tpu.metrics.collector import MetricsCollector
from activemonitor_tpu.obs.flightrec import KIND_MATRIX, FlightRecorder
from activemonitor_tpu.probes.rated import RatedSpec
from activemonitor_tpu.utils.clock import FakeClock

RATED = RatedSpec(
    "v5e", bf16_tflops=197.0, hbm_gbps=819.0, ici_unidir_gbps=45.0, ici_links=4
)


# ---------------------------------------------------------------------
# expansion edge cases
# ---------------------------------------------------------------------


def skip_codes(skipped):
    return {r.cell.cell_id: r.details["skip"]["code"] for r in skipped}


def test_expand_missing_axis_is_a_structured_skip_naming_the_axis():
    spec = {"ops": ["ring"], "meshes": [{"ep": 8}], "dtypes": ["f32"]}
    cells, skipped = matrix_mod.expand(spec)
    assert cells == []
    [result] = skipped
    assert result.status == matrix_mod.STATUS_SKIPPED
    assert result.details["skip"]["code"] == matrix_mod.SKIP_MISSING_AXIS
    # the skip names the mesh/axis the cell lacked
    assert "'sp'" in result.reason
    assert "ep" in result.reason


def test_expand_unsupported_dtype_is_a_structured_skip_naming_the_dtype():
    spec = {"ops": ["decode"], "meshes": [{}], "dtypes": ["bf16"]}
    cells, skipped = matrix_mod.expand(spec)
    assert cells == []
    [result] = skipped
    assert result.details["skip"]["code"] == matrix_mod.SKIP_UNSUPPORTED_DTYPE
    assert "bfloat16" in result.reason
    assert "float32" in result.reason  # what it DOES support


def test_expand_unknown_op_and_dtype_tokens_never_crash():
    spec = {
        "ops": ["warp-drive", "flash"],
        "meshes": [{}],
        "dtypes": ["complex128", "f32"],
    }
    cells, skipped = matrix_mod.expand(spec)
    assert [c.cell_id for c in cells] == ["flash/1chip/f32"]
    codes = {r.details["skip"]["code"] for r in skipped}
    assert matrix_mod.SKIP_UNKNOWN_OP in codes
    assert matrix_mod.SKIP_UNKNOWN_DTYPE in codes


def test_expand_insufficient_devices_is_a_structured_skip_with_counts():
    spec = {"ops": ["ring"], "meshes": [{"sp": 64}], "dtypes": ["f32"]}
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    assert cells == []
    [result] = skipped
    assert result.details["skip"]["code"] == matrix_mod.SKIP_DEVICES
    assert "64" in result.reason and "8" in result.reason


def test_expand_dedupes_cells_that_agree_on_required_axes():
    # flash shards over no axis: three meshes, ONE cell
    spec = {
        "ops": ["flash"],
        "meshes": [{"sp": 8}, {"ep": 8}, {}],
        "dtypes": ["f32"],
        "schedules": ["auto", "rsag"],
    }
    cells, skipped = matrix_mod.expand(spec)
    # and a collective-free op does not multiply over schedule variants
    assert [c.cell_id for c in cells] == ["flash/1chip/f32"]
    assert skipped == []


def test_expand_default_spec_covers_every_op_on_the_test_platform():
    spec, warning = matrix_mod.load_spec(None)
    assert warning is None
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    assert {c.op for c in cells} == set(spec["ops"])
    assert skipped  # the honest holes: ops x meshes that don't combine
    quick = matrix_mod.quick_slice(cells)
    assert len(quick) == 2
    assert all(c.devices_needed == 1 for c in quick)


def test_load_spec_corrupt_file_degrades_to_default_with_warning(tmp_path):
    path = tmp_path / "matrix.json"
    path.write_text("{never json")
    spec, warning = matrix_mod.load_spec(str(path))
    assert spec["ops"] == matrix_mod.DEFAULT_SPEC["ops"]
    assert warning["reason"] == "spec-unreadable"
    # a list top level is a shape warning, same fallback
    path.write_text("[1, 2]")
    spec, warning = matrix_mod.load_spec(str(path))
    assert spec["ops"] == matrix_mod.DEFAULT_SPEC["ops"]
    assert warning["reason"] == "spec-shape"
    # and a missing file is simply the default (config is optional)
    spec, warning = matrix_mod.load_spec(str(tmp_path / "absent.json"))
    assert spec["ops"] and warning is None


# ---------------------------------------------------------------------
# hysteresis jump-to-raw (the matrix contract on detector.py)
# ---------------------------------------------------------------------


def test_hysteresis_jump_to_raw_escalates_to_confirmed_level():
    state = Hysteresis(confirm_runs=2, calm_runs=3, jump_to_raw=True)
    assert state.update(LEVEL_DEGRADED) is None  # lone outlier: no move
    assert state.update(LEVEL_DEGRADED) == (LEVEL_OK, LEVEL_DEGRADED)
    # the jump lands on the WEAKEST level the streak sustained
    state = Hysteresis(confirm_runs=2, calm_runs=3, jump_to_raw=True)
    assert state.update(LEVEL_DEGRADED) is None
    assert state.update(LEVEL_WARNING) == (LEVEL_OK, LEVEL_WARNING)
    # recovery stays one deliberate step per calm streak
    state = Hysteresis(confirm_runs=2, calm_runs=2, jump_to_raw=True)
    state.update(LEVEL_DEGRADED)
    state.update(LEVEL_DEGRADED)
    assert state.update(LEVEL_OK) is None
    assert state.update(LEVEL_OK) == (LEVEL_DEGRADED, LEVEL_WARNING)


def test_hysteresis_floor_roundtrips_and_stays_out_of_calm_blobs():
    state = Hysteresis(confirm_runs=3, jump_to_raw=True)
    state.update(LEVEL_DEGRADED)
    doc = json.loads(json.dumps(state.to_dict()))
    assert doc["floor"] == LEVEL_DEGRADED
    restored = Hysteresis.from_dict(doc, 3, 3, jump_to_raw=True)
    assert restored.up_floor == LEVEL_DEGRADED
    assert restored.update(LEVEL_DEGRADED) is None
    assert restored.update(LEVEL_DEGRADED) == (LEVEL_OK, LEVEL_DEGRADED)
    # a calm state serializes without the floor key (pre-existing
    # .status.analysis blobs stay byte-identical)
    assert "floor" not in Hysteresis().to_dict()


# ---------------------------------------------------------------------
# durable sidecar (analysis/baseline.py blob helpers)
# ---------------------------------------------------------------------


def test_blob_roundtrip_and_defensive_restores(tmp_path):
    path = str(tmp_path / "BENCH_BASELINES.json")
    assert baseline_store.load_blob(path) == (None, None)  # first round
    assert baseline_store.save_blob(path, {"x": 1}) is None
    doc, warning = baseline_store.load_blob(path)
    assert warning is None and doc["x"] == 1
    assert doc["blob_version"] == baseline_store.BLOB_VERSION

    (tmp_path / "BENCH_BASELINES.json").write_text("{truncated")
    doc, warning = baseline_store.load_blob(path)
    assert doc is None and warning["reason"] == "corrupt-json"

    (tmp_path / "BENCH_BASELINES.json").write_text('["not", "an", "object"]')
    doc, warning = baseline_store.load_blob(path)
    assert doc is None and warning["reason"] == "corrupt-shape"

    (tmp_path / "BENCH_BASELINES.json").write_text(
        json.dumps({"blob_version": 999, "x": 1})
    )
    doc, warning = baseline_store.load_blob(path)
    assert doc is None and warning["reason"] == "version-skew"
    assert "999" in warning["detail"]


def test_observatory_restores_fresh_from_corrupt_sidecar_with_warning(tmp_path):
    path = tmp_path / "BENCH_BASELINES.json"
    path.write_text("}{")
    observatory = matrix_mod.MatrixObservatory(
        clock=FakeClock(), path=str(path)
    )
    assert observatory.restore_warning["reason"] == "corrupt-json"
    assert observatory.baselines.metrics() == []
    # the warning rides the round summary so the artifact says WHY the
    # baselines started over
    summary = observatory.observe_round([])
    assert summary["restore_warning"]["reason"] == "corrupt-json"
    # and the round save repairs the sidecar for the next reader
    doc, warning = baseline_store.load_blob(str(path))
    assert warning is None and doc["last_round"]["cells"] == {}


# ---------------------------------------------------------------------
# the closed loop (acceptance)
# ---------------------------------------------------------------------

CELL = matrix_mod.CellSpec("flash", (), "bfloat16", "-")


def scripted(seconds, cell=CELL):
    """A scripted measurement: 4 GFLOP over 2 MB — compute-bound on the
    v5e roofline, so the stamp should name the compute ceiling."""
    return matrix_mod.CellResult(
        cell,
        matrix_mod.STATUS_OK,
        value=seconds,
        seconds=seconds,
        flops=4e9,
        bytes_accessed=2e6,
    )


class ScriptedExecutor:
    """Bisect executor returning a fixed re-run value, counting calls."""

    def __init__(self, seconds):
        self.seconds = seconds
        self.calls = 0

    def __call__(self, cell):
        self.calls += 1
        return scripted(self.seconds, cell)


def build_observatory(tmp_path, **kwargs):
    clock = FakeClock()
    recorder = FlightRecorder(clock=clock)
    collector = MetricsCollector()
    observatory = matrix_mod.MatrixObservatory(
        clock=clock,
        path=str(tmp_path / "BENCH_BASELINES.json"),
        warmup_runs=3,
        confirm_runs=2,
        calm_runs=3,
        rated_spec=RATED,
        metrics=collector,
        flightrec=recorder,
        **kwargs,
    )
    return observatory, recorder, collector, clock


def tick(clock, seconds=60.0):
    # FakeClock.advance is async (it wakes sleepers); matrix rounds only
    # need the timestamp to move
    clock._t += seconds


def observe(observatory, clock, seconds, executor=None):
    tick(clock)
    return observatory.observe_round(
        [scripted(seconds)], executor=executor, interpret_mode=True
    )


def test_closed_loop_regression_escalates_bisects_and_bundles(tmp_path):
    observatory, recorder, collector, clock = build_observatory(tmp_path)
    executor = ScriptedExecutor(0.004)  # the re-run still reproduces
    for _ in range(5):
        summary = observe(observatory, clock, 0.001, executor)
        assert summary["cells"][CELL.cell_id]["verdict"] == "ok"
        assert summary["regressions"] == []
    # roofline stamped on every round, model-sourced, compute-bound
    stamp = summary["cells"][CELL.cell_id]["roofline"]
    assert stamp["bound"] == "compute"
    assert stamp["cost_source"] == "model"

    # a lone noisy round never flaps: no transition, no bisect, no bundle
    summary = observe(observatory, clock, 0.004, executor)
    assert summary["cells"][CELL.cell_id]["verdict"] == "ok"
    assert summary["regressions"] == [] and summary["bisects"] == []
    assert executor.calls == 0
    summary = observe(observatory, clock, 0.001, executor)
    assert summary["cells"][CELL.cell_id]["verdict"] == "ok"

    # seed the regression across two rounds: the verdict escalates to
    # degraded on the confirming round
    first = observe(observatory, clock, 0.004, executor)
    assert first["cells"][CELL.cell_id]["verdict"] == "ok"
    assert first["regressions"] == []
    second = observe(observatory, clock, 0.004, executor)
    entry = second["cells"][CELL.cell_id]
    assert entry["verdict"] == "degraded"
    assert entry["vs_baseline"] == pytest.approx(4.0)

    # the regression names the moved ceiling from the roofline stamp
    [regression] = second["regressions"]
    assert regression["cell"] == CELL.cell_id
    assert regression["ceiling"] == "compute"
    assert regression["cost_source"] == "model"

    # exactly one auto-bisect re-run fired, and it reproduced
    assert executor.calls == 1
    [bisect] = second["bisects"]
    assert bisect["outcome"] == matrix_mod.BISECT_REPRODUCED
    assert bisect["round_value"] == 0.004
    assert bisect["prior_value"] == 0.004  # the prior artifact's value
    assert bisect["rerun_value"] == 0.004

    # exactly one flight bundle, carrying BOTH artifacts' evidence
    [bundle] = recorder.bundles(kind=KIND_MATRIX)
    assert bundle["check"] == f"matrix/{CELL.cell_id}"
    assert bundle["extra"]["cell"]["verdict"] == "degraded"
    assert bundle["extra"]["prior_cell"]["value"] == 0.004
    assert bundle["extra"]["bisect"]["outcome"] == "reproduced"

    # the verdict is visible on the pinned gauges
    cell_label = "flash_1chip_bf16"
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_state",
            {"cell": cell_label, "state": "degraded"},
        )
        == 1.0
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_value",
            {"cell": cell_label, "metric": "seconds"},
        )
        == 0.004
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_bisect_runs_total",
            {"cell": cell_label, "outcome": "reproduced"},
        )
        == 1.0
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_roofline_fraction",
            {"cell": cell_label, "bound": "compute"},
        )
        == pytest.approx(stamp["fraction"] / 4.0, rel=1e-3)
    )

    # ...and in `am-tpu matrix` and /statusz
    from activemonitor_tpu.__main__ import render_matrix
    from activemonitor_tpu.obs.slo import FleetStatus, rollup_statusz

    text = render_matrix(second)
    assert "REGRESSION flash/1chip/bf16" in text
    assert "ceiling=compute" in text
    assert "bisect=reproduced" in text
    assert "degraded" in text

    fleet = FleetStatus(clock, MetricsCollector())
    fleet.matrix = observatory
    payload = json.loads(json.dumps(fleet.statusz([])))
    assert (
        payload["fleet"]["matrix"]["cells"][CELL.cell_id]["verdict"]
        == "degraded"
    )
    # the rollup carries the newest round over matrix-less replicas
    bare = FleetStatus(clock, MetricsCollector())
    merged = rollup_statusz([bare.statusz([]), payload])
    assert merged["fleet"]["matrix"]["cells"][CELL.cell_id]["verdict"] == (
        "degraded"
    )

    # still exactly one bisect/bundle after another degraded round (the
    # hysteresis already reports degraded: no new transition)
    third = observe(observatory, clock, 0.004, executor)
    assert third["regressions"] == [] and third["bisects"] == []
    assert executor.calls == 1
    assert len(recorder.bundles(kind=KIND_MATRIX)) == 1


def test_bisect_recovered_when_rerun_is_healthy(tmp_path):
    observatory, recorder, _collector, clock = build_observatory(tmp_path)
    executor = ScriptedExecutor(0.001)  # the re-run comes back healthy
    for _ in range(4):
        observe(observatory, clock, 0.001, executor)
    observe(observatory, clock, 0.004, executor)
    summary = observe(observatory, clock, 0.004, executor)
    [bisect] = summary["bisects"]
    assert bisect["outcome"] == matrix_mod.BISECT_RECOVERED
    [bundle] = recorder.bundles(kind=KIND_MATRIX)
    assert bundle["extra"]["bisect"]["outcome"] == "recovered"


def test_error_and_skipped_cells_never_feed_baselines(tmp_path):
    observatory, _recorder, _collector, clock = build_observatory(tmp_path)
    broken = matrix_mod.CellResult(CELL, matrix_mod.STATUS_ERROR, reason="boom")
    other = matrix_mod.CellSpec("decode", (), "float32", "-")
    skipped = matrix_mod.skipped_result(
        other, matrix_mod.SKIP_DEVICES, "needs 64 devices, have 8"
    )
    tick(clock)
    # a duplicate cell_id contributes one row and one count (the header
    # and the table must never disagree)
    summary = observatory.observe_round([broken, skipped, skipped])
    assert summary["counts"] == {"ok": 0, "skipped": 1, "error": 1}
    assert len(summary["cells"]) == 2
    entry = summary["cells"][CELL.cell_id]
    assert "verdict" not in entry
    assert observatory.baselines.metrics() == []


def test_round_survives_restart_through_the_sidecar(tmp_path):
    observatory, _recorder, _collector, clock = build_observatory(tmp_path)
    executor = ScriptedExecutor(0.004)
    for _ in range(4):
        observe(observatory, clock, 0.001, executor)
    observe(observatory, clock, 0.004, executor)

    # a fresh process restores baselines AND the mid-escalation streak:
    # the confirming round after restart still escalates to degraded
    restored, recorder2, _collector2, clock2 = build_observatory(tmp_path)
    assert restored.restore_warning is None
    summary = observe(restored, clock2, 0.004, ScriptedExecutor(0.004))
    assert summary["cells"][CELL.cell_id]["verdict"] == "degraded"
    assert len(recorder2.bundles(kind=KIND_MATRIX)) == 1
    # and the sidecar view serves the restored round to /statusz
    view = matrix_mod.SidecarView(str(tmp_path / "BENCH_BASELINES.json"))
    assert view.snapshot()["cells"][CELL.cell_id]["verdict"] == "degraded"


def test_sidecar_view_reports_structured_warning_on_corrupt_blob(tmp_path):
    path = tmp_path / "BENCH_BASELINES.json"
    view = matrix_mod.SidecarView(str(path))
    assert view.snapshot() is None  # no rounds yet
    path.write_text("{nope")
    snapshot = view.snapshot()
    assert snapshot["restore_warning"]["reason"] == "corrupt-json"
    assert snapshot["cells"] == {}
    # and render_matrix surfaces it instead of crashing
    from activemonitor_tpu.__main__ import render_matrix

    assert "sidecar restored fresh: corrupt-json" in render_matrix(snapshot)


def test_fallback_reason_and_interpret_mode_ride_every_cell(tmp_path):
    observatory, _recorder, _collector, clock = build_observatory(tmp_path)
    other = matrix_mod.CellSpec("decode", (), "float32", "-")
    skipped = matrix_mod.skipped_result(
        other, matrix_mod.SKIP_DEVICES, "needs 8 devices, have 1"
    )
    tick(clock)
    summary = observatory.observe_round(
        [scripted(0.001), skipped],
        interpret_mode=True,
        fallback_reason="device probe hung past 120s (wedged tunnel?)",
    )
    assert summary["interpret_mode"] is True
    assert summary["fallback_reason"].startswith("device probe hung")
    for entry in summary["cells"].values():
        # EVERY cell — measured and skipped alike — carries the labels
        assert entry["interpret_mode"] is True
        assert entry["fallback_reason"].startswith("device probe hung")


# ---------------------------------------------------------------------
# the real executor (quick slice in tier-1, full matrix on the slow tier)
# ---------------------------------------------------------------------


def run_real_cells(cells, tmp_path):
    executor = matrix_mod.make_executor(iters=1)
    observatory = matrix_mod.MatrixObservatory(
        clock=FakeClock(), path=str(tmp_path / "BENCH_BASELINES.json")
    )
    results = [executor(cell) for cell in cells]
    return observatory.observe_round(
        results, executor=executor, interpret_mode=True
    )


def test_quick_slice_measures_real_cells_on_the_cpu_platform(tmp_path):
    spec, _warning = matrix_mod.load_spec(None)
    cells, _skipped = matrix_mod.expand(spec, n_devices=8)
    summary = run_real_cells(matrix_mod.quick_slice(cells), tmp_path)
    assert summary["counts"]["ok"] == 2
    for entry in summary["cells"].values():
        assert entry["status"] == "ok"
        assert entry["value"] > 0
        # interpret mode: no rated roofline — the omission is a
        # structured skip, never a silent hole
        assert "skipped" in entry["roofline"]
        assert entry["verdict"] == "ok"


@pytest.mark.slow  # the full default matrix: 11 cells incl. 8-device meshes
def test_full_matrix_soak_runs_every_default_cell(tmp_path):
    spec, _warning = matrix_mod.load_spec(None)
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    summary = run_real_cells(cells, tmp_path)
    assert summary["counts"]["ok"] == len(cells)
    assert summary["counts"]["error"] == 0
    by_op = {matrix_mod.CellSpec(**{  # noqa: F841 - readability only
        "op": c.op, "mesh": c.mesh, "dtype": c.dtype, "schedule": c.schedule
    }).op for c in cells}
    assert by_op == set(spec["ops"])
    # collective-riding cells resolved a schedule from the table (or
    # the XLA fallback when nothing is tuned)
    for cell_id, entry in summary["cells"].items():
        if entry["schedule_requested"] == "auto":
            assert entry["schedule"], cell_id
        if cell_id.startswith("training-step/"):
            # the default mesh carries model=2, which gates the tuned
            # sync back to the XLA-inserted reduction — the stamp must
            # report what RAN, not the requested token
            assert entry["schedule"] == "xla(implicit)", entry
    # the skips stayed structured (SKIP_DEVICES: the default spec's
    # deliberate dcn2xici8 single-process impossibility)
    assert all(
        r.details["skip"]["code"] in (
            matrix_mod.SKIP_MISSING_AXIS,
            matrix_mod.SKIP_UNSUPPORTED_DTYPE,
            matrix_mod.SKIP_DEVICES,
        )
        for r in skipped
    )
    assert any(
        r.details["skip"]["code"] == matrix_mod.SKIP_DEVICES
        and r.cell.mesh_id == "dcn2xici8"
        for r in skipped
    )


def test_expand_alias_dtype_tokens_dedupe_to_one_cell_and_one_skip():
    # "bf16" and "bfloat16" canonicalize identically: one row, one
    # count — runnable or skip — so the counts header and the table can
    # never disagree
    spec = {
        "ops": ["flash", "decode"],
        "meshes": [{}],
        "dtypes": ["bf16", "bfloat16"],
    }
    cells, skipped = matrix_mod.expand(spec)
    assert [c.cell_id for c in cells] == ["flash/1chip/bf16"]
    assert [r.cell.cell_id for r in skipped] == ["decode/1chip/bf16"]


def test_hysteresis_floor_resets_when_the_streak_breaks():
    # an ordinary (non-jump) check that sees one noisy run must not
    # serialize a stale "floor" key forever
    state = Hysteresis(confirm_runs=3)
    state.update(LEVEL_WARNING)
    assert "floor" in state.to_dict()
    state.update(LEVEL_OK)  # streak broken
    assert "floor" not in state.to_dict()


def test_collector_drops_series_of_cells_removed_from_the_spec():
    collector = MetricsCollector()
    degraded_round = {
        "cells": {
            "ring/sp8/bf16": {
                "status": "ok",
                "metric": "seconds",
                "value": 0.005,
                "verdict": "degraded",
                "roofline": {"bound": "comm", "fraction": 0.2},
            }
        },
        "bisects": [],
    }
    collector.record_matrix_round(degraded_round)
    labels = {"cell": "ring_sp8_bf16", "state": "degraded"}
    assert collector.sample_value("healthcheck_matrix_cell_state", labels) == 1.0
    # the operator renames the cell away: the next round must drop the
    # old series instead of alerting degraded=1 until restart
    collector.record_matrix_round({"cells": {}, "bisects": []})
    assert collector.sample_value("healthcheck_matrix_cell_state", labels) is None
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_value",
            {"cell": "ring_sp8_bf16", "metric": "seconds"},
        )
        is None
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_roofline_fraction",
            {"cell": "ring_sp8_bf16", "bound": "comm"},
        )
        is None
    )


def test_controller_exports_matrix_gauges_from_sidecar_once_per_round(tmp_path):
    from activemonitor_tpu.obs.slo import FleetStatus

    observatory, _recorder, _collector, clock = build_observatory(tmp_path)
    executor = ScriptedExecutor(0.004)
    for _ in range(4):
        observe(observatory, clock, 0.001, executor)
    observe(observatory, clock, 0.004, executor)
    observe(observatory, clock, 0.004, executor)  # confirmed: 1 bisect

    collector = MetricsCollector()
    fleet = FleetStatus(FakeClock(), collector)
    fleet.matrix = matrix_mod.SidecarView(
        str(tmp_path / "BENCH_BASELINES.json")
    )
    fleet.refresh_matrix_metrics()
    bisect_labels = {"cell": "flash_1chip_bf16", "outcome": "reproduced"}
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_state",
            {"cell": "flash_1chip_bf16", "state": "degraded"},
        )
        == 1.0
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_bisect_runs_total", bisect_labels
        )
        == 1.0
    )
    # the rollup loop re-serving an UNCHANGED sidecar must not
    # double-count the bisect counter
    fleet.refresh_matrix_metrics()
    assert (
        collector.sample_value(
            "healthcheck_matrix_bisect_runs_total", bisect_labels
        )
        == 1.0
    )
    # a controller without --matrix-state is a no-op
    FleetStatus(FakeClock(), MetricsCollector()).refresh_matrix_metrics()


def test_sidecar_view_caches_on_mtime_and_size(tmp_path, monkeypatch):
    import os

    path = tmp_path / "BENCH_BASELINES.json"
    baseline_store.save_blob(str(path), {"last_round": {"cells": {"a": {}}}})
    view = matrix_mod.SidecarView(str(path))
    assert view.snapshot()["cells"] == {"a": {}}
    # unchanged file: the parse must not re-run
    monkeypatch.setattr(
        baseline_store,
        "load_blob",
        lambda _p: (_ for _ in ()).throw(AssertionError("reparsed")),
    )
    assert view.snapshot()["cells"] == {"a": {}}
    monkeypatch.undo()
    # a new round re-reads (mtime/size move)
    baseline_store.save_blob(str(path), {"last_round": {"cells": {"b": {}}}})
    os.utime(path, (1e9, 1e9))
    assert view.snapshot()["cells"] == {"b": {}}


def test_baselines_are_scoped_per_platform_mode(tmp_path):
    # TPU-learned seconds must never judge a CPU-fallback round (the
    # r02-r05 wedge scenario): each mode warms its own baseline
    observatory, recorder, _collector, clock = build_observatory(tmp_path)
    # 4 GFLOP in 22 us = 0.92 of the v5e compute ceiling: a HEALTHY
    # TPU reading (the rated-floor detector judges tpu-mode fractions
    # absolutely, so the scripted value must sit ON the roofline)
    tpu_seconds = 2.2e-5
    executor = ScriptedExecutor(tpu_seconds)
    for _ in range(5):
        tick(clock)
        summary = observatory.observe_round(
            [scripted(tpu_seconds)], executor=executor, interpret_mode=False
        )
        assert summary["cells"][CELL.cell_id]["verdict"] == "ok"
    # the tunnel wedges: interpret rounds run ~50x slower — platform
    # noise, not a regression; no verdict, no bisect, no bundle
    for _ in range(3):
        tick(clock)
        summary = observatory.observe_round(
            [scripted(0.1)], executor=executor, interpret_mode=True,
            fallback_reason="wedged tunnel",
        )
        assert summary["cells"][CELL.cell_id]["verdict"] == "ok"
        assert summary["regressions"] == []
    assert executor.calls == 0
    assert recorder.bundles(kind=KIND_MATRIX) == []
    # recovery back to TPU: the tpu-mode baseline is untainted
    tick(clock)
    summary = observatory.observe_round(
        [scripted(tpu_seconds)], executor=executor, interpret_mode=False
    )
    assert summary["cells"][CELL.cell_id]["verdict"] == "ok"


def test_internal_dispatch_ops_do_not_expand_over_schedule_variants():
    # moe's token gather is an internal autotune.all_gather("auto"):
    # explicit variants cannot be threaded in, so expanding them would
    # label identical runs as distinct scenarios
    spec = {
        "ops": ["moe", "pipeline"],
        "meshes": [{"ep": 8, "pp": 2}],
        "dtypes": ["f32"],
        "schedules": ["auto", "rsag"],
    }
    cells, _skipped = matrix_mod.expand(spec)
    assert [c.cell_id for c in cells] == [
        "moe/ep8/f32/auto",
        "pipeline/pp2/f32/auto",
        "pipeline/pp2/f32/rsag",
    ]


def test_insufficient_devices_skips_dedupe_alias_dtypes():
    spec = {
        "ops": ["ring"],
        "meshes": [{"sp": 8}],
        "dtypes": ["bf16", "bfloat16"],
    }
    cells, skipped = matrix_mod.expand(spec, n_devices=4)
    assert cells == []
    assert [r.cell.cell_id for r in skipped] == ["ring/sp8/bf16"]


def test_tpu_mode_double_metric_regression_fires_exactly_one_bisect(tmp_path):
    # on a real-TPU round both 'seconds' and 'roofline-fraction' can
    # confirm degraded together — that is ONE regression: one re-run,
    # one bundle (the documented invariant), with per-metric regression
    # entries sharing the bisect outcome
    observatory, recorder, collector, clock = build_observatory(tmp_path)
    healthy, sick = 2.2e-5, 8.8e-5  # 0.92 -> 0.23 of the compute ceiling
    executor = ScriptedExecutor(sick)
    for _ in range(5):
        tick(clock)
        summary = observatory.observe_round(
            [scripted(healthy)], executor=executor, interpret_mode=False
        )
        assert summary["cells"][CELL.cell_id]["verdict"] == "ok"
    for _ in range(2):
        tick(clock)
        summary = observatory.observe_round(
            [scripted(sick)], executor=executor, interpret_mode=False
        )
    entry = summary["cells"][CELL.cell_id]
    assert entry["verdict"] == "degraded"
    # both metrics transitioned, but exactly one re-run and one bundle
    assert len(summary["regressions"]) == 2
    assert {r["metric"] for r in summary["regressions"]} == {
        "seconds", "roofline-fraction",
    }
    assert len(summary["bisects"]) == 1
    assert executor.calls == 1
    assert len(recorder.bundles(kind=KIND_MATRIX)) == 1
    assert (
        collector.sample_value(
            "healthcheck_matrix_bisect_runs_total",
            {"cell": "flash_1chip_bf16", "outcome": "reproduced"},
        )
        == 1.0
    )


def test_collector_drops_state_series_when_cell_flips_to_skipped():
    # a degraded cell whose next round is skipped (the TPU wedged to a
    # smaller fallback platform) has no fresh verdict: the stale
    # degraded one-hot and roofline fraction must drop, not alert on
    # last round's evidence forever
    collector = MetricsCollector()
    collector.record_matrix_round(
        {
            "cells": {
                "ring/sp8/bf16": {
                    "status": "ok",
                    "metric": "seconds",
                    "value": 0.005,
                    "verdict": "degraded",
                    "roofline": {"bound": "comm", "fraction": 0.3},
                }
            },
            "bisects": [],
        }
    )
    collector.record_matrix_round(
        {
            "cells": {
                "ring/sp8/bf16": {
                    "status": "skipped",
                    "reason": "insufficient-devices: needs 8, have 1",
                }
            },
            "bisects": [],
        }
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_state",
            {"cell": "ring_sp8_bf16", "state": "degraded"},
        )
        is None
    )
    assert (
        collector.sample_value(
            "healthcheck_matrix_cell_roofline_fraction",
            {"cell": "ring_sp8_bf16", "bound": "comm"},
        )
        is None
    )
    # ...and the skipped cell still counts in the round totals
    assert (
        collector.sample_value("healthcheck_matrix_cells", {"status": "skipped"})
        == 1.0
    )


def test_dtype_skips_carry_the_canonical_cell_id_across_meshes():
    # one logical scenario = one skip row under the id its runnable
    # siblings would use, however many meshes the spec lists
    spec = {
        "ops": ["decode"],
        "meshes": [{"sp": 8}, {"ep": 8}, {"data": 2, "model": 2, "pp": 2}],
        "dtypes": ["bf16", "f32"],
    }
    cells, skipped = matrix_mod.expand(spec)
    assert [c.cell_id for c in cells] == ["decode/1chip/f32"]
    assert [r.cell.cell_id for r in skipped] == ["decode/1chip/bf16"]


def test_unknown_schedule_token_is_a_structured_skip_not_a_runner_error():
    spec = {
        "ops": ["training-step"],
        "meshes": [{"data": 2, "model": 2}],
        "dtypes": ["f32"],
        "schedules": ["ringz"],  # config typo
    }
    cells, skipped = matrix_mod.expand(spec)
    assert cells == []
    [result] = skipped
    assert result.details["skip"]["code"] == matrix_mod.SKIP_UNKNOWN_SCHEDULE
    assert "ringz" in result.reason and "rsag" in result.reason
    # the mirror stays in lockstep with the probe layer's token set
    from activemonitor_tpu.probes.training_step import GRAD_SYNC_SCHEDULES

    assert set(matrix_mod.KNOWN_SCHEDULES) == set(GRAD_SYNC_SCHEDULES) - {
        "implicit"
    }
