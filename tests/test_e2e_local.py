"""End-to-end slice: the real wiring from SURVEY.md §7 driven in-process.

File-backed store + local process engine + manager — the exact stack
``python -m activemonitor_tpu run --engine local`` assembles — applied a
HealthCheck whose probe really executes as a subprocess, observed
through status, metrics, and events. The local-mode equivalent of the
reference's kind-cluster manual tier (SURVEY.md §4 tier 3), but
automated.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.client_file import FileHealthCheckClient
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine.local import LocalProcessEngine
from activemonitor_tpu.metrics import MetricsCollector

CHECK = """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: e2e-echo, namespace: default}
spec:
  repeatAfterSec: 2
  backoffMax: 1
  backoffMin: 1
  level: cluster
  workflow:
    generateName: e2e-echo-
    workflowtimeout: 10
    resource:
      namespace: default
      serviceAccount: local
      source:
        inline: |
          apiVersion: argoproj.io/v1alpha1
          kind: Workflow
          spec:
            entrypoint: main
            templates:
              - name: main
                container:
                  command: [/bin/sh, -c]
                  args: ['echo "{\\"metrics\\": [{\\"name\\": \\"e2e-gauge\\", \\"value\\": 3.5, \\"metrictype\\": \\"gauge\\", \\"help\\": \\"x\\"}]}"']
"""

FAILING_CHECK = """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: e2e-fail, namespace: default}
spec:
  repeatAfterSec: 3600
  backoffMax: 1
  backoffMin: 1
  level: cluster
  workflow:
    generateName: e2e-fail-
    workflowtimeout: 10
    resource:
      namespace: default
      serviceAccount: local
      source:
        inline: |
          apiVersion: argoproj.io/v1alpha1
          kind: Workflow
          spec:
            entrypoint: main
            templates:
              - name: main
                container:
                  command: [/bin/sh, -c]
                  args: ["echo broken probe; exit 7"]
  remedyworkflow:
    generateName: e2e-remedy-
    resource:
      namespace: default
      serviceAccount: local-remedy
      source:
        inline: |
          apiVersion: argoproj.io/v1alpha1
          kind: Workflow
          spec:
            entrypoint: fix
            templates:
              - name: fix
                container:
                  command: [/bin/true]
"""


async def wait_for(client, name, predicate, timeout=20.0):
    for _ in range(int(timeout / 0.1)):
        hc = await client.get("default", name)
        if hc is not None and predicate(hc):
            return hc
        await asyncio.sleep(0.1)
    raise TimeoutError(name)


@pytest.mark.asyncio
async def test_local_stack_end_to_end(tmp_path):
    client = FileHealthCheckClient(str(tmp_path), poll_seconds=0.1)
    engine = LocalProcessEngine()
    recorder = EventRecorder()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=recorder,
        metrics=metrics,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=4)
    await manager.start()
    try:
        # live apply through the watch path (no manual enqueue)
        await client.apply(HealthCheck.from_yaml(CHECK))
        hc = await wait_for(client, "e2e-echo", lambda h: h.status.success_count >= 1)
        assert hc.status.status == "Succeeded"
        # custom metric flowed probe stdout -> engine outputs -> gauge
        assert (
            metrics.sample_value("e2e_echo_e2e_gauge", {"healthcheck_name": "e2e-echo"})
            == 3.5
        )
        # periodic: a second run arrives on the real clock
        await wait_for(client, "e2e-echo", lambda h: h.status.success_count >= 2)

        # failure path incl. remedy subprocess + rbac cleanup
        await client.apply(HealthCheck.from_yaml(FAILING_CHECK))
        hc = await wait_for(client, "e2e-fail", lambda h: h.status.failed_count >= 1)
        assert hc.status.status == "Failed"
        assert "exited 7" in hc.status.error_message
        hc = await wait_for(
            client, "e2e-fail", lambda h: h.status.remedy_success_count >= 1
        )
        assert hc.status.remedy_status == "Succeeded"
        messages = [e.message for e in recorder.events_for("default", "e2e-fail")]
        assert "Successfully created remedyWorkflow" in messages

        # durability: a fresh client (restart) sees the same status
        fresh = FileHealthCheckClient(str(tmp_path))
        persisted = await fresh.get("default", "e2e-echo")
        assert persisted.status.success_count >= 2
    finally:
        await manager.stop()
