"""Shared utilities."""

from activemonitor_tpu.utils.duration import parse_go_duration

__all__ = ["parse_go_duration"]
