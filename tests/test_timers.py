"""Timer wheel tests (reference behavior: healthcheck_controller.go:745-754
reschedule, :180-184 cancel-on-delete, :264-267 exists-for-dedupe)."""


import pytest

from activemonitor_tpu.scheduler import TimerWheel
from activemonitor_tpu.utils.clock import FakeClock


@pytest.mark.asyncio
async def test_fires_after_delay():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(clock.monotonic())

    wheel.schedule("hc-a", 30, cb)
    await clock.advance(29)
    assert fired == []
    await clock.advance(2)
    assert fired == [30.0]


@pytest.mark.asyncio
async def test_reschedule_replaces_pending_timer():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def mk(tag):
        async def cb():
            fired.append(tag)
        return cb

    wheel.schedule("hc-a", 30, await mk("first"))
    await clock.advance(10)
    wheel.schedule("hc-a", 30, await mk("second"))
    await clock.advance(100)
    assert fired == ["second"]


@pytest.mark.asyncio
async def test_stop_cancels_pending():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(1)

    wheel.schedule("hc-a", 30, cb)
    assert wheel.pending("hc-a")
    assert wheel.stop("hc-a") is True
    await clock.advance(100)
    assert fired == []
    assert not wheel.exists("hc-a")


@pytest.mark.asyncio
async def test_exists_after_firing_for_dedupe():
    clock = FakeClock()
    wheel = TimerWheel(clock)

    async def cb():
        pass

    wheel.schedule("hc-a", 1, cb)
    await clock.advance(5)
    assert wheel.exists("hc-a")  # fired entries remain (dedupe contract)
    assert not wheel.pending("hc-a")
    assert wheel.stop("hc-a") is False  # nothing pending to cancel


@pytest.mark.asyncio
async def test_callback_exception_does_not_kill_wheel(caplog):
    clock = FakeClock()
    wheel = TimerWheel(clock)

    async def boom():
        raise RuntimeError("probe exploded")

    async def ok():
        fired.append(1)

    fired = []
    wheel.schedule("hc-bad", 1, boom)
    wheel.schedule("hc-good", 2, ok)
    await clock.advance(5)
    assert fired == [1]


@pytest.mark.asyncio
async def test_snapshot_reports_pending_fire_times_only():
    clock = FakeClock()
    wheel = TimerWheel(clock)

    async def cb():
        pass

    wheel.schedule("hc-a", 30, cb)
    wheel.schedule("hc-b", 90, cb)
    wheel.schedule("hc-fired", 1, cb)
    await clock.advance(10)
    snap = wheel.snapshot()
    # fired entries carry no pending run and must be absent — restoring
    # them would duplicate a run the old owner already fired
    assert set(snap) == {"hc-a", "hc-b"}
    assert snap["hc-a"] == pytest.approx(20.0)
    assert snap["hc-b"] == pytest.approx(80.0)
    assert wheel.remaining("hc-fired") is None
    await wheel.shutdown()


@pytest.mark.asyncio
async def test_owed_run_adoption_across_owner_change():
    """ISSUE-6 satellite: serialize pending fire times on one wheel (the
    dying shard owner), restore onto a fresh wheel (the adopting owner)
    with the shared injectable Clock, and assert every owed run fires
    EXACTLY once, at its original deadline — no dropped, no duplicated
    runs across the handoff."""
    clock = FakeClock()
    old_owner = TimerWheel(clock)
    fired = []

    def cb_factory(name):
        async def cb():
            fired.append((name, clock.monotonic()))
        return cb

    for i, delay in enumerate((30, 60, 90, 120)):
        old_owner.schedule(f"health/hc-{i}", delay, cb_factory(f"health/hc-{i}"))
    await clock.advance(45)  # hc-0 fires on the OLD owner before it dies
    assert fired == [("health/hc-0", 30.0)]

    # owner change: the dying owner's pending state is serialized, its
    # wheel torn down (crash semantics: every timer task dies with it)
    snap = old_owner.snapshot()
    await old_owner.shutdown()
    assert set(snap) == {"health/hc-1", "health/hc-2", "health/hc-3"}

    new_owner = TimerWheel(clock)
    assert new_owner.restore(snap, cb_factory) == 3
    # no early fire: restored deadlines are the ORIGINAL ones
    await clock.advance(10)  # t=55, next deadline is 60
    assert len(fired) == 1
    await clock.advance(100)  # t=155: every owed run has fired
    assert fired == [
        ("health/hc-0", 30.0),
        ("health/hc-1", 60.0),
        ("health/hc-2", 90.0),
        ("health/hc-3", 120.0),
    ]
    # exactly once: nothing re-fires later on either wheel
    await clock.advance(200)
    assert len(fired) == 4
    await new_owner.shutdown()


@pytest.mark.asyncio
async def test_shutdown_cancels_everything():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(1)

    for i in range(5):
        wheel.schedule(f"hc-{i}", 10, cb)
    await wheel.shutdown()
    await clock.advance(100)
    assert fired == []
