"""Continuous-batching serving probe — the workload users actually feel.

``probes/decode.py`` times ONE static batch decoding in lockstep;
production inference is continuous batching under mixed traffic:
sequences arrive open-loop, prefill, join the in-flight decode batch,
finish at different times, and their KV blocks recycle into the next
admission. This probe runs that loop end to end on the serving runtime
(ops/kv_cache.py paged cache + scheduler/serving.py admission policy)
and exports the serving-shaped numbers:

- ``serving-tokens-per-s`` — generated tokens over engine-busy seconds,
  judged (on rated TPU) against the roofline MEMORY-BOUND ceiling:
  decode streams every parameter plus the banked KV per step, so the
  ceiling is HBM bandwidth over bytes-per-token
  (``ops/kv_cache.kv_bytes_per_token`` — the same figure the static
  decode probe exports as ``decode-kv-bytes-per-token``, so the two
  probes' ceilings share one input).
- ``serving-ttft-p50-ms`` / ``serving-ttft-p99-ms`` — time to first
  token, arrival to prefill-produced token (queueing included: the
  open-loop generator keeps offering load, so overload shows up HERE,
  not as a silently slowed generator).
- ``serving-intertoken-p99-ms`` — per-token decode latency tail.
- ``serving-batch-occupancy`` — mean in-flight fraction of the batch
  ceiling over decode steps (how continuously the batching actually
  batched).
- ``serving-kv-frag-ratio`` — the paged cache's explicit fragmentation
  account, time-averaged.

Correctness gates (the probe verdict): continuous-batched logits must
match the per-sequence STATIC decode path (prefill + ``decode_step``)
within numeric tolerance — teacher-forced on the serving path's own
tokens so near-tie argmax flips cannot cascade, the decode probe's
discipline — and the scheduler's per-sequence/per-tenant token
accounting must conserve EXACTLY (admitted = completed + in-flight).

Clock discipline: this module is wall-clock-banned (hack/lint.py) —
all timing flows through the injectable ``timer`` (or the scripted
``StepCosts`` virtual clock, which is how the acceptance test replays
a deterministic soak), and the roofline verdict is ``capture()`` math
over the measured seconds (``cost_source: model`` off-TPU, fraction
emitted against the rated ceiling on TPU only — PR 9's discipline).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    init_params,
    param_count,
    prefill,
    tiny_config,
)
from activemonitor_tpu.ops.kv_cache import (
    KVBlockManager,
    bank_prompt,
    init_paged_kv,
    kv_bytes_per_token,
    paged_decode_step,
    shard_paged_kv,
)
from activemonitor_tpu.probes.base import PhaseTimings, ProbeMetric, ProbeResult
from activemonitor_tpu.scheduler.serving import (
    ContinuousBatchingScheduler,
    Request,
    open_loop_requests,
)


@dataclass(frozen=True)
class StepCosts:
    """Scripted virtual clock for deterministic soaks: seconds charged
    per prefill (given the prompt length) and per shared decode step
    (given the in-flight count). The acceptance test charges a flat
    decode cost — the memory-bound regime, where a step streams the
    weights regardless of batch width — which is exactly the regime
    where continuous batching beats sequential static decode."""

    prefill: Callable[[int], float]
    decode: Callable[[int], float]


@dataclass
class SoakResult:
    """Everything one soak measured, for the probe/tests to fold."""

    scheduler: ContinuousBatchingScheduler
    elapsed: float  # virtual seconds, arrival 0 to last retirement
    busy_seconds: float  # engine-busy seconds (prefill + decode)
    decode_seconds: float
    decode_steps: int
    ttft_ms: List[float] = field(default_factory=list)
    intertoken_ms: List[float] = field(default_factory=list)
    frag_samples: List[float] = field(default_factory=list)
    banked_samples: List[int] = field(default_factory=list)
    # rid -> [logits row per generated token] for checked sequences
    logit_trace: Dict[int, List] = field(default_factory=dict)
    prompts: Dict[int, jax.Array] = field(default_factory=dict)

    @property
    def tokens_generated(self) -> int:
        return self.scheduler.conservation()["tokens_emitted"]

    @property
    def tokens_per_second(self) -> float:
        return self.tokens_generated / max(self.busy_seconds, 1e-9)

    @property
    def occupancy(self) -> float:
        samples = self.scheduler.occupancy_samples
        return sum(samples) / len(samples) if samples else 0.0


def _percentile(samples: Sequence[float], q: float) -> float:
    # the SLO layer's nearest-rank quantile (obs/slo.py) — one tail
    # convention across serving-ttft-p99-ms and the controller's
    # latency quantiles, not two that disagree on small samples
    from activemonitor_tpu.obs.slo import quantile

    value = quantile(samples, q)
    return 0.0 if value is None else float(value)


@functools.lru_cache(maxsize=8)
def _jitted(cfg: ProbeModelConfig):
    """One jitted (decode step, prefill) pair per model config, shared
    across soaks in the process — the calibration soak's compiles are
    the measurement soak's warm cache, not a second compile bill.

    The decode step DONATES the storage argument: the soak always
    rebinds storage from the call's return, and without donation every
    step would materialize a fresh copy of the whole K/V pool —
    doubling peak cache HBM and putting a full-pool memcpy in the hot
    loop at real pool sizes. (Backends without donation support, e.g.
    CPU, warn once and copy — correctness is unchanged.)"""
    step = jax.jit(
        lambda p, s, t, pos, bt: paged_decode_step(p, s, t, pos, bt, cfg),
        donate_argnums=(1,),
    )
    pre = jax.jit(lambda p, c, t: prefill(p, c, t, cfg))
    return step, pre


def _fresh_prefill_cache(cfg: ProbeModelConfig, cap: int) -> Dict:
    """A one-sequence contiguous staging cache for prefill before the
    K/V scatters into blocks (exact capacity — no rounding, so the
    block reshape in bank_prompt stays shape-exact)."""
    shape = (cfg.n_layers, 1, cfg.kv_heads, cap, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def run_soak(
    cfg: ProbeModelConfig,
    requests: Sequence[Request],
    *,
    max_batch: int,
    block_size: int = 8,
    n_blocks: Optional[int] = None,
    timer: Callable[[], float] = time.monotonic,
    costs: Optional[StepCosts] = None,
    collect: int = 0,
    seed: int = 0,
    params: Optional[Dict] = None,
    mesh=None,
    tp_axis: str = "model",
) -> SoakResult:
    """Run one continuous-batching soak over ``requests``.

    The engine owns the model and the clock; the scheduler owns policy.
    With ``costs`` the soak runs on the scripted virtual clock (real
    logits, deterministic time); otherwise every phase is measured with
    the injectable ``timer``. ``collect`` records full logits for the
    first N request ids so the probe can pin them against the static
    decode path. ``params`` lets the caller share one parameter tree
    with the static-replay check (defaults to the seed's init — the
    consistency gate needs both paths under the SAME tree). ``mesh``
    places the paged storage on its partition-rule shardings (kv heads
    over ``tp_axis``) before the loop."""
    if params is None:
        params = init_params(jax.random.key(seed), cfg)
    probe_key = jax.random.fold_in(jax.random.key(seed), 1)
    manager_probe = KVBlockManager(1, block_size)  # blocks_for arithmetic
    max_blk = max(
        manager_probe.blocks_for(r.prompt_len + r.output_tokens)
        for r in requests
    )
    if n_blocks is None:
        n_blocks = max_batch * max_blk  # a full batch always fits
    if max_blk > n_blocks:
        # a request whose reservation exceeds the WHOLE pool can never
        # admit: with nothing in flight the head-of-line refusal would
        # spin the loop forever — a config error, reported up front
        raise ValueError(
            f"largest request needs {max_blk} blocks but the pool has "
            f"{n_blocks}; raise n_blocks or block_size"
        )
    manager = KVBlockManager(n_blocks, block_size)
    trash = n_blocks  # storage-only scratch block (ops/kv_cache docstring)
    storage = init_paged_kv(cfg, n_blocks + 1, block_size)
    if mesh is not None:
        storage = shard_paged_kv(storage, cfg, mesh, tp_axis)
    sched = ContinuousBatchingScheduler(requests, manager, max_batch)
    prompts = {
        r.rid: jax.random.randint(
            jax.random.fold_in(probe_key, r.rid),
            (1, r.prompt_len),
            0,
            cfg.vocab_size,
        )
        for r in requests
    }
    collected = {r.rid for r in requests if r.rid < collect}

    step_fn, prefill_fn = _jitted(cfg)
    stage_cap = max_blk * block_size

    # warm the compiles out of the measured timeline: one prefill per
    # distinct prompt length, one decode step at the soak's fixed shape
    for plen in sorted({r.prompt_len for r in requests}):
        warm = prefill_fn(
            params,
            _fresh_prefill_cache(cfg, stage_cap),
            jnp.zeros((1, plen), jnp.int32),
        )
        jax.block_until_ready(warm[0])
    warm_tables = jnp.full((max_batch, max_blk), trash, jnp.int32)
    # the step donates storage, so thread the returned pool (the warm
    # step's tables are all-trash — only the scratch block is written)
    warm_logits, storage = step_fn(
        params,
        storage,
        jnp.zeros((max_batch,), jnp.int32),
        jnp.zeros((max_batch,), jnp.int32),
        warm_tables,
    )
    jax.block_until_ready(warm_logits)

    result = SoakResult(
        scheduler=sched,
        elapsed=0.0,
        busy_seconds=0.0,
        decode_seconds=0.0,
        decode_steps=0,
        prompts={rid: prompts[rid] for rid in collected},
    )
    now = 0.0
    while not sched.done:
        next_arrival = sched.next_arrival()
        if not sched.active and next_arrival is not None and next_arrival > now:
            now = next_arrival  # open-loop idle: jump to the next arrival
        step_cost = 0.0
        for seq in sched.admit(now):
            rid = seq.req.rid
            start = timer()
            logits, staged = prefill_fn(
                params, _fresh_prefill_cache(cfg, stage_cap), prompts[rid]
            )
            storage = bank_prompt(
                storage,
                staged["k"][:, 0, :, : seq.req.prompt_len],
                staged["v"][:, 0, :, : seq.req.prompt_len],
                jnp.asarray(manager.table(rid), jnp.int32),
            )
            jax.block_until_ready(storage["k"])
            elapsed = (
                costs.prefill(seq.req.prompt_len)
                if costs is not None
                else max(0.0, timer() - start)
            )
            step_cost += elapsed
            token = int(jnp.argmax(logits[0]))
            if rid in collected:
                result.logit_trace.setdefault(rid, []).append(
                    jax.device_get(logits[0])
                )
            sched.record_first_token(seq, token, now + step_cost)
            result.ttft_ms.append((now + step_cost - seq.req.arrival) * 1e3)
        batch = sched.decode_batch()
        if batch:
            tokens = [0] * max_batch
            positions = [0] * max_batch
            tables = [[trash] * max_blk for _ in range(max_batch)]
            for seq in batch:
                tokens[seq.slot] = seq.tokens[-1]
                positions[seq.slot] = seq.req.prompt_len + seq.generated - 1
                row = manager.table(seq.req.rid)
                tables[seq.slot] = row + [trash] * (max_blk - len(row))
            start = timer()
            logits, storage = step_fn(
                params,
                storage,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(tables, jnp.int32),
            )
            jax.block_until_ready(logits)
            elapsed = (
                costs.decode(len(batch))
                if costs is not None
                else max(0.0, timer() - start)
            )
            step_cost += elapsed
            result.decode_seconds += elapsed
            result.decode_steps += 1
            result.intertoken_ms.extend([elapsed * 1e3] * len(batch))
            result.banked_samples.append(manager.banked_tokens)
            result.frag_samples.append(manager.fragmentation_ratio())
            by_slot = {
                seq.slot: int(jnp.argmax(logits[seq.slot])) for seq in batch
            }
            for seq in batch:
                if seq.req.rid in collected:
                    result.logit_trace.setdefault(seq.req.rid, []).append(
                        jax.device_get(logits[seq.slot])
                    )
            sched.record_decode_step(by_slot, now + step_cost)
        now += step_cost
        result.busy_seconds += step_cost
    result.elapsed = now
    return result


def roofline_inputs(
    soak: SoakResult, cfg: ProbeModelConfig, max_batch: int
) -> Dict[str, float]:
    """The serving analytic cost model, in its ONE home: a decode
    step's measured seconds, FLOPs, and HBM bytes from what the soak
    actually ran (mean occupancy, mean banked-KV footprint, the shared
    ``kv_bytes_per_token`` figure). Both the probe's roofline capture
    and the matrix cell's roofline stamp read THIS — two hand-copied
    models would let a regression be judged against a ceiling the
    probe no longer exports."""
    mean_active = max(1.0, soak.occupancy * max_batch)
    mean_banked = (
        sum(soak.banked_samples) / len(soak.banked_samples)
        if soak.banked_samples
        else 0.0
    )
    param_bytes = param_count(cfg) * jnp.dtype(cfg.dtype).itemsize
    return {
        "seconds": soak.decode_seconds / max(1, soak.decode_steps),
        "flops": 2.0 * param_count(cfg) * mean_active,
        "bytes": float(param_bytes + mean_banked * kv_bytes_per_token(cfg)),
    }


def sequential_static_seconds(
    requests: Sequence[Request], costs: StepCosts
) -> float:
    """The sequential static-batch baseline under the same cost model:
    each request alone — one prefill plus one single-sequence decode
    step per remaining token, no overlap. The acceptance test's
    denominator for the continuous-batching speedup claim."""
    total = 0.0
    for req in requests:
        total += costs.prefill(req.prompt_len)
        total += costs.decode(1) * max(0, req.output_tokens - 1)
    return total


def _check_against_static(
    cfg: ProbeModelConfig,
    params: Dict,
    soak: SoakResult,
) -> float:
    """Max relative logit divergence between the continuous-batched
    trace and the per-sequence static path, teacher-forced on the
    serving tokens. The serving correctness gate's number."""
    from activemonitor_tpu.models.probe_model import decode_step, init_kv_cache

    worst = 0.0
    finished = {s.req.rid: s for s in soak.scheduler.completed}
    prefill_fn = _jitted(cfg)[1]
    for rid, trace in sorted(soak.logit_trace.items()):
        seq = finished.get(rid)
        if seq is None:
            continue
        prompt = soak.prompts[rid]
        plen = seq.req.prompt_len
        cache = init_kv_cache(cfg, 1, plen + seq.req.output_tokens + 1)
        logits, cache = prefill_fn(params, cache, prompt)
        static_rows = [logits[0]]
        for i, token in enumerate(seq.tokens[:-1]):
            logits, cache = decode_step(
                params,
                cache,
                jnp.asarray([token], jnp.int32),
                jnp.asarray(plen + i, jnp.int32),
                cfg,
            )
            static_rows.append(logits[0])
        for served, static in zip(trace, static_rows):
            scale = max(float(jnp.max(jnp.abs(static))), 1e-6)
            diff = float(jnp.max(jnp.abs(jnp.asarray(served) - static)))
            worst = max(worst, diff / scale)
    return worst


def run(
    tiny: bool = False,
    n_requests: int = 10,
    max_batch: int = 4,
    block_size: int = 8,
    rate_rps: Optional[float] = None,
    seed: int = 0,
    check_sequences: int = 2,
    roofline: bool = True,
    timer: Callable[[], float] = time.monotonic,
) -> ProbeResult:
    """The serving probe. ``rate_rps=None`` calibrates the open-loop
    arrival rate to roughly half the engine's measured token capacity
    (one warm decode step), so the soak exercises admission churn on
    any hardware instead of degenerating to all-idle or all-queued."""
    cfg = tiny_config() if tiny else ProbeModelConfig()
    if tiny:
        prompt_lens, outputs = (4, 6, 8), (2, 3, 5)
    else:
        prompt_lens, outputs = (16, 32, 48), (6, 10)
    timings = PhaseTimings(monotonic=timer)
    params = init_params(jax.random.key(seed), cfg)

    with timings.phase("calibrate"):
        if rate_rps is None:
            # one warm full-width decode step prices a token
            probe_reqs = open_loop_requests(
                max_batch,
                1e9,
                seed,
                prompt_len_choices=prompt_lens[:1],
                output_choices=(2,),
            )
            warm = run_soak(
                cfg,
                probe_reqs,
                max_batch=max_batch,
                block_size=block_size,
                timer=timer,
                seed=seed,
                params=params,
            )
            step_seconds = warm.decode_seconds / max(1, warm.decode_steps)
            capacity_tps = max_batch / max(step_seconds, 1e-9)
            mean_out = sum(outputs) / len(outputs)
            rate_rps = 0.5 * capacity_tps / mean_out

    requests = open_loop_requests(
        n_requests,
        rate_rps,
        seed,
        prompt_len_choices=prompt_lens,
        output_choices=outputs,
    )
    with timings.phase("soak"):
        soak = run_soak(
            cfg,
            requests,
            max_batch=max_batch,
            block_size=block_size,
            timer=timer,
            collect=check_sequences,
            seed=seed,
            params=params,
        )

    with timings.phase("verify"):
        max_rel_diff = _check_against_static(cfg, params, soak)
    # same tolerance story as the decode probe: bf16 path-shape
    # differences read ~1e-2 relative; a broken cache/block-table reads
    # O(1). NaNs fail the <= comparison, so they fail the gate.
    consistent = max_rel_diff <= 0.05
    conservation = soak.scheduler.conservation()
    ok = consistent and bool(conservation["ok"])

    frag = (
        sum(soak.frag_samples) / len(soak.frag_samples)
        if soak.frag_samples
        else 0.0
    )
    bytes_per_token = kv_bytes_per_token(cfg)
    metrics = [
        ProbeMetric(
            "serving-tokens-per-s",
            soak.tokens_per_second,
            help="Generated tokens per engine-busy second under "
            "continuous batching",
        ),
        ProbeMetric(
            "serving-ttft-p50-ms",
            _percentile(soak.ttft_ms, 0.50),
            help="Time to first token, median (arrival -> prefill token, "
            "queueing included)",
        ),
        ProbeMetric(
            "serving-ttft-p99-ms",
            _percentile(soak.ttft_ms, 0.99),
            help="Time to first token, p99",
        ),
        ProbeMetric(
            "serving-intertoken-p99-ms",
            _percentile(soak.intertoken_ms, 0.99),
            help="Per-token decode latency, p99 across sequences and steps",
        ),
        ProbeMetric(
            "serving-batch-occupancy",
            soak.occupancy,
            help="Mean in-flight fraction of the batch ceiling over "
            "decode steps",
        ),
        ProbeMetric(
            "serving-kv-frag-ratio",
            frag,
            help="Paged KV cache fragmentation: reserved-but-unwritten "
            "slots over reserved slots, time-averaged",
        ),
        ProbeMetric(
            "serving-consistency",
            1.0 if consistent else 0.0,
            help="1 when continuous-batched logits match the static "
            "per-sequence decode path within tolerance",
        ),
        ProbeMetric(
            "serving-kv-bytes-per-token",
            bytes_per_token,
            help="HBM bytes one generated token adds to the KV cache — "
            "shared roofline-ceiling input with decode-kv-bytes-per-token",
        ),
    ]
    # TTFT decomposition (obs/criticalpath.py, ISSUE 17): the
    # scheduler's token-exact stamps split TTFT into queue-wait
    # (arrival -> admission), prefill (admission -> first token; the
    # two sum to TTFT exactly) and first-decode (first token -> the
    # first shared decode step's token)
    from activemonitor_tpu.obs.criticalpath import decompose_ttft

    ttft_split = decompose_ttft(soak.scheduler.completed)
    if ttft_split is not None:
        metrics.extend(
            [
                ProbeMetric(
                    "serving-ttft-queue-wait-p99-ms",
                    ttft_split["queue_wait"]["p99"] * 1e3,
                    help="TTFT queue-wait component, p99 (arrival -> "
                    "batch admission)",
                ),
                ProbeMetric(
                    "serving-ttft-prefill-p99-ms",
                    ttft_split["prefill"]["p99"] * 1e3,
                    help="TTFT prefill component, p99 (admission -> "
                    "first token; queue-wait + prefill == TTFT)",
                ),
                ProbeMetric(
                    "serving-ttft-first-decode-p99-ms",
                    ttft_split["first_decode"]["p99"] * 1e3,
                    help="First shared decode step after the prefill "
                    "token, p99 (the decode scheduler's handoff cost)",
                ),
            ]
        )
    result = ProbeResult(
        ok=ok,
        summary=(
            f"serving {soak.tokens_per_second:,.0f} tok/s, ttft p99 "
            f"{_percentile(soak.ttft_ms, 0.99):.1f}ms, occupancy "
            f"{soak.occupancy:.2f}, "
            f"consistency {'OK' if consistent else 'MISMATCH'} "
            f"(rel diff {max_rel_diff:.1e}), accounting "
            f"{'conserved' if conservation['ok'] else 'LEAKED'}"
        ),
        metrics=metrics,
        details={
            "n_requests": n_requests,
            "max_batch": max_batch,
            "block_size": block_size,
            "rate_rps": round(float(rate_rps), 4),
            "tokens_generated": soak.tokens_generated,
            "decode_steps": soak.decode_steps,
            "elapsed_seconds": soak.elapsed,
            "busy_seconds": soak.busy_seconds,
            "max_rel_logit_diff": max_rel_diff,
            "checked_sequences": len(soak.logit_trace),
            "conservation": conservation,
            "refusals": dict(soak.scheduler.refusals),
            # the block manager's structured refusal counters (ISSUE 20
            # small fix): a double-free or over-capacity append is a
            # scheduler bug — nonzero here is attributable, not silent
            "kv_refusals": soak.scheduler.manager.stats()["refusals"],
            "kv_frag_peak": max(soak.frag_samples, default=0.0),
            "kv_bytes_per_token": bytes_per_token,
            "ttft_decomposition": ttft_split,
        },
        timings=timings,
    )
    # roofline verdict: a serving decode step streams the parameters
    # plus the banked KV — the analytic model (roofline_inputs, shared
    # with the matrix cell's stamp) measured over the mean decode-step
    # seconds. On TPU capture() judges it against the rated
    # memory-bound ceiling; off-TPU the fraction is a structured skip
    # (cost_source: model evidence, never a TPU-bar comparison).
    from activemonitor_tpu.obs import roofline as roofline_model

    cost = roofline_inputs(soak, cfg, max_batch)
    roofline_model.apply(
        result,
        roofline_model.capture(
            "serving",
            seconds=cost["seconds"],
            model_flops=cost["flops"],
            model_bytes=cost["bytes"],
            enabled=roofline,
        ),
    )
    return result


# ---------------------------------------------------------------------
# disaggregated serving (ISSUE 20): prefill/decode pool split with KV
# handoff, content-addressed prefix caching, speculative decoding
# ---------------------------------------------------------------------


@dataclass
class DisaggSoakResult:
    """One disaggregated soak's measurements — the two-lane analog of
    :class:`SoakResult` (same duck-typed surface for the static
    consistency check: ``scheduler.completed`` / ``logit_trace`` /
    ``prompts``)."""

    scheduler: object  # DisaggregatedScheduler
    elapsed: float = 0.0  # max of the two lane clocks at drain
    prefill_busy: float = 0.0  # prefill-pool engine-busy virtual seconds
    decode_busy: float = 0.0
    decode_steps: int = 0  # real target decode steps (verify included)
    spec_rounds: int = 0
    prefill_tokens: int = 0  # prompt tokens actually prefilled (hits excluded)
    ttft_ms: List[float] = field(default_factory=list)
    intertoken_ms: List[float] = field(default_factory=list)
    migration_ms: List[float] = field(default_factory=list)  # modeled, per transfer
    prefill_frag_samples: List[float] = field(default_factory=list)
    decode_frag_samples: List[float] = field(default_factory=list)
    banked_samples: List[int] = field(default_factory=list)  # decode pool
    logit_trace: Dict[int, List] = field(default_factory=dict)
    prompts: Dict[int, jax.Array] = field(default_factory=dict)

    @property
    def tokens_generated(self) -> int:
        return self.scheduler.conservation()["tokens_emitted"]

    @property
    def decode_tokens_per_second(self) -> float:
        return self.tokens_generated / max(self.decode_busy, 1e-9)

    @property
    def prefill_tokens_per_second(self) -> float:
        return self.prefill_tokens / max(self.prefill_busy, 1e-9)


def default_disagg_costs() -> StepCosts:
    """The scripted cost model the disagg probe replays when no real
    per-op timing is wanted (CPU tier-1): prefill linear in prompt
    tokens (compute-bound — 2·P FLOPs per token), decode flat per step
    (memory-bound — the step streams the weights regardless of width).
    Virtual seconds, deterministic; the probe labels the evidence
    ``cost_source: scripted`` so nobody reads it as a TPU measurement."""
    per_token = 2e-3
    return StepCosts(
        prefill=lambda plen: per_token * plen,
        decode=lambda width: per_token,
    )


def run_disagg_soak(
    cfg: ProbeModelConfig,
    requests: Sequence[Request],
    *,
    prefill_slots: int,
    decode_slots: int,
    block_size: int = 4,
    prefill_blocks: Optional[int] = None,
    decode_blocks: Optional[int] = None,
    prefix_cache: bool = False,
    speculate: int = 0,
    draft_layers: Optional[int] = None,
    cross_slice: bool = False,
    costs: Optional[StepCosts] = None,
    timer: Callable[[], float] = time.monotonic,
    collect: int = 0,
    seed: int = 0,
    params: Optional[Dict] = None,
):
    """Run one disaggregated soak: prefill pool and decode pool on a
    TWO-LANE virtual clock (the pools are separate worker sets — that
    independence is the disaggregation win), KV block tables handed off
    over the priced migration channel with the actual K/V copied
    between the pools' storages (``ops/kv_cache.migrate_blocks``), an
    optional content-addressed prefix cache on the prefill pool, and
    optional early-exit speculative decoding on the decode pool.

    Speculation drafts with the target's own FIRST ``draft_layers``
    layers (a shrunk ``ProbeModelConfig`` sharing the real params and a
    throwaway slice of the banked K/V) and verifies with the real
    target step, feeding only already-confirmed tokens — so every
    emitted token is EXACTLY what plain greedy decode would emit and
    the static consistency gate covers the speculative path unchanged.
    The virtual clock charges one target-step cost per verify ROUND
    (the batched-verify memory-bound claim) plus a layer-fraction cost
    per draft step; acceptance is measured, not assumed.
    """
    import dataclasses as _dc

    from activemonitor_tpu.ops.kv_cache import PrefixCache, migrate_blocks
    from activemonitor_tpu.scheduler.pools import (
        DisaggregatedScheduler,
        PoolTopology,
    )

    if params is None:
        params = init_params(jax.random.key(seed), cfg)
    probe_key = jax.random.fold_in(jax.random.key(seed), 1)
    arith = KVBlockManager(1, block_size)
    pre_max_blk = max(arith.blocks_for(r.prompt_len) for r in requests)
    dec_max_blk = max(
        arith.blocks_for(r.prompt_len + r.output_tokens + max(0, speculate))
        for r in requests
    )
    if prefill_blocks is None:
        # slots' worth of prompts plus the same again for cache entries
        prefill_blocks = prefill_slots * pre_max_blk * (2 if prefix_cache else 1)
    if decode_blocks is None:
        decode_blocks = decode_slots * dec_max_blk
    pm = KVBlockManager(prefill_blocks, block_size)
    dm = KVBlockManager(decode_blocks, block_size)
    pre_trash, dec_trash = prefill_blocks, decode_blocks
    storage_p = init_paged_kv(cfg, prefill_blocks + 1, block_size)
    storage_d = init_paged_kv(cfg, decode_blocks + 1, block_size)
    cache = PrefixCache(pm) if prefix_cache else None
    sched = DisaggregatedScheduler(
        requests,
        PoolTopology.disaggregated(prefill_slots, decode_slots, cross_slice),
        prefill_manager=pm,
        decode_manager=dm,
        bytes_per_token=float(kv_bytes_per_token(cfg)),
        prefix_cache=cache,
    )
    prompts = {
        r.rid: (
            jnp.asarray([list(r.prompt_tokens)], jnp.int32)
            if r.prompt_tokens is not None
            else jax.random.randint(
                jax.random.fold_in(probe_key, r.rid),
                (1, r.prompt_len),
                0,
                cfg.vocab_size,
            )
        )
        for r in requests
    }
    collected = {r.rid for r in requests if r.rid < collect}

    step_fn, prefill_fn = _jitted(cfg)
    stage_cap = max(pre_max_blk, dec_max_blk) * block_size
    draft_step = None
    k_layers = 0
    if speculate > 0:
        k_layers = draft_layers or max(1, cfg.n_layers // 2)
        cfg_draft = _dc.replace(cfg, n_layers=k_layers)
        draft_step = _jitted(cfg_draft)[0]
        params_draft = {**params, "layers": params["layers"][:k_layers]}

    # warm the compiles off the virtual timeline
    for plen in sorted({r.prompt_len for r in requests}):
        warm = prefill_fn(
            params,
            _fresh_prefill_cache(cfg, stage_cap),
            jnp.zeros((1, plen), jnp.int32),
        )
        jax.block_until_ready(warm[0])
    warm_tables = jnp.full((decode_slots, dec_max_blk), dec_trash, jnp.int32)
    warm_logits, storage_d = step_fn(
        params,
        storage_d,
        jnp.zeros((decode_slots,), jnp.int32),
        jnp.zeros((decode_slots,), jnp.int32),
        warm_tables,
    )
    jax.block_until_ready(warm_logits)

    result = DisaggSoakResult(
        scheduler=sched,
        prompts={rid: prompts[rid] for rid in collected},
    )
    costs_live = costs
    t_pre = 0.0
    t_dec = 0.0
    ready_at: Dict[int, float] = {}

    def _charge(measured_start: float, scripted: float) -> float:
        if costs_live is not None:
            return scripted
        return max(0.0, timer() - measured_start)

    while not sched.done:
        moved = False
        # -- pool boundary: drain the handoff queue, copy the K/V ------
        for rec in sched.pump_migrations(t_pre):
            src = rec["src_blocks"]
            dst = rec["dst_blocks"][: len(src)]
            storage_d = migrate_blocks(storage_p, storage_d, src, dst)
            ready_at[rec["rid"]] = rec["ready_at"]
            result.migration_ms.append(rec["seconds"] * 1e3)
            moved = True
        # -- prefill lane ---------------------------------------------
        sched.sample_prefill_occupancy()
        for seq in sched.admit(t_pre):
            rid = seq.req.rid
            plen = seq.req.prompt_len
            hit = sched.hit_tokens(rid)
            start = timer()
            logits, staged = prefill_fn(
                params, _fresh_prefill_cache(cfg, stage_cap), prompts[rid]
            )
            if hit < plen:
                # bank only the non-cached remainder into the private
                # table (the shared prefix is already banked — that IS
                # the hit); block-granular hits keep this block-aligned
                storage_p = bank_prompt(
                    storage_p,
                    staged["k"][:, 0, :, hit:plen],
                    staged["v"][:, 0, :, hit:plen],
                    jnp.asarray(pm.table(rid), jnp.int32),
                )
                jax.block_until_ready(storage_p["k"])
            elapsed = _charge(start, costs.prefill(plen - hit) if costs else 0.0)
            t_pre += elapsed
            result.prefill_busy += elapsed
            result.prefill_tokens += plen - hit
            result.prefill_frag_samples.append(pm.fragmentation_ratio())
            token = int(jnp.argmax(logits[0]))
            if rid in collected:
                result.logit_trace.setdefault(rid, []).append(
                    jax.device_get(logits[0])
                )
            sched.record_first_token(seq, token, t_pre)
            result.ttft_ms.append((t_pre - seq.req.arrival) * 1e3)
            moved = True
        # -- decode lane ----------------------------------------------
        batch = sched.decode_batch(t_dec)
        if not batch and sched.decode_active:
            pending = [
                ready_at.get(s.req.rid, 0.0)
                for s in sched.decode_active.values()
            ]
            horizon = min(pending)
            if horizon > t_dec:
                t_dec = horizon
                batch = sched.decode_batch(t_dec)
        if batch and speculate > 0:
            storage_d, cost = _speculative_round(
                sched,
                batch,
                params,
                params_draft,
                step_fn,
                draft_step,
                storage_d,
                dm,
                dec_trash,
                dec_max_blk,
                decode_slots,
                speculate,
                k_layers,
                cfg.n_layers,
                costs_live,
                timer,
                t_dec,
                collected,
                result,
            )
            t_dec += cost
            result.decode_busy += cost
            moved = True
        elif batch:
            tokens = [0] * decode_slots
            positions = [0] * decode_slots
            tables = [[dec_trash] * dec_max_blk for _ in range(decode_slots)]
            for seq in batch:
                tokens[seq.slot] = seq.tokens[-1]
                positions[seq.slot] = seq.req.prompt_len + seq.generated - 1
                row = dm.table(seq.req.rid)
                tables[seq.slot] = row + [dec_trash] * (dec_max_blk - len(row))
            start = timer()
            logits, storage_d = step_fn(
                params,
                storage_d,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(tables, jnp.int32),
            )
            jax.block_until_ready(logits)
            elapsed = _charge(start, costs.decode(len(batch)) if costs else 0.0)
            result.decode_steps += 1
            result.intertoken_ms.extend([elapsed * 1e3] * len(batch))
            result.banked_samples.append(dm.banked_tokens)
            result.decode_frag_samples.append(dm.fragmentation_ratio())
            by_slot = {s.slot: int(jnp.argmax(logits[s.slot])) for s in batch}
            for seq in batch:
                if seq.req.rid in collected:
                    result.logit_trace.setdefault(seq.req.rid, []).append(
                        jax.device_get(logits[seq.slot])
                    )
            t_dec += elapsed
            result.decode_busy += elapsed
            sched.record_decode_step(by_slot, t_dec)
            moved = True
        if not moved:
            nxt = sched.next_arrival()
            if nxt is not None and nxt > t_pre:
                t_pre = nxt
                continue
            raise RuntimeError(
                "disagg soak stalled: no admissible, migratable or "
                "decodable work but the scheduler is not done"
            )
    result.elapsed = max(t_pre, t_dec)
    return result


def _speculative_round(
    sched,
    batch,
    params,
    params_draft,
    step_fn,
    draft_step,
    storage_d,
    dm,
    dec_trash,
    dec_max_blk,
    decode_slots,
    speculate,
    k_layers,
    n_layers,
    costs,
    timer,
    t_dec,
    collected,
    result,
):
    """One draft/verify round on the decode pool. Draft: ``speculate``
    early-exit steps on a throwaway K/V slice (its bankings die with
    the slice). Verify: sequential target steps feeding ONLY confirmed
    tokens, so banked K/V and emitted tokens are exactly greedy's; a
    mismatch or completion drops the slot out of the round (trash-table
    padding keeps the batch shape static). Returns the updated storage
    and the round's charged seconds — scripted cost charges ONE target
    step per round plus a ``k/L`` fraction per draft step (the modeled
    batched-verify claim; measured mode charges real seconds)."""
    start = timer()
    # ---- draft ------------------------------------------------------
    draft_storage = {
        "k": storage_d["k"][:k_layers],
        "v": storage_d["v"][:k_layers],
    }
    fed = {s.slot: s.tokens[-1] for s in batch}
    pos = {s.slot: s.req.prompt_len + s.generated - 1 for s in batch}
    rows = {}
    for s in batch:
        row = dm.table(s.req.rid)
        rows[s.slot] = row + [dec_trash] * (dec_max_blk - len(row))
    proposals: Dict[int, List[int]] = {s.slot: [] for s in batch}
    for _ in range(speculate):
        tokens = [0] * decode_slots
        positions = [0] * decode_slots
        tables = [[dec_trash] * dec_max_blk for _ in range(decode_slots)]
        for s in batch:
            tokens[s.slot] = fed[s.slot]
            positions[s.slot] = pos[s.slot]
            tables[s.slot] = rows[s.slot]
        dlogits, draft_storage = draft_step(
            params_draft,
            draft_storage,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(tables, jnp.int32),
        )
        for s in batch:
            t = int(jnp.argmax(dlogits[s.slot]))
            proposals[s.slot].append(t)
            fed[s.slot] = t
            pos[s.slot] += 1
    # ---- verify -----------------------------------------------------
    active = {s.slot: s for s in batch}
    emitted: Dict[int, List[int]] = {s.slot: [] for s in batch}
    accepted: Dict[int, int] = {s.slot: 0 for s in batch}
    vfed = {s.slot: s.tokens[-1] for s in batch}
    vpos = {s.slot: s.req.prompt_len + s.generated - 1 for s in batch}
    for j in range(speculate + 1):
        if not active:
            break
        tokens = [0] * decode_slots
        positions = [0] * decode_slots
        tables = [[dec_trash] * dec_max_blk for _ in range(decode_slots)]
        for slot in active:
            tokens[slot] = vfed[slot]
            positions[slot] = vpos[slot]
            tables[slot] = rows[slot]
        logits, storage_d = step_fn(
            params,
            storage_d,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(tables, jnp.int32),
        )
        jax.block_until_ready(logits)
        result.decode_steps += 1
        for slot, seq in list(active.items()):
            t_true = int(jnp.argmax(logits[slot]))
            emitted[slot].append(t_true)
            if seq.req.rid in collected:
                result.logit_trace.setdefault(seq.req.rid, []).append(
                    jax.device_get(logits[slot])
                )
            matched = j < speculate and t_true == proposals[slot][j]
            if matched:
                accepted[slot] += 1
            completes = (
                seq.generated + len(emitted[slot]) >= seq.req.output_tokens
            )
            if matched and not completes:
                vfed[slot] = t_true
                vpos[slot] += 1
            else:
                del active[slot]
    width = len(batch)
    scripted = 0.0
    if costs is not None:
        scripted = costs.decode(width) * (1.0 + speculate * k_layers / n_layers)
    elapsed = scripted if costs is not None else max(0.0, timer() - start)
    result.spec_rounds += 1
    result.banked_samples.append(dm.banked_tokens)
    result.decode_frag_samples.append(dm.fragmentation_ratio())
    for slot in emitted:
        n = len(emitted[slot])
        if n:
            result.intertoken_ms.extend([elapsed * 1e3 / n] * n)
    sched.record_speculative_step(
        {slot: toks for slot, toks in emitted.items() if toks},
        {slot: speculate for slot in emitted},
        accepted,
        t_dec + elapsed,
    )
    return storage_d, elapsed


def run_disagg(
    tiny: bool = False,
    n_requests: int = 12,
    prefill_slots: int = 2,
    decode_slots: int = 4,
    block_size: int = 4,
    rate_rps: float = 60.0,
    seed: int = 0,
    check_sequences: int = 2,
    prefix_cache: bool = True,
    speculate: int = 2,
    cross_slice: bool = False,
    roofline: bool = True,
    costs: Optional[StepCosts] = None,
    timer: Callable[[], float] = time.monotonic,
) -> ProbeResult:
    """The disaggregated serving probe (ISSUE 20): one mixed open-loop
    workload with a hot shared prefix (scheduler/arrivals.
    TenantPrefixMix) served twice under the SAME scripted cost model —
    once colocated (the PR 14 scheduler verbatim), once split across
    prefill/decode pools with prefix caching and speculative decoding —
    and the TTFT comparison exported with per-pool throughput, the
    migration channel's receipts, the prefix-cache ledger and the
    speculative acceptance fraction.

    Evidence discipline: the clock is the scripted virtual one
    (``default_disagg_costs`` unless the caller scripts their own), so
    the TTFT claim is deterministic, seed-reproducible MODEL evidence —
    ``details["serving_disagg"]["cost_source"] = "scripted"`` and
    bench.py labels the CPU path ``interpret_mode: true``. The logits
    underneath are REAL compute either way: the static consistency gate
    spans prefill, the block migration copy, and the speculative verify
    path."""
    cfg = tiny_config() if tiny else ProbeModelConfig()
    prompt_lens = (12, 16) if tiny else (16, 32, 48)
    outputs = (2, 3, 5) if tiny else (6, 10)
    prefix_len = 2 * block_size  # two shared blocks — hits are visible
    timings = PhaseTimings(monotonic=timer)
    params = init_params(jax.random.key(seed), cfg)
    if costs is None:
        costs = default_disagg_costs()

    from activemonitor_tpu.scheduler.serving import mixed_open_loop_requests

    requests = mixed_open_loop_requests(
        n_requests,
        rate_rps,
        seed,
        prefix_len=prefix_len,
        prompt_len_choices=prompt_lens,
        output_choices=outputs,
        vocab=cfg.vocab_size,
    )
    max_batch = decode_slots  # the colocated pool gets the same width
    with timings.phase("soak-colocated"):
        colo = run_soak(
            cfg,
            requests,
            max_batch=max_batch,
            block_size=block_size,
            costs=costs,
            seed=seed,
            params=params,
        )
    with timings.phase("soak-disagg"):
        soak = run_disagg_soak(
            cfg,
            requests,
            prefill_slots=prefill_slots,
            decode_slots=decode_slots,
            block_size=block_size,
            prefix_cache=prefix_cache,
            speculate=speculate,
            cross_slice=cross_slice,
            costs=costs,
            collect=check_sequences,
            seed=seed,
            params=params,
        )
    with timings.phase("verify"):
        max_rel_diff = _check_against_static(cfg, params, soak)

    consistent = max_rel_diff <= 0.05
    conservation = soak.scheduler.conservation()
    migration = soak.scheduler.migration_ledger()
    speculation = soak.scheduler.speculation()
    prefix_ledger = (
        soak.scheduler.prefix_cache.ledger()
        if soak.scheduler.prefix_cache is not None
        else None
    )
    pool_stats = soak.scheduler.pool_stats()
    kv_refusals = {
        "prefill": pool_stats["prefill"]["refusals"],
        "decode": pool_stats["decode"]["refusals"],
    }
    clean_kv = all(
        count == 0 for pool in kv_refusals.values() for count in pool.values()
    )
    ok = (
        consistent
        and bool(conservation["ok"])
        and bool(migration["ok"])
        and bool(speculation["ok"])
        and (prefix_ledger is None or bool(prefix_ledger["ok"]))
        and clean_kv
    )

    colo_p99 = _percentile(colo.ttft_ms, 0.99)
    disagg_p99 = _percentile(soak.ttft_ms, 0.99)
    improvement = (colo_p99 - disagg_p99) / max(colo_p99, 1e-9)
    cache_stats = pool_stats.get("prefix_cache") or {}
    hit_ratio = float(cache_stats.get("hit_ratio", 0.0))
    evictions = float((cache_stats.get("counters") or {}).get("evictions", 0))

    metrics = [
        ProbeMetric(
            "serving-pool-prefill-ttft-p99-ms",
            disagg_p99,
            help="Time to first token p99 under disaggregated pools "
            "(TTFT lives entirely in the prefill pool)",
        ),
        ProbeMetric(
            "serving-pool-prefill-tokens-per-s",
            soak.prefill_tokens_per_second,
            help="Prompt tokens prefilled per prefill-pool busy second "
            "(prefix-cache hits excluded — they were never recomputed)",
        ),
        ProbeMetric(
            "serving-pool-decode-tokens-per-s",
            soak.decode_tokens_per_second,
            help="Generated tokens per decode-pool busy second",
        ),
        ProbeMetric(
            "serving-disagg-ttft-improvement",
            improvement,
            help="Fractional TTFT p99 improvement of disaggregated+"
            "prefix-cache over colocated, same requests and cost model",
        ),
        ProbeMetric(
            "serving-kv-migration-bytes",
            float(migration["bytes_total"]),
            help="Total KV bytes handed prefill pool -> decode pool "
            "over the migration channel (alpha/B modeled)",
        ),
        ProbeMetric(
            "serving-kv-migration-p99-ms",
            _percentile(soak.migration_ms, 0.99),
            help="Per-transfer modeled migration latency p99 (ICI "
            "intra-slice, DCN cross-slice)",
        ),
        ProbeMetric(
            "serving-prefix-hit-ratio",
            hit_ratio,
            help="Block-granular prefix-cache hit ratio (hits over "
            "lookups); 0 when the cache is disabled",
        ),
        ProbeMetric(
            "serving-prefix-evictions",
            evictions,
            help="Prefix-cache entries evicted (LRU, refcount zero only)",
        ),
        ProbeMetric(
            "serving-disagg-consistency",
            1.0 if consistent else 0.0,
            help="1 when disaggregated logits (prefill, migrated KV, "
            "speculative verify) match the static decode path",
        ),
    ]
    if speculation["acceptance"] is not None:
        metrics.append(
            ProbeMetric(
                "serving-spec-accept-fraction-of-rated",
                float(speculation["acceptance"]),
                help="Speculative-decode draft acceptance fraction "
                "(accepted drafts over drafted) — a rated-fraction "
                "metric: analysis/detector.py floors and am-tpu why "
                "attribution judge it like any other subsystem",
            )
        )

    serving_disagg = {
        "mode": pool_stats["mode"],
        "prefill_slots": prefill_slots,
        "decode_slots": decode_slots,
        "cross_slice": cross_slice,
        "prefix_cache": prefix_cache,
        "speculate": speculate,
        "colocated_ttft_p99_ms": colo_p99,
        "disagg_ttft_p99_ms": disagg_p99,
        "ttft_improvement": improvement,
        "prefix_hit_ratio": hit_ratio,
        "prefix_evictions": evictions,
        "prefix_counters": dict(cache_stats.get("counters") or {}),
        "spec_acceptance": speculation["acceptance"],
        "migration_transfers": migration["transfers"],
        "migration_bytes_total": migration["bytes_total"],
        "migration_by_tier": migration["by_tier"],
        "cost_source": "scripted",
    }
    result = ProbeResult(
        ok=ok,
        summary=(
            f"disagg ttft p99 {disagg_p99:.1f}ms vs colocated "
            f"{colo_p99:.1f}ms ({improvement:+.0%}), prefix hit ratio "
            f"{hit_ratio:.2f}, spec acceptance "
            + (
                f"{speculation['acceptance']:.2f}"
                if speculation["acceptance"] is not None
                else "n/a"
            )
            + f", consistency {'OK' if consistent else 'MISMATCH'} "
            f"(rel diff {max_rel_diff:.1e}), boundary "
            f"{'conserved' if migration['ok'] else 'LEAKED'}"
        ),
        metrics=metrics,
        details={
            "n_requests": n_requests,
            "block_size": block_size,
            "rate_rps": rate_rps,
            "tokens_generated": soak.tokens_generated,
            "decode_steps": soak.decode_steps,
            "spec_rounds": soak.spec_rounds,
            "max_rel_logit_diff": max_rel_diff,
            "checked_sequences": len(soak.logit_trace),
            "conservation": conservation,
            "migration_ledger": migration,
            "speculation": speculation,
            "prefix_ledger": prefix_ledger,
            "refusals": dict(soak.scheduler.refusals),
            "kv_refusals": kv_refusals,
            "pool_stats": pool_stats,
            "serving_disagg": serving_disagg,
        },
        timings=timings,
    )
    # per-pool roofline verdicts against each pool's OWN ceiling:
    # prefill is compute-shaped (2*P FLOPs per prompt token, params
    # read once per prefill), decode is memory-shaped (params plus
    # banked KV streamed per step) — the disaggregation thesis stated
    # as two captures instead of one blended number
    from activemonitor_tpu.obs import roofline as roofline_model

    n_prefills = max(1, len(soak.ttft_ms))
    param_bytes = param_count(cfg) * jnp.dtype(cfg.dtype).itemsize
    mean_prefill_tokens = soak.prefill_tokens / n_prefills
    roofline_model.apply(
        result,
        roofline_model.capture(
            "serving-prefill",
            seconds=soak.prefill_busy / n_prefills,
            model_flops=2.0 * param_count(cfg) * max(1.0, mean_prefill_tokens),
            model_bytes=float(param_bytes),
            enabled=roofline,
        ),
    )
    mean_banked = (
        sum(soak.banked_samples) / len(soak.banked_samples)
        if soak.banked_samples
        else 0.0
    )
    dec_steps = max(1, soak.decode_steps)
    mean_width = (
        len(soak.intertoken_ms) / dec_steps if soak.intertoken_ms else 1.0
    )
    roofline_model.apply(
        result,
        roofline_model.capture(
            "serving-decode",
            seconds=soak.decode_busy / dec_steps,
            model_flops=2.0 * param_count(cfg) * max(1.0, mean_width),
            model_bytes=float(
                param_bytes + mean_banked * kv_bytes_per_token(cfg)
            ),
            enabled=roofline,
        ),
    )
    return result
