"""KubernetesLeaseElector against the stub API server with a fake clock.

Deterministic election semantics (reference: controller-runtime's
leaderelection used at cmd/main.go:87-88): renew-keeps-leadership,
expiry-takeover, step-down-before-takeover (never two leaders), and
release-hands-over.
"""

import asyncio

import pytest

from activemonitor_tpu.controller.leader import KubernetesLeaseElector
from activemonitor_tpu.kube import KubeApi, KubeConfig
from activemonitor_tpu.utils.clock import FakeClock

from tests.kube_harness import advance, stub_env

LEASE = 15.0


def elector(api, clock, identity):
    return KubernetesLeaseElector(
        api=api, namespace="health", identity=identity, lease_seconds=LEASE, clock=clock
    )


class FlakyApi:
    """KubeApi wrapper with a switchable failure mode (simulated
    API-server partition for one client only)."""

    def __init__(self, inner):
        self._inner = inner
        self.failing = False

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in ("get", "create", "replace", "merge_patch", "delete", "request"):
            async def wrapper(*a, **kw):
                if self.failing:
                    raise OSError("partitioned")
                return await attr(*a, **kw)

            return wrapper
        return attr


@pytest.mark.asyncio
async def test_acquire_creates_lease_and_renews():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)

        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert lease["spec"]["holderIdentity"] == "replica-a"
        first_renew = lease["spec"]["renewTime"]

        await advance(clock, LEASE)  # several renew periods
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert lease["spec"]["renewTime"] > first_renew
        assert not a.lost.is_set()
        a.release()


@pytest.mark.asyncio
async def test_standby_waits_while_leader_renews():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)

        b = elector(api, clock, "replica-b")
        b_acquired = asyncio.Event()

        async def b_runs():
            await b.acquire()
            b_acquired.set()

        task = asyncio.create_task(b_runs())
        await advance(clock, LEASE * 4)  # a renews throughout
        assert not b_acquired.is_set()
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert lease["spec"]["holderIdentity"] == "replica-a"
        a.release()
        b.release()
        task.cancel()


@pytest.mark.asyncio
async def test_dead_leader_is_taken_over_after_expiry():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)
        # replica-a dies without releasing (no relinquish, no renewal)
        a._renew_task.cancel()
        a._stop = True

        b = elector(api, clock, "replica-b")
        b_acquired = asyncio.Event()

        async def b_runs():
            await b.acquire()
            b_acquired.set()

        task = asyncio.create_task(b_runs())
        await advance(clock, LEASE / 2)
        assert not b_acquired.is_set()  # lease not yet expired
        await advance(clock, LEASE * 1.5)
        await asyncio.wait_for(b_acquired.wait(), 5)
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", b._name)
        assert lease["spec"]["holderIdentity"] == "replica-b"
        b.release()
        task.cancel()


@pytest.mark.asyncio
async def test_partitioned_leader_steps_down_before_takeover():
    """The failing holder hits its renew deadline (2/3 lease) and fires
    ``lost`` BEFORE the challenger's takeover window (full lease)
    opens — the split-brain ordering guarantee."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        flaky = FlakyApi(api)
        a = elector(flaky, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)

        b = elector(api, clock, "replica-b")
        b_acquired_at = []

        async def b_runs():
            await b.acquire()
            b_acquired_at.append(clock.monotonic())

        task = asyncio.create_task(b_runs())
        await advance(clock, 2.6)  # let b observe a live leader first
        flaky.failing = True
        a_lost_at = []

        async def watch_lost():
            await a.lost.wait()
            a_lost_at.append(clock.monotonic())

        lost_task = asyncio.create_task(watch_lost())
        await advance(clock, LEASE * 3)
        await asyncio.wait_for(a.lost.wait(), 5)
        await asyncio.wait_for(lost_task, 5)
        await asyncio.wait_for(task, 10)

        assert a_lost_at and b_acquired_at
        # the old leader stood down strictly before the new one rose
        assert a_lost_at[0] < b_acquired_at[0]
        b.release()


@pytest.mark.asyncio
async def test_single_transient_renewal_failure_does_not_lose_leadership():
    """One API blip must be retried on the short retry cadence and
    recovered — not turn into a full failover."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        flaky = FlakyApi(api)
        a = elector(flaky, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)

        # fail exactly one renewal attempt (t=5), then recover
        await advance(clock, 4.9)
        flaky.failing = True
        await advance(clock, 0.2)  # the t=5 renew attempt fails
        flaky.failing = False
        await advance(clock, LEASE * 2)  # retries recover well before deadline
        assert not a.lost.is_set()
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert lease["spec"]["holderIdentity"] == "replica-a"
        a.release()


@pytest.mark.asyncio
async def test_takeover_observed_by_renewal_fires_lost():
    """If another replica somehow takes the lease (e.g. after a long GC
    pause on the holder), the holder's next renewal sees the foreign
    identity and declares leadership lost rather than fighting."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)

        # replace the holder behind a's back
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        lease["spec"]["holderIdentity"] = "replica-b"

        await advance(clock, LEASE)
        await asyncio.wait_for(a.lost.wait(), 5)


@pytest.mark.asyncio
async def test_renew_conflict_demotes_immediately():
    """ISSUE-6 satellite: a resourceVersion conflict mid-renew means
    another holder replaced the lease between our GET and PUT — the
    holder must demote on the spot (fire ``lost``), NOT retry the renew
    for the rest of the deadline while still reconciling (that window
    is split-brain)."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)
        assert a.fence_rv  # fencing token recorded at acquisition

        # the NEXT renew PUT hits a conflict (the stub's CAS rejects a
        # stale resourceVersion exactly like a real takeover race); the
        # GET still shows us as holder, so only the PUT sees the race
        server.inject_fault(
            f"/leases/{a._name}", status=409, times=1, method="PUT"
        )
        await advance(clock, LEASE / 3 + 1)  # one renew period
        # demoted at the FIRST conflict — well before the 2/3-lease
        # renew deadline the generic-transient path would burn
        await asyncio.wait_for(a.lost.wait(), 5)
        assert clock.monotonic() < LEASE * 2 / 3
        a.release()


@pytest.mark.asyncio
async def test_lease_writes_record_the_fencing_token():
    """Every successful lease write (create, takeover, renew) records
    the object's resourceVersion — the token the sharding layer's write
    fence compares against the server."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert a.fence_rv == lease["metadata"]["resourceVersion"]
        first_rv, first_write = a.fence_rv, a.last_write

        await advance(clock, LEASE)  # several renewals
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert a.fence_rv == lease["metadata"]["resourceVersion"]
        assert a.fence_rv != first_rv
        assert a.last_write > first_write
        a.release()


@pytest.mark.asyncio
async def test_release_relinquishes_for_fast_handover():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        await asyncio.wait_for(a.acquire(), 5)
        a.release()
        await asyncio.sleep(0.2)  # relinquish task runs in real time
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        assert lease["spec"]["holderIdentity"] == ""

        # a standby acquires without waiting out the lease duration
        b = elector(api, clock, "replica-b")
        await asyncio.wait_for(b.acquire(), 5)
        b.release()


@pytest.mark.asyncio
async def test_relinquished_lease_lands_on_the_zero_grace_claimant():
    """A relinquished (home-return) shard lease must go HOME: the
    zero-grace claimant polls every lease/3 while graced standbys sit
    out the shorter vacancy window, so the prioritized replica wins the
    vacancy race deterministically — not whichever peer GETs first.
    (Regression: graced standbys used to treat an empty holder as
    instantly expired, racing the home replica 50/50 per hop.)"""
    async with stub_env() as (server, api):
        clock = FakeClock()
        holder = elector(api, clock, "replica-c")
        await asyncio.wait_for(holder.acquire(), 5)

        # a graced standby (a peer's non-home standby loop) watching
        graced = KubernetesLeaseElector(
            api=api, namespace="health", identity="replica-b",
            lease_seconds=LEASE, clock=clock, takeover_grace=LEASE,
        )
        graced_task = asyncio.create_task(graced.acquire())
        await advance(clock, LEASE / 3)  # it has observed the live holder

        holder.release()
        await asyncio.sleep(0.2)  # relinquish task runs in real time
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", holder._name)
        assert lease["spec"]["holderIdentity"] == ""

        # the home replica starts contending AFTER the relinquish with
        # zero grace; the graced standby has a head start but must
        # still sit out the vacancy window (lease/2 > home's lease/3
        # poll) — home wins
        home = elector(api, clock, "replica-home")
        home_task = asyncio.create_task(home.acquire())
        await advance(clock, LEASE / 2)
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", holder._name)
        assert lease["spec"]["holderIdentity"] == "replica-home"
        await asyncio.wait_for(home_task, 5)
        graced_task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await graced_task
        home.release()

        # with NO zero-grace claimant around, the graced standby does
        # adopt the vacancy — after the window, not never
        await asyncio.sleep(0.2)
        orphan = KubernetesLeaseElector(
            api=api, namespace="health", identity="replica-b2",
            lease_seconds=LEASE, clock=clock, takeover_grace=LEASE,
        )
        orphan_task = asyncio.create_task(orphan.acquire())
        await advance(clock, LEASE)
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", holder._name)
        assert lease["spec"]["holderIdentity"] == "replica-b2"
        await asyncio.wait_for(orphan_task, 5)
        orphan.release()


@pytest.mark.asyncio
async def test_manager_stops_reconciling_on_lost_leadership():
    """The manager end of the contract: when the elector fires ``lost``,
    reconcile workers stop — the reference terminates the process
    (controller-runtime semantics); here the stop signal propagates to
    the CLI, which exits."""
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryHealthCheckClient,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.controller.manager import Manager
    from activemonitor_tpu.engine import FakeWorkflowEngine
    from activemonitor_tpu.metrics import MetricsCollector

    async with stub_env() as (server, api):
        clock = FakeClock()
        a = elector(api, clock, "replica-a")
        client = InMemoryHealthCheckClient()
        reconciler = HealthCheckReconciler(
            client=client,
            engine=FakeWorkflowEngine(),
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=EventRecorder(),
            metrics=MetricsCollector(),
        )
        manager = Manager(
            client=client, reconciler=reconciler, max_parallel=2, leader_elector=a
        )
        await manager.start()
        assert manager.ready and not manager.stopping.is_set()

        # another replica takes the lease behind our back
        lease = server.obj("coordination.k8s.io", "v1", "leases", "health", a._name)
        lease["spec"]["holderIdentity"] = "replica-b"
        await advance(clock, LEASE)
        await asyncio.wait_for(a.lost.wait(), 5)
        await asyncio.wait_for(manager.stopping.wait(), 5)
        await manager.stop()


@pytest.mark.asyncio
async def test_two_challengers_race_one_wins():
    """Preconditioned takeover: with an expired lease, two challengers
    race the replace; exactly one must win the round."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        dead = elector(api, clock, "replica-dead")
        await asyncio.wait_for(dead.acquire(), 5)
        dead._renew_task.cancel()
        dead._stop = True

        api2 = KubeApi(KubeConfig(server=server.url))
        try:
            b = elector(api, clock, "replica-b")
            c = elector(api2, clock, "replica-c")
            winners = []

            async def run(e, name):
                await e.acquire()
                winners.append(name)

            tb = asyncio.create_task(run(b, "b"))
            tc = asyncio.create_task(run(c, "c"))
            await advance(clock, LEASE * 2.5)
            await asyncio.sleep(0.3)
            assert len(winners) == 1
            holder = server.obj(
                "coordination.k8s.io", "v1", "leases", "health", b._name
            )["spec"]["holderIdentity"]
            assert holder == f"replica-{winners[0]}"
            for t in (tb, tc):
                t.cancel()
            b.release()
            c.release()
        finally:
            await api2.close()


def test_lease_timestamps_are_strict_microtime():
    """Lease renewTime/acquireTime are Kubernetes MicroTime — the real
    apiserver's parser REQUIRES six fractional digits, while
    datetime.isoformat() omits the fraction at microsecond == 0 (which
    FakeClock's fixed epoch hits on every write). Pin the wire format
    so the stub tier can't hide a flaky real-cluster 400."""
    import datetime
    import re

    from activemonitor_tpu.utils.clock import micro_time

    strict = re.compile(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{6}Z$")
    # the exact hazard: zero microseconds must still carry .000000
    zero_us = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
    assert strict.match(micro_time(zero_us)), micro_time(zero_us)
    assert micro_time(zero_us).endswith(".000000Z")
    assert strict.match(
        micro_time(datetime.datetime.now(datetime.timezone.utc))
    )
    # non-UTC input normalizes to Z
    offset = datetime.timezone(datetime.timedelta(hours=5))
    assert micro_time(zero_us.astimezone(offset)) == micro_time(zero_us)


def test_micro_time_treats_naive_as_utc():
    import datetime

    from activemonitor_tpu.utils.clock import micro_time

    aware = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
    naive = datetime.datetime(2026, 1, 1)
    # naive input must mean UTC (repo convention), never host-local time
    assert micro_time(naive) == micro_time(aware)
