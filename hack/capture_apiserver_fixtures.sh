#!/usr/bin/env bash
# Regenerate tests/fixtures/apiserver/*.json from a REAL cluster.
#
# The committed corpus is hand-transcribed from the Kubernetes wire
# format (docs/conformance.md explains the provenance); this script
# upgrades it to machine-captured bytes whenever a cluster is
# reachable (kind, GKE, ...). It drives the same scenarios the
# conformance tests replay, captures the raw response bodies with
# curl, and rewrites each fixture's "response"/"stream" in place --
# tests/test_apiserver_conformance.py then re-validates both the
# client and the stub against the captured reality.
#
# Requirements: kubectl with cluster-admin on a test cluster, curl, jq,
# python3. The CRD must be installed (make crd && kubectl apply -f
# config/crd/). Nothing here touches non-test namespaces.
set -euo pipefail

NS="activemonitor-fixture-capture"
GROUP="activemonitor.keikoproj.io"
VERSION="v1alpha1"
OUT_DIR="$(cd "$(dirname "$0")/.." && pwd)/tests/fixtures/apiserver"

API_SERVER=$(kubectl config view --minify -o jsonpath='{.clusters[0].cluster.server}')

kcurl() { # method path [body] [content-type]
  local method=$1 path=$2 body=${3:-} ctype=${4:-application/json}
  if [ -n "$body" ]; then
    curl -ksS -X "$method" -H "Authorization: Bearer $TOKEN" \
      -H "Content-Type: $ctype" -d "$body" \
      -w '\n%{http_code}' "$API_SERVER$path"
  else
    curl -ksS -X "$method" -H "Authorization: Bearer $TOKEN" \
      -w '\n%{http_code}' "$API_SERVER$path"
  fi
}

update_fixture() { # name status body
  python3 - "$OUT_DIR/$1.json" "$2" <<'PY'
import json, sys
path, status = sys.argv[1], int(sys.argv[2])
body = json.load(sys.stdin)
# a TokenReview response echoes the full object back, INCLUDING the
# live bearer token in spec.token — redact before it can reach git
if body.get("kind") == "TokenReview" and (body.get("spec") or {}).get("token"):
    body["spec"]["token"] = "<redacted-sa-token>"
with open(path) as fh:
    fx = json.load(fh)
fx["response"] = {"status": status, "body": body}
fx["source"] = (
    "Machine-captured by hack/capture_apiserver_fixtures.sh against "
    f"a live apiserver ({body.get('apiVersion', 'v1')})."
)
with open(path, "w") as fh:
    json.dump(fx, fh, indent=2)
    fh.write("\n")
print(f"updated {path}")
PY
}

capture() { # fixture-name method path [body]
  local name=$1; shift
  local raw code body
  raw=$(kcurl "$@")
  code=${raw##*$'\n'}
  body=${raw%$'\n'*}
  printf '%s' "$body" | update_fixture "$name" "$code"
}

HC_PATH="/apis/$GROUP/$VERSION/namespaces/$NS/healthchecks"
DEMO='{"apiVersion":"'$GROUP'/'$VERSION'","kind":"HealthCheck","metadata":{"name":"demo","namespace":"'$NS'"},"spec":{"repeatAfterSec":60,"workflow":{"generateName":"demo-","resource":{"namespace":"'$NS'","source":{"inline":"{}"}}}}}'

kubectl create namespace "$NS" --dry-run=client -o yaml | kubectl apply -f -
trap 'kubectl delete namespace "$NS" --wait=false >/dev/null 2>&1 || true;
      kubectl delete clusterrolebinding fixture-capture-admin >/dev/null 2>&1 || true' EXIT

# the captures must run with enough RBAC to exercise the CRD verbs —
# an unprivileged token would record 403s over every intended shape
kubectl create serviceaccount fixture-capture -n "$NS" \
  --dry-run=client -o yaml | kubectl apply -f -
kubectl create clusterrolebinding fixture-capture-admin \
  --clusterrole=cluster-admin \
  --serviceaccount="$NS:fixture-capture" \
  --dry-run=client -o yaml | kubectl apply -f -
TOKEN=$(kubectl create token fixture-capture -n "$NS" --duration=10m)

echo "== 404 NotFound"
capture get_notfound GET "$HC_PATH/demo"

echo "== create + 409 AlreadyExists"
kcurl POST "$HC_PATH" "$DEMO" >/dev/null
capture post_alreadyexists POST "$HC_PATH" "$DEMO"

echo "== 409 Conflict (stale resourceVersion)"
STALE=$(kcurl GET "$HC_PATH/demo" | head -n -1)
# merge-patch content type: a real apiserver rejects PATCH with plain
# application/json (415), which would leave the RV unbumped and turn
# the PUT below into a 200 instead of the Conflict being captured
kcurl PATCH "$HC_PATH/demo" '{"spec":{"repeatAfterSec":61}}' \
  application/merge-patch+json >/dev/null
capture put_conflict PUT "$HC_PATH/demo" "$STALE"

echo "== 422 Invalid"
capture invalid_422 POST "$HC_PATH" \
  '{"apiVersion":"'$GROUP'/'$VERSION'","kind":"HealthCheck","metadata":{"name":"bad","namespace":"'$NS'"},"spec":{"repeatAfterSec":"not-a-number"}}'

echo "== LIST envelope"
capture list_envelope GET "$HC_PATH"

echo "== DELETE Status/Success"
capture delete_success DELETE "$HC_PATH/demo"

echo "== 401 Unauthorized"
# subshell: a plain `TOKEN=... capture ...` prefix is a bash-ism whose
# temporary-environment scoping flips in POSIX mode — the assignment
# would persist and poison every capture after this one
(TOKEN="invalid-bearer"; capture unauthorized GET "$HC_PATH/demo") || true

echo "== TokenReview / SubjectAccessReview"
SA_TOKEN=$(kubectl create token default --duration=10m)
capture tokenreview POST /apis/authentication.k8s.io/v1/tokenreviews \
  '{"apiVersion":"authentication.k8s.io/v1","kind":"TokenReview","spec":{"token":"'$SA_TOKEN'"}}'
capture subjectaccessreview POST /apis/authorization.k8s.io/v1/subjectaccessreviews \
  '{"apiVersion":"authorization.k8s.io/v1","kind":"SubjectAccessReview","spec":{"user":"system:serviceaccount:default:default","nonResourceAttributes":{"path":"/metrics","verb":"get"}}}'

echo
echo "Watch fixtures (watch_stream, watch_expired) stream over time —"
echo "capture manually with:"
echo "  curl -ksN -H \"Authorization: Bearer \$TOKEN\" \\"
echo "    \"$API_SERVER$HC_PATH?watch=true&allowWatchBookmarks=true\""
echo "and paste the observed event lines into the fixtures' \"stream\"."
echo
echo "Done. tokenreview.json's spec.token is auto-redacted; verify with"
echo "  grep -r 'redacted-sa-token' tests/fixtures/apiserver/tokenreview.json"
echo "then run: python -m pytest tests/test_apiserver_conformance.py"
