"""Checkpoint/resume of the sharded TRAINING state.

The checkpoint probe round-trips a synthetic pytree; these tests pin
the real thing: the full (params, AdamW state) from
build_sharded_train_step survives save/restore and training CONTINUES
as if never interrupted — including restoring onto a different mesh
shape and a different (ZeRO-1) optimizer layout, the elastic-resume
case a preempted TPU job actually hits.
"""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.models.probe_model import tiny_config
from activemonitor_tpu.parallel.mesh import make_2d_mesh
from activemonitor_tpu.probes.training_step import (
    build_sharded_train_step,
    restore_train_state,
    save_train_state,
    train_state_templates,
)

pytest.importorskip("orbax.checkpoint")

from activemonitor_tpu.utils.compat import LEGACY_JAX

if LEGACY_JAX:
    # restoring orbax train state and stepping it SEGFAULTS the legacy
    # CPU runtime (donated-buffer path) — a crash here aborts the whole
    # pytest process, so the module is gated, not just failing
    pytest.skip(
        "legacy jax/jaxlib: orbax train-state resume segfaults the CPU "
        "runtime",
        allow_module_level=True,
    )


def _tokens(data_sh):
    cfg = tiny_config()
    tokens = jax.random.randint(jax.random.key(3), (8, 17), 0, cfg.vocab_size)
    return jax.device_put(tokens, data_sh)


def test_resume_same_mesh_is_bitwise(tmp_path):
    cfg = tiny_config()
    mesh = make_2d_mesh()
    step, params, opt, data_sh = build_sharded_train_step(cfg, mesh)
    tokens = _tokens(data_sh)
    for _ in range(2):
        params, opt, _ = step(params, opt, tokens)
    save_train_state(str(tmp_path / "ckpt"), params, opt, step=2)

    # uninterrupted continuation
    ref_params, ref_opt = params, opt
    ref_losses = []
    for _ in range(2):
        ref_params, ref_opt, loss = step(ref_params, ref_opt, tokens)
        ref_losses.append(float(loss))

    # resume from disk on the same mesh: bitwise identical continuation.
    # Templates are ABSTRACT and the builder allocates NOTHING
    # (init_state=False) — resume never materializes a throwaway init
    step2, no_params, no_opt, _ = build_sharded_train_step(
        cfg, mesh, init_state=False
    )
    assert no_params is None and no_opt is None
    p_like, o_like = train_state_templates(cfg, mesh)
    r_params, r_opt, at_step = restore_train_state(
        str(tmp_path / "ckpt"), p_like, o_like
    )
    assert at_step == 2
    resumed_losses = []
    for _ in range(2):
        r_params, r_opt, loss = step2(r_params, r_opt, tokens)
        resumed_losses.append(float(loss))
    assert resumed_losses == ref_losses
    drift = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(r_params))
    )
    assert drift == 0.0


def test_resume_reshards_onto_different_mesh_and_zero1(tmp_path):
    """Elastic resume: a checkpoint from dp=2×tp=4 (plain optimizer
    layout) restores onto dp=4×tp=2 WITH ZeRO-1 — values carry over,
    the new layouts apply, and training continues to the same losses
    within cross-sharding reduction tolerance."""
    cfg = tiny_config()
    mesh_a = make_2d_mesh(shape=(2, 4))
    step_a, params, opt, data_sh_a = build_sharded_train_step(cfg, mesh_a)
    tokens = _tokens(data_sh_a)
    for _ in range(2):
        params, opt, _ = step_a(params, opt, tokens)
    save_train_state(str(tmp_path / "ckpt"), params, opt, step=2)
    ref_losses = []
    rp, ro = params, opt
    for _ in range(2):
        rp, ro, loss = step_a(rp, ro, tokens)
        ref_losses.append(float(loss))

    mesh_b = make_2d_mesh(shape=(4, 2))
    step_b, _, _, data_sh_b = build_sharded_train_step(
        cfg, mesh_b, zero1=True, init_state=False
    )
    p_like, o_like = train_state_templates(cfg, mesh_b, zero1=True)
    r_params, r_opt, _ = restore_train_state(
        str(tmp_path / "ckpt"), p_like, o_like
    )
    # layouts are the NEW mesh's (ZeRO-1: mu carries the data axis)
    mu = r_opt[0].mu["layers"][0]["w_up"]
    assert mu.sharding.spec == ("data", "model")
    tokens_b = jax.device_put(jax.device_get(tokens), data_sh_b)
    resumed_losses = []
    for _ in range(2):
        r_params, r_opt, loss = step_b(r_params, r_opt, tokens_b)
        resumed_losses.append(float(loss))
    # different tp width reorders the bf16 reductions: close, not bitwise
    for a, b in zip(ref_losses, resumed_losses):
        assert abs(a - b) < 5e-2, (ref_losses, resumed_losses)


def test_step_numbered_retention_and_explicit_restore(tmp_path):
    """Step-numbered checkpoints: the previous checkpoint survives the
    next save (the crash-durability contract — orbax only removes it
    after the new one commits, bounded by keep=), and an explicit step
    restores over latest."""
    cfg = tiny_config()
    mesh = make_2d_mesh()
    step, params, opt, data_sh = build_sharded_train_step(cfg, mesh)
    tokens = _tokens(data_sh)
    params_at = {}
    for i in range(1, 4):
        params, opt, _ = step(params, opt, tokens)
        save_train_state(str(tmp_path / "ckpt"), params, opt, step=i, keep=2)
        # host copies: the step donates its input buffers, so device
        # arrays from earlier iterations get deleted
        params_at[i] = jax.device_get(params)
    p_like, o_like = train_state_templates(cfg, mesh)
    # latest
    _, _, at = restore_train_state(str(tmp_path / "ckpt"), p_like, o_like)
    assert at == 3
    # the PREVIOUS one still exists (keep=2)
    r2, _, at2 = restore_train_state(
        str(tmp_path / "ckpt"), p_like, o_like, step=2
    )
    assert at2 == 2
    drift = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree.leaves(params_at[2]), jax.tree.leaves(r2))
    )
    assert drift == 0.0
    # step 1 aged out under keep=2
    with pytest.raises(Exception):
        restore_train_state(str(tmp_path / "ckpt"), p_like, o_like, step=1)


def test_poisoned_step_dir_falls_back_to_previous(tmp_path):
    """A step directory that EXISTS but cannot be restored (a crash
    between mkdir and data, a filesystem dying mid-rename, manual
    tampering) must not brick resume: restore-latest skips it with a
    warning and lands on the newest restorable step. Orbax's tmp-dir
    rename already hides interrupted saves; this covers the shapes it
    can't. An explicit step= request still raises."""
    import pathlib
    import shutil

    cfg = tiny_config()
    mesh = make_2d_mesh()
    step, params, opt, data_sh = build_sharded_train_step(cfg, mesh)
    tokens = _tokens(data_sh)
    d = str(tmp_path / "ckpt")
    for i in (1, 2):
        params, opt, _ = step(params, opt, tokens)
        save_train_state(d, params, opt, step=i)
    root = pathlib.Path(d)
    # interrupted-save debris (orbax tmp dir): invisible to restore
    shutil.copytree(root / "2", root / "3.orbax-checkpoint-tmp-99")
    # the nastier shape: a committed-LOOKING but empty step dir
    (root / "4").mkdir()

    p_like, o_like = train_state_templates(cfg, mesh)
    restored, _, at = restore_train_state(d, p_like, o_like)
    assert at == 2  # fell back past the poisoned step 4
    drift = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree.leaves(jax.device_get(params)),
                        jax.tree.leaves(restored))
    )
    assert drift == 0.0
    # asking for the poisoned step EXPLICITLY surfaces the error
    with pytest.raises(Exception):
        restore_train_state(d, p_like, o_like, step=4)
    # a SYSTEMIC failure (wrong templates) must surface as the real
    # error, never FileNotFoundError — a resume harness reads that as
    # "cold start, reinitialize" and would silently discard progress
    import dataclasses

    bad_cfg = dataclasses.replace(tiny_config(), d_model=tiny_config().d_model * 2)
    bp_like, bo_like = train_state_templates(bad_cfg, mesh)
    with pytest.raises(Exception) as exc:
        restore_train_state(d, bp_like, bo_like)
    assert not isinstance(exc.value, FileNotFoundError), exc.value
