"""Device mesh + timed collective helpers (XLA builtins in
``collectives``, the explicit ppermute schedule zoo — flat AND
hierarchical DCN×ICI compositions — in ``schedules``, the tier-keyed
message-size autotuner over both in ``autotune``) plus the one
sharding surface (regex partition rules, topology-tier resolution,
and the single shard_map entry point in ``partition``)."""

from activemonitor_tpu.parallel.collectives import (
    CollectiveResult,
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.parallel.mesh import (
    best_2d_shape,
    device_info,
    make_1d_mesh,
    make_2d_mesh,
)
from activemonitor_tpu.parallel.partition import (
    make_gather_fns,
    make_shard_fns,
    match_partition_rules,
    named_tree_map,
    resolve_tiers,
    shard_tree,
    validate_rules,
)
from activemonitor_tpu.parallel.schedules import (
    all_gather_recdouble_bandwidth,
    all_gather_ring_bandwidth,
    all_reduce_recdouble_bandwidth,
    all_reduce_rsag_bandwidth,
    all_reduce_tree_bandwidth,
    hier_all_reduce_bandwidth,
)

__all__ = [
    "CollectiveResult",
    "all_gather_bandwidth",
    "all_gather_recdouble_bandwidth",
    "all_gather_ring_bandwidth",
    "all_reduce_bandwidth",
    "all_reduce_recdouble_bandwidth",
    "all_reduce_rsag_bandwidth",
    "all_reduce_tree_bandwidth",
    "all_to_all_bandwidth",
    "best_2d_shape",
    "device_info",
    "hier_all_reduce_bandwidth",
    "make_1d_mesh",
    "make_2d_mesh",
    "make_gather_fns",
    "make_shard_fns",
    "match_partition_rules",
    "named_tree_map",
    "ppermute_ring_bandwidth",
    "reduce_scatter_bandwidth",
    "resolve_tiers",
    "shard_tree",
    "validate_rules",
]
