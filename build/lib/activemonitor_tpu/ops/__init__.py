"""TPU kernels (Pallas) used by probes."""

from activemonitor_tpu.ops.flash_attention import flash_attention
from activemonitor_tpu.ops.stream import stream_scale_pallas, stream_scale_xla

__all__ = ["flash_attention", "stream_scale_pallas", "stream_scale_xla"]
