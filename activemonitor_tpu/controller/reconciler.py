"""The HealthCheck reconciler — the core state machine.

Implements the reference's reconcile flow (SURVEY.md §3.2-3.4;
reference: internal/controllers/healthcheck_controller.go:170-874) as
cooperating asyncio tasks:

reconcile(key)
├─ get: gone ⇒ stop timer, done               (:175-186)
└─ process (exceptions recovered, 1s requeue)  (:190-223)
   ├─ pause: no interval and no cron ⇒ Stopped (:238-250)
   ├─ cron ⇒ effective interval = next-fire delta (+1s) (:251-263)
   ├─ dedupe: finished recently AND timer known ⇒ no-op (:264-267)
   ├─ provision check RBAC                     (:269)
   ├─ submit workflow                          (:277)
   └─ spawn watch task                         (:283)

watch task (one per in-flight workflow)
├─ poll engine with inverse-exp backoff; timeout ⇒ synthesized Failed (:607-632)
├─ Succeeded ⇒ counters/metrics/remedy-reset  (:635-661)
├─ Failed ⇒ counters/metrics + remedy gating  (:662-723)
│  └─ remedy: RBAC → submit → watch → delete RBAC (:759-874)
├─ conflict-retried status write               (:734,:1445-1462)
└─ reschedule via timer wheel                  (:745-754)

Deliberate divergences from the reference (each marked inline):

1. The watch loop runs as its own task instead of blocking a reconcile
   worker for the whole workflow duration — the reference's known
   throughput bound (SURVEY.md §2 defect (e)).
2. The timer-fired resubmission recomputes the effective interval (cron
   delta or repeatAfterSec) at reschedule time. The reference reuses the
   re-fetched spec's repeatAfterSec, which is 0 for cron-only specs and
   degenerates into an immediate-refire loop until the next watch event
   corrects it.
3. Workflow labels are computed per-check (see workflow_spec.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from activemonitor_tpu.api.types import (
    HealthCheck,
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    STATUS_STOPPED,
    WORKFLOW_TYPE_HEALTHCHECK,
    WORKFLOW_TYPE_REMEDY,
)
from activemonitor_tpu.controller.client import (
    HealthCheckClient,
    NotFoundError,
    is_transient,
    retry_on_conflict,
    retry_on_transient,
)
from activemonitor_tpu.controller.events import (
    EVENT_NORMAL,
    EVENT_WARNING,
    EventRecorder,
)
from activemonitor_tpu.controller.rbac import RBACProvisioner
from activemonitor_tpu.controller.workflow_spec import (
    parse_remedy_workflow_from_healthcheck,
    parse_workflow_from_healthcheck,
)
from activemonitor_tpu.engine.base import WorkflowEngine
from activemonitor_tpu.metrics.collector import (
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)
from activemonitor_tpu.obs.slo import FleetStatus
from activemonitor_tpu.obs.trace import Tracer
from activemonitor_tpu.scheduler import (
    CronParseError,
    InverseExpBackoff,
    TimerWheel,
    compute_backoff_params,
    parse_cron,
    seconds_until_next,
)
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.reconciler")


class HealthCheckReconciler:
    def __init__(
        self,
        client: HealthCheckClient,
        engine: WorkflowEngine,
        rbac: RBACProvisioner,
        recorder: EventRecorder,
        metrics: MetricsCollector,
        clock: Optional[Clock] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.client = client
        self.engine = engine
        self.rbac = rbac
        self.recorder = recorder
        self.metrics = metrics
        self.clock = clock or Clock()
        # the reconciler owns the tracer like it owns the clock — the
        # manager and the CLI reach it through here
        self.tracer = tracer or Tracer(self.clock)
        # fleet SLO aggregate (result history + error budgets), fed from
        # the status-write path below and served by the manager's
        # /statusz endpoint. Same ownership shape as the tracer.
        self.fleet = FleetStatus(self.clock, metrics)
        self.timers = TimerWheel(self.clock)
        self._watch_tasks: Dict[str, asyncio.Task] = {}
        # set by the Manager: routes failed-run requeues through its
        # workqueue (per-key serialized, stop-aware, retried on crash)
        # instead of a loop inside the dying task
        self.requeue_hook = None
        self._stopping = False
        self._requeue_loops: set = set()  # standalone-mode fallback loops

    # ------------------------------------------------------------------
    # entry point (reference: Reconcile, healthcheck_controller.go:170-188)
    # ------------------------------------------------------------------
    async def reconcile(self, namespace: str, name: str) -> Optional[float]:
        """Returns a requeue-after delay in seconds, or None."""
        hc = await self.client.get(namespace, name)
        if hc is None:
            # deleted: cancel the next scheduled run (reference: :180-184).
            # Timers are keyed by namespace/name — the reference keys by
            # bare name (:139), letting same-named checks in different
            # namespaces clobber each other's schedules.
            key = f"{namespace}/{name}"
            if self.timers.exists(key):
                log.info("cancelling scheduled run for deleted healthcheck %s", key)
                self.timers.stop(key)
            # drop the check's result ring and SLO gauge series — the
            # fleet summary must not advertise a deleted check's budget
            self.fleet.forget(key, name, namespace)
            return None
        return await self._process_or_recover(hc)

    async def _process_or_recover(self, hc: HealthCheck) -> Optional[float]:
        # panic-recover equivalent (reference: :191-195)
        try:
            return await self._process(hc)
        except NotFoundError:
            # resource vanished mid-process: swallow (reference: :201-203)
            return None
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception(
                "error processing healthcheck %s", hc.key
            )
            return 1.0  # 1s requeue on process error (reference: :204)

    # ------------------------------------------------------------------
    # decision logic (reference: processHealthCheck, :225-291)
    # ------------------------------------------------------------------
    async def _process(self, hc: HealthCheck) -> Optional[float]:
        spec = hc.spec
        if spec.workflow.resource is None:
            return None  # nothing to run (reference guards on Resource != nil, :227)

        # pause (reference: :238-250)
        if spec.repeat_after_sec <= 0 and not spec.schedule.cron:
            hc.status.status = STATUS_STOPPED
            hc.status.error_message = (
                "workflow execution is stopped; either spec.RepeatAfterSec or "
                f"spec.Schedule must be provided. spec.RepeatAfterSec set to "
                f"{spec.repeat_after_sec}. spec.Schedule set to {spec.schedule.cron!r}"
            )
            hc.status.finished_at = self.clock.now()
            self.recorder.event(
                hc,
                EVENT_WARNING,
                "Warning",
                "Workflow execution is stopped; either spec.RepeatAfterSec or "
                "spec.Schedule must be provided",
            )
            await self._update_status(hc)
            return None

        # cron → effective interval (reference: :251-263)
        if spec.repeat_after_sec <= 0 and spec.schedule.cron:
            try:
                hc.spec.repeat_after_sec = seconds_until_next(
                    spec.schedule.cron, self.clock.now()
                )
            except CronParseError as e:
                self.recorder.event(hc, EVENT_WARNING, "Warning", "Fail to parse cron")
                log.error("fail to parse cron for %s: %s", hc.key, e)
                raise
        # dedupe (reference: :264-267): the schedule is current (no run
        # is owed yet) and a timer is known for this check ⇒ healthy.
        # Divergence 4: unlike the reference (where this guard is an
        # `else if` that cron specs never reach, so each status-write
        # event resubmits immediately — continuous churn), the guard
        # applies to cron checks too — "current" for a cron spec means
        # no fire has passed since the last finish (comparing elapsed
        # against the delta-to-NEXT-fire is wrong for absolute schedules
        # reconciled late in a period).
        remaining = self._schedule_remaining(hc)
        # nothing owed yet AND a live (unfired) timer ⇒ the schedule is
        # healthy; let the timer drive the next run. Time-bounding the
        # guard matters: a fired-but-bailed timer entry must not wedge
        # the check forever, and a spec edited to a faster cadence must
        # not wait out the old timer.
        if remaining is not None and self.timers.pending(hc.key):
            return None
        # a watch for this check is still in flight (workflow running
        # longer than the interval): don't stack a duplicate run
        if self._watch_active(hc.key):
            return None
        # Divergence 10: true resume after a controller restart. The
        # reference's dedupe needs its process-local timer, so a restart
        # resubmits EVERY recent check at once (a restart storm). Here a
        # current-schedule check with no live timer — the boot-resync
        # state, or a cadence shrunk by a spec edit — (re)builds its
        # timer from durable status for the remaining time to the owed
        # fire. Overdue checks (a fire passed while down) fall through
        # and run immediately.
        if remaining is not None:
            self.timers.schedule(hc.key, remaining, self._resubmit_callback(hc))
            self.recorder.event(
                hc,
                EVENT_NORMAL,
                "Normal",
                "Schedule resumed from durable status for the remaining interval",
            )
            return None
        # a run is owed NOW: cancel any still-pending timer first (the
        # sub-second rounding sliver, or a stale long timer after a spec
        # edit) so it cannot double-fire behind this submission
        self.timers.stop(hc.key)

        # per-run RBAC (reference: :269)
        await self.rbac.create_rbac_for_workflow(hc, WORKFLOW_TYPE_HEALTHCHECK)

        wf_name = await self._submit_workflow(hc)
        self._spawn_watch(hc, wf_name)
        return None

    def _schedule_remaining(self, hc: HealthCheck) -> Optional[float]:
        """Seconds until the NEXT owed fire, judged purely from durable
        status — or None when a run is owed right now (never ran, or a
        fire/interval passed since finished_at, e.g. while the
        controller was down). One definition serves both the dedupe
        guard (remaining is not None ⇒ nothing owed yet) and the
        restart-resume timer (anchored at finished_at, so downtime
        neither double-runs nor stretches the cadence)."""
        if hc.status.finished_at is None:
            return None  # never ran: owed now
        now = self.clock.now()
        if hc.spec.schedule.cron:
            try:
                schedule = parse_cron(hc.spec.schedule.cron)
                next_after_finish = schedule.next(hc.status.finished_at)
            except CronParseError:
                return None  # unparseable: let the normal path complain
            if next_after_finish <= now:
                return None  # a fire passed since the last finish: owed
            return max(1.0, (next_after_finish - now).total_seconds())
        elapsed = (now - hc.status.finished_at).total_seconds()
        if elapsed >= hc.spec.repeat_after_sec:
            return None  # interval elapsed: owed
        return max(1.0, hc.spec.repeat_after_sec - elapsed)

    # ------------------------------------------------------------------
    # submit (reference: createSubmitWorkflow, :502-534)
    # ------------------------------------------------------------------
    async def _parse_manifest(self, parser, hc: HealthCheck, workflow_spec):
        """A url/file artifact read is BLOCKING I/O (requests.get with
        a 30 s timeout; a possibly-NFS disk read) — run inline on the
        loop it would freeze every other check, the watches, AND lease
        renewal (whose ~2/3-lease deadline a slow artifact server could
        eat, costing leadership for a fetch). Only the I/O-bearing
        sources pay the thread hop — the store layer owns that
        classification next to its reader dispatch — so inline-source
        fake-clock tests stay deterministic."""
        from activemonitor_tpu.store import is_blocking_source

        resource = getattr(workflow_spec, "resource", None)
        if is_blocking_source(getattr(resource, "source", None)):
            return await asyncio.to_thread(parser, hc)
        return parser(hc)

    @property
    def _engine_name(self) -> str:
        """Label value for the engine submit/poll counters."""
        return getattr(self.engine, "name", type(self.engine).__name__)

    async def _submit_workflow(self, hc: HealthCheck) -> str:
        try:
            with self.tracer.span("parse", healthcheck=hc.key):
                manifest = await self._parse_manifest(
                    parse_workflow_from_healthcheck, hc, hc.spec.workflow
                )
        except Exception:
            self.recorder.event(
                hc, EVENT_WARNING, "Warning", "Error creating or submitting workflow"
            )
            raise
        with self.tracer.span(
            "submit", healthcheck=hc.key, engine=self._engine_name
        ):
            wf_name = await self.engine.submit(manifest)
        self.metrics.record_engine_submit(self._engine_name)
        self.recorder.event(hc, EVENT_NORMAL, "Normal", "Successfully created workflow")
        return wf_name

    async def _pace_poll(
        self, ieb: InverseExpBackoff, wf_namespace: str, wf_name: str
    ) -> bool:
        """One backoff step between status polls. Engines exposing
        ``wait_change`` (the Argo engine's watch-backed cache) wake the
        loop the moment the workflow object changes instead of sleeping
        out the whole delay — detection becomes event-driven with the
        inverse-exp cadence as the fallback bound. The change-wait races
        the pacing sleep on ``self.clock``, so fake-clock tests drive
        time exactly as with poll-only engines. Returns False once the
        poll deadline has passed (caller synthesizes failure)."""
        waiter = getattr(self.engine, "wait_change", None)
        if waiter is None:
            return await ieb.next()
        if ieb.expired():
            return False
        sleep_task = asyncio.ensure_future(self.clock.sleep(ieb.advance()))
        wake_task = asyncio.ensure_future(waiter(wf_namespace, wf_name))
        try:
            await asyncio.wait(
                {sleep_task, wake_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if (
                wake_task.done()
                and not wake_task.cancelled()
                and wake_task.exception() is not None
                and not sleep_task.done()
            ):
                # a raising wait_change must not turn into an unpaced
                # hot poll loop: log it and let the backoff sleep pace
                log.warning(
                    "wait_change for %s/%s failed (%r); falling back to "
                    "timed polling for this step",
                    wf_namespace,
                    wf_name,
                    wake_task.exception(),
                )
                await sleep_task
        finally:
            for task in (sleep_task, wake_task):
                if not task.done():
                    task.cancel()
            await asyncio.gather(sleep_task, wake_task, return_exceptions=True)
        return True

    def _watch_active(self, key: str) -> bool:
        t = self._watch_tasks.get(key)
        return t is not None and not t.done()

    def _spawn_watch(self, hc: HealthCheck, wf_name: str) -> None:
        """Divergence 1: poll in a free task, not in the reconcile worker."""
        key = hc.key
        self._watch_tasks[key] = asyncio.create_task(
            self._watch_guarded(hc, wf_name),
            name=f"watch:{key}:{wf_name}",
        )

    async def _watch_guarded(self, hc: HealthCheck, wf_name: str) -> None:
        """Exception recovery for detached watch tasks: a transient
        engine/client error must not silently kill the check's schedule
        — emulate the reference's 1s requeue (:204) by re-reconciling."""
        try:
            await self._watch_workflow_reschedule(hc, wf_name)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("watch failed for %s; requeueing in 1s", hc.key)
            self.recorder.event(
                hc, EVENT_WARNING, "Warning", "Error executing Workflow"
            )
            await self._requeue_until_clean(hc)

    async def _requeue_until_clean(self, hc: HealthCheck) -> None:
        """Put the check back on the reconcile path after a failed run —
        and keep it there until a reconcile lands cleanly (a single
        shot would strand the schedule if the API-server outage
        outlives one retry; the reference's workqueue re-rate-limits
        indefinitely, deletion ends the loop via None). Deregisters
        this task from the in-flight table first: the guard must not
        see a (still-running) requeue and skip the retry.

        Under a Manager the requeue goes through its WORKQUEUE
        (requeue_hook): per-key serialized against event-driven
        reconciles, honors stop, and a crashed reconcile re-rate-limits
        at 1 s — so no reconcile ever runs outside the queue's
        discipline, and nothing outlives Manager.stop(). The in-task
        loop remains only for standalone reconcilers (no Manager), is
        tracked in ``_requeue_loops``, and exits on shutdown."""
        if self._watch_tasks.get(hc.key) is asyncio.current_task():
            del self._watch_tasks[hc.key]
        current = asyncio.current_task()
        if current is not None:
            # tracked for BOTH paths: the hook path's 1 s sleeper was
            # deregistered from _watch_tasks above, so without this it
            # would be invisible to shutdown() and outlive stop()
            self._requeue_loops.add(current)
        if self.requeue_hook is not None:
            try:
                await self.clock.sleep(1.0)
                if not self._stopping:
                    self.requeue_hook(hc.metadata.namespace, hc.metadata.name)
            finally:
                if current is not None:
                    self._requeue_loops.discard(current)
            return
        try:
            delay: Optional[float] = 1.0
            while delay and not self._stopping:
                await self.clock.sleep(delay)
                if self._stopping:
                    return
                try:
                    delay = await self.reconcile(
                        hc.metadata.namespace, hc.metadata.name
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("requeued reconcile of %s failed", hc.key)
                    delay = 1.0
        finally:
            if current is not None:
                self._requeue_loops.discard(current)

    async def wait_watches(self) -> None:
        """Test/shutdown helper: wait for all in-flight watches."""
        tasks = [t for t in self._watch_tasks.values() if not t.done()]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def shutdown(self) -> None:
        self._stopping = True
        stragglers = list(self._watch_tasks.values()) + list(self._requeue_loops)
        for t in stragglers:
            if not t.done():
                t.cancel()
        await asyncio.gather(*stragglers, return_exceptions=True)
        await self.timers.shutdown()

    async def _poll_workflow(
        self,
        wf_namespace: str,
        wf_name: str,
        ieb: InverseExpBackoff,
        timed_out: bool,
        *,
        storm_rides_past_deadline: bool,
        what: str = "workflow",
    ):
        """One poll step shared by the healthcheck and remedy watches —
        the error policy lives HERE so the two loops cannot drift:

        - pre-deadline errors always retry in place at the 1 s requeue
          cadence (aborting to a requeued reconcile submits a DUPLICATE
          workflow for the same fire — the defect the chaos soak found);
        - past the deadline, the verdict comes from an authoritative
          confirm-read. A TRANSIENT error (5xx/429) retries that read
          when ``storm_rides_past_deadline`` (healthcheck watch: the
          liveness of the old requeue-forever ladder, without its
          duplicates); a DETERMINISTIC error (4xx, code bug) — or any
          error on the remedy path, whose ephemeral WRITE-capable RBAC
          must not stay alive under an unbounded storm — stops
          retrying, and the caller synthesizes Failed.

        Returns ``(workflow, timed_out, retry)``; ``retry=True`` means
        the caller should ``continue`` its loop (workflow is None then).
        """
        self.metrics.record_engine_poll(self._engine_name)
        try:
            if timed_out:
                # the deadline verdict must come from the API server,
                # not a possibly-lagging watch cache: a terminal phase
                # that landed during a watch reconnect gap must win
                getter = getattr(self.engine, "get_fresh", self.engine.get)
                return await getter(wf_namespace, wf_name), timed_out, False
            return await self.engine.get(wf_namespace, wf_name), timed_out, False
        except asyncio.CancelledError:
            raise
        except Exception as e:
            transient = is_transient(e)
            log.warning(
                "%s error polling %s %s/%s%s",
                "transient" if transient else "deterministic",
                what,
                wf_namespace,
                wf_name,
                (
                    "; giving up on this run (synthesizing Failed)"
                    if timed_out and not (transient and storm_rides_past_deadline)
                    else "; retrying"
                ),
                exc_info=True,
            )
            if timed_out and not (transient and storm_rides_past_deadline):
                return {}, timed_out, False  # caller synthesizes Failed
            await self.clock.sleep(1.0)
            if ieb.expired():
                timed_out = True
            return None, timed_out, True

    # ------------------------------------------------------------------
    # watch + status + reschedule (reference: watchWorkflowReschedule, :607-757)
    # ------------------------------------------------------------------
    async def _watch_workflow_reschedule(self, hc: HealthCheck, wf_name: str) -> None:
        wf_namespace = hc.spec.workflow.resource.namespace
        then = self.clock.now()
        params = compute_backoff_params(
            workflow_timeout=hc.spec.workflow.timeout,
            backoff_max=hc.spec.backoff_max,
            backoff_min=hc.spec.backoff_min,
            backoff_factor=hc.spec.backoff_factor,
        )
        ieb = InverseExpBackoff(params, self.clock)
        timed_out = False
        run_remedy = False
        polls = 0
        # one "poll" span bounds the whole detection window (submit →
        # terminal phase); remedy and the status write are SIBLING
        # phases recorded after it, so per-phase durations add up to the
        # cycle instead of nesting remedy time inside poll time
        with self.tracer.span(
            "poll", healthcheck=hc.key, workflow=wf_name
        ) as poll_span:
            while True:
                now = self.clock.now()
                polls += 1
                workflow, timed_out, retry = await self._poll_workflow(
                    wf_namespace, wf_name, ieb, timed_out,
                    storm_rides_past_deadline=True,
                )
                if retry:
                    continue
                if workflow is None:
                    # workflow GC'd / healthcheck deleted: swallow, no reschedule
                    # (reference: :618-623)
                    self.recorder.event(
                        hc,
                        EVENT_WARNING,
                        "Warning",
                        "Error attempting to find workflow for healthcheck. This may "
                        "indicate that either the healthcheck was removed or the "
                        "Workflow was GC'd before active-monitor could obtain the status",
                    )
                    poll_span.attrs["outcome"] = "gone"
                    return
                status = workflow.get("status") or {}
                if timed_out and status.get("phase") not in (PHASE_SUCCEEDED, PHASE_FAILED):
                    # poll deadline exceeded ⇒ synthesized failure (reference:
                    # :627-632 — though unlike the reference, a terminal phase
                    # seen on this final poll is honored rather than discarded)
                    status = {"phase": PHASE_FAILED, "message": PHASE_FAILED}
                    self.recorder.event(hc, EVENT_WARNING, "Warning", "Workflow timed out")
                phase = status.get("phase")

                if phase == PHASE_SUCCEEDED:
                    self.recorder.event(
                        hc, EVENT_NORMAL, "Normal", "Workflow status is Succeeded"
                    )
                    hc.status.status = PHASE_SUCCEEDED
                    hc.status.started_at = then
                    hc.status.finished_at = now
                    hc.status.success_count += 1
                    hc.status.total_healthcheck_runs = (
                        hc.status.success_count + hc.status.failed_count
                    )
                    hc.status.last_successful_workflow = wf_name
                    self.metrics.record_success(
                        hc.metadata.name,
                        WORKFLOW_LABEL_HEALTHCHECK,
                        then.timestamp(),
                        now.timestamp(),
                    )
                    # custom metrics, wired for real (reference gap: SURVEY.md §2)
                    self.metrics.record_custom_metrics(hc.metadata.name, status)
                    # the run lands in the result history on the same
                    # path that writes status — one source for SLO math
                    self.fleet.record(
                        hc,
                        ok=True,
                        latency=(now - then).total_seconds(),
                        workflow=wf_name,
                    )
                    if not hc.spec.remedy_workflow.is_empty() and hc.status.remedy_total_runs >= 1:
                        hc.status.reset_remedy("HealthCheck Passed so Remedy is reset")
                        self.recorder.event(
                            hc, EVENT_NORMAL, "Normal", "HealthCheck passed so Remedy is reset"
                        )
                    break

                if phase == PHASE_FAILED:
                    self.recorder.event(
                        hc, EVENT_WARNING, "Warning", "Workflow status is Failed"
                    )
                    hc.status.status = PHASE_FAILED
                    hc.status.started_at = then
                    hc.status.finished_at = now
                    hc.status.last_failed_at = now
                    hc.status.error_message = str(status.get("message") or "")
                    hc.status.failed_count += 1
                    hc.status.total_healthcheck_runs = (
                        hc.status.success_count + hc.status.failed_count
                    )
                    hc.status.last_failed_workflow = wf_name
                    self.metrics.record_failure(
                        hc.metadata.name,
                        WORKFLOW_LABEL_HEALTHCHECK,
                        then.timestamp(),
                        now.timestamp(),
                    )
                    self.metrics.record_custom_metrics(hc.metadata.name, status)
                    self.fleet.record(
                        hc,
                        ok=False,
                        latency=(now - then).total_seconds(),
                        workflow=wf_name,
                    )
                    run_remedy = True
                    break

                if not await self._pace_poll(ieb, wf_namespace, wf_name):
                    timed_out = True
            poll_span.attrs["outcome"] = phase
            poll_span.attrs["polls"] = polls
        if run_remedy:
            # same position in the flow as the reference's in-loop call
            # (:681): after failure accounting, before the status write
            await self._maybe_run_remedy(hc)

        # status write + reschedule (reference: :732-755)
        if hc.metadata.deletion_timestamp is None:
            try:
                with self.tracer.span("status_write", healthcheck=hc.key):
                    await self._update_status(hc)
            except NotFoundError:
                self.timers.stop(hc.key)
                return
            except Exception:
                # transient write failure (API-server blip outliving the
                # conflict retries): raise so _watch_guarded requeues in
                # 1s like the reference's reconcile error path (:204).
                # Stopping the timer here instead would leave the check
                # schedule dead until some external watch event arrived.
                log.exception("error updating healthcheck resource %s", hc.key)
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "Error updating healthcheck resource"
                )
                raise
            repeat = self._effective_repeat_after(hc)
            if repeat > 0:
                self.timers.schedule(hc.key, repeat, self._resubmit_callback(hc))
                self.recorder.event(
                    hc, EVENT_NORMAL, "Normal", "Rescheduled workflow for next run"
                )

    def _effective_repeat_after(self, hc: HealthCheck) -> int:
        """Divergence 2: recompute the interval at reschedule time."""
        if hc.spec.repeat_after_sec > 0 and not hc.spec.schedule.cron:
            return hc.spec.repeat_after_sec
        if hc.spec.schedule.cron:
            try:
                return seconds_until_next(hc.spec.schedule.cron, self.clock.now())
            except CronParseError:
                return 0
        return hc.spec.repeat_after_sec

    def _resubmit_callback(self, prev_hc: HealthCheck):
        """Timer-fired resubmission (reference: createSubmitWorkflowHelper,
        :479-500): re-fetch the CR, submit, watch."""

        namespace, name = prev_hc.metadata.namespace, prev_hc.metadata.name

        async def resubmit() -> None:
            # atomically (no awaits) check-and-claim the in-flight slot:
            # registering BEFORE the first await means a concurrent
            # reconcile sees _watch_active and cannot cancel this timer
            # task mid-submit (which would orphan a created workflow)
            current = asyncio.current_task()
            existing = self._watch_tasks.get(f"{namespace}/{name}")
            if existing is not None and not existing.done() and existing is not current:
                # a run is still in flight (it will reschedule on its
                # own completion) — never stack a duplicate
                return
            if current is not None:
                self._watch_tasks[f"{namespace}/{name}"] = current

            hc = await self.client.get(namespace, name)
            if hc is None:
                return
            # the spec may have changed since this timer was armed: if
            # nothing is owed under the CURRENT spec (cadence slowed, or
            # a sub-second rounding sliver), re-arm for the remaining
            # time instead of firing early
            remaining = self._schedule_remaining(hc)
            if remaining is not None:
                self.timers.schedule(hc.key, remaining, self._resubmit_callback(hc))
                return
            # keep the effective interval for timeout/backoff derivation
            if hc.spec.repeat_after_sec <= 0 and hc.spec.schedule.cron:
                try:
                    hc.spec.repeat_after_sec = seconds_until_next(
                        hc.spec.schedule.cron, self.clock.now()
                    )
                except CronParseError:
                    return
            if hc.spec.repeat_after_sec <= 0:
                return  # paused since the timer was armed
            # a fresh ROOT trace per timer-driven run: the timer task's
            # context snapshot was taken when the PREVIOUS cycle armed
            # it, so inheriting would chain every run of this check into
            # one unbounded trace
            with self.tracer.trace("cycle", healthcheck=hc.key, origin="timer"):
                try:
                    await self.rbac.create_rbac_for_workflow(
                        hc, WORKFLOW_TYPE_HEALTHCHECK
                    )
                    wf_name = await self._submit_workflow(hc)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception(
                        "error creating or submitting workflow for %s", hc.key
                    )
                    self.recorder.event(
                        hc,
                        EVENT_WARNING,
                        "Warning",
                        "Error creating or submitting workflow",
                    )
                    # the timer entry is consumed, so bailing here would end
                    # the check's schedule FOREVER (the chaos-soak tier
                    # caught exactly this: a 500 on the timer-fired resubmit
                    # left dead schedules — owed run, no timer, no watch).
                    # Ride the same requeue ladder a failed watch uses.
                    await self._requeue_until_clean(hc)
                    return
                # already registered in _watch_tasks at the top, so
                # reconcile's in-flight guard and wait_watches() saw this
                # timer-driven run from before the submit
                await self._watch_guarded(hc, wf_name)

        return resubmit

    # ------------------------------------------------------------------
    # remedy (reference: :677-721 gating, processRemedyWorkflow :759-786,
    # watchRemedyWorkflow :788-874)
    # ------------------------------------------------------------------
    async def _maybe_run_remedy(self, hc: HealthCheck) -> None:
        spec = hc.spec
        if spec.remedy_workflow.is_empty():
            return
        if spec.remedy_runs_limit != 0 and spec.remedy_reset_interval != 0:
            if spec.remedy_runs_limit > hc.status.remedy_total_runs:
                await self._process_remedy(hc)
            else:
                # limit hit: wait out the reset interval, then reset and run
                # (reference: :689-711)
                since_last = (
                    (self.clock.now() - hc.status.remedy_finished_at).total_seconds()
                    if hc.status.remedy_finished_at is not None
                    else float("inf")
                )
                if spec.remedy_reset_interval >= since_last:
                    log.info(
                        "skipping remedy for %s: run limit reached, waiting out "
                        "the reset interval",
                        hc.key,
                    )
                else:
                    hc.status.reset_remedy("RemedyResetInterval elapsed so Remedy is reset")
                    self.recorder.event(
                        hc,
                        EVENT_NORMAL,
                        "Normal",
                        "RemedyResetInterval elapsed so Remedy is reset",
                    )
                    await self._process_remedy(hc)
        else:
            # gates unset ⇒ always run (reference: :712-720)
            await self._process_remedy(hc)

    async def _process_remedy(self, hc: HealthCheck) -> None:
        with self.tracer.span("remedy", healthcheck=hc.key):
            await self._process_remedy_inner(hc)

    async def _process_remedy_inner(self, hc: HealthCheck) -> None:
        await self.rbac.create_rbac_for_workflow(hc, WORKFLOW_TYPE_REMEDY)
        # remedy RBAC is ephemeral (reference: :779-784) — and because
        # it is the WRITE-capable identity, it must be torn down on
        # every exit path: a parse error, a submit failure, or an engine
        # exception mid-watch may not leave the SA/Role/Binding behind
        # (the reference shares this leak shape at
        # healthcheck_controller.go:773-784; we close it)
        try:
            try:
                with self.tracer.span(
                    "parse", healthcheck=hc.key, workflow_type="remedy"
                ):
                    manifest = await self._parse_manifest(
                        parse_remedy_workflow_from_healthcheck,
                        hc,
                        hc.spec.remedy_workflow,
                    )
            except Exception:
                self.recorder.event(
                    hc,
                    EVENT_WARNING,
                    "Warning",
                    "Error creating or submitting remedyworkflow",
                )
                raise
            with self.tracer.span(
                "submit",
                healthcheck=hc.key,
                workflow_type="remedy",
                engine=self._engine_name,
            ):
                wf_name = await self.engine.submit(manifest)
            self.metrics.record_engine_submit(self._engine_name)
            self.recorder.event(
                hc, EVENT_NORMAL, "Normal", "Successfully created remedyWorkflow"
            )
            await self._watch_remedy_workflow(hc, wf_name)
        finally:
            try:
                await self.rbac.delete_rbac_for_workflow(hc)
            except Exception:
                # a failed teardown must not mask the original error;
                # the next remedy run retries the delete via the
                # collision-rename path
                log.warning(
                    "failed to delete ephemeral remedy RBAC for %s",
                    hc.key,
                    exc_info=True,
                )

    async def _watch_remedy_workflow(self, hc: HealthCheck, wf_name: str) -> None:
        wf_namespace = hc.spec.remedy_workflow.resource.namespace
        then = self.clock.now()
        # remedy polling derives from the CHECK's timeout with default
        # factor — parity with the reference (:791-801)
        params = compute_backoff_params(workflow_timeout=hc.spec.workflow.timeout)
        ieb = InverseExpBackoff(params, self.clock)
        timed_out = False
        with self.tracer.span(
            "poll", healthcheck=hc.key, workflow=wf_name, workflow_type="remedy"
        ):
            write_owed = await self._watch_remedy_loop(
                hc, wf_name, wf_namespace, then, ieb, timed_out
            )
        if not write_owed:
            return
        if hc.metadata.deletion_timestamp is None:
            try:
                with self.tracer.span(
                    "status_write", healthcheck=hc.key, workflow_type="remedy"
                ):
                    await self._update_status(hc)
            except NotFoundError:
                self.timers.stop(hc.key)

    async def _watch_remedy_loop(
        self, hc, wf_name, wf_namespace, then, ieb, timed_out
    ) -> bool:
        """Poll the remedy workflow to a terminal verdict and record it
        on ``hc.status``; returns False when the workflow vanished
        (parent deleted / GC'd) and no status write is owed."""
        while True:
            now = self.clock.now()
            workflow, timed_out, retry = await self._poll_workflow(
                wf_namespace, wf_name, ieb, timed_out,
                # the finally in _process_remedy would otherwise hold the
                # WRITE-capable ephemeral RBAC alive under an unbounded
                # storm — the remedy path always converges at the deadline
                storm_rides_past_deadline=False,
                what="remedy workflow",
            )
            if retry:
                continue
            if workflow is None:
                return False  # parent deleted / GC'd (reference: :806-810)
            status = workflow.get("status") or {}
            if timed_out and status.get("phase") not in (PHASE_SUCCEEDED, PHASE_FAILED):
                # same final-poll policy as the healthcheck loop above: a
                # terminal phase seen at the deadline is honored, not discarded
                status = {"phase": PHASE_FAILED, "message": PHASE_FAILED}
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "remedy workflow is timedout"
                )
            phase = status.get("phase")

            if phase == PHASE_SUCCEEDED:
                self.recorder.event(
                    hc, EVENT_NORMAL, "Normal", "Remedy workflow status is Succeeded"
                )
                hc.status.remedy_status = PHASE_SUCCEEDED
                hc.status.remedy_started_at = then
                hc.status.remedy_finished_at = now
                hc.status.remedy_success_count += 1
                hc.status.remedy_total_runs = (
                    hc.status.remedy_success_count + hc.status.remedy_failed_count
                )
                hc.status.last_successful_workflow = wf_name
                self.metrics.record_success(
                    hc.metadata.name,
                    WORKFLOW_LABEL_REMEDY,
                    then.timestamp(),
                    now.timestamp(),
                )
                self.metrics.record_custom_metrics(hc.metadata.name, status)
                break
            if phase == PHASE_FAILED:
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "remedy workflow status is failed"
                )
                hc.status.remedy_status = PHASE_FAILED
                hc.status.remedy_started_at = then
                hc.status.remedy_finished_at = now
                hc.status.remedy_last_failed_at = now
                hc.status.remedy_error_message = str(status.get("message") or "")
                hc.status.remedy_failed_count += 1
                hc.status.remedy_total_runs = (
                    hc.status.remedy_success_count + hc.status.remedy_failed_count
                )
                hc.status.last_failed_workflow = wf_name
                self.metrics.record_failure(
                    hc.metadata.name,
                    WORKFLOW_LABEL_REMEDY,
                    then.timestamp(),
                    now.timestamp(),
                )
                self.metrics.record_custom_metrics(hc.metadata.name, status)
                break

            if not await self._pace_poll(ieb, wf_namespace, wf_name):
                timed_out = True
        return True

    # ------------------------------------------------------------------
    # status writes (reference: updateHealthCheckStatus, :1445-1462)
    # ------------------------------------------------------------------
    async def _update_status(self, hc: HealthCheck) -> None:
        async def attempt():
            fresh = await self.client.get(hc.metadata.namespace, hc.metadata.name)
            if fresh is None:
                raise NotFoundError(hc.key)
            fresh.status = hc.status.model_copy(deep=True)
            return await self.client.update_status(fresh)

        async def write():
            return await retry_on_conflict(attempt)

        # transient 5xx ride out IN PLACE: this write records a run
        # that already happened, and losing it sends the requeue path
        # back through a full reconcile that submits a DUPLICATE
        # workflow for the same scheduled fire (the chaos-soak tier
        # measured 26 submissions for 3 recorded runs without this)
        updated = await retry_on_transient(write, clock=self.clock)
        hc.metadata.resource_version = updated.metadata.resource_version
