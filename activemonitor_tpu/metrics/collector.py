"""Prometheus metrics.

Exact metric names/labels of the reference
(reference: internal/metrics/collector.go:19-48):

- ``healthcheck_success_count``  counter {healthcheck_name, workflow}
- ``healthcheck_error_count``    counter {healthcheck_name, workflow}
- ``healthcheck_runtime_seconds`` gauge  {healthcheck_name, workflow}
- ``healthcheck_starttime``      gauge   {healthcheck_name, workflow}
- ``healthcheck_finishedtime``   gauge   {healthcheck_name, workflow}

with ``workflow`` ∈ {healthCheck, remedy}, plus dynamic custom gauges
parsed from workflow global output parameters in the
``{"metrics": [{name, value, metrictype, help}]}`` contract
(reference: collector.go:68-115). Two deliberate fixes over the
reference: custom metrics are actually invoked from the controller (the
reference implements but never calls them — SURVEY.md §2 known
defects), and the metric-name sanitizer handles the metric's own name,
not just the HealthCheck name (collector.go:90 only rewrites ``name``).
"""

from __future__ import annotations

import collections
import json
import logging
import re
import threading
from typing import Dict, Optional

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

from activemonitor_tpu.obs.trace import current_trace_id

log = logging.getLogger(__name__)

LABEL_HC = "healthcheck_name"
LABEL_WF = "workflow"

# label values for the controller-runtime-parity families below — one
# controller and one workqueue in this process, named like
# controller-runtime would name them for the HealthCheck kind
CONTROLLER_NAME = "healthcheck"
WORKQUEUE_NAME = "healthcheck"

# reconcile result labels, exactly controller-runtime's vocabulary
# (internal/controller/metrics: success | error | requeue | requeue_after)
RECONCILE_SUCCESS = "success"
RECONCILE_ERROR = "error"
RECONCILE_REQUEUE_AFTER = "requeue_after"

# controller-runtime's reconcile-time buckets are exponential from
# microseconds up; probe workflows live in the 5ms..minutes range, so
# the low end is trimmed and the top extended to the poll-timeout scale
_DURATION_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60,
    120, 300, float("inf"),
)

WORKFLOW_LABEL_HEALTHCHECK = "healthCheck"
WORKFLOW_LABEL_REMEDY = "remedy"

# TPU probe workflows run seconds to tens of minutes; the client's
# default histogram buckets cap at 10 s, which would fold every
# multi-minute probe into +Inf. Log-spaced (~x3) 1 s .. 30 m instead —
# and deliberately few: these buckets multiply across every
# {healthcheck_name, workflow} pair, and the soak tier budgets the
# fleet's series cardinality
_PROBE_RUNTIME_BUCKETS = (
    1, 3, 10, 30, 90, 300, 900, 1800, float("inf"),
)

# front-door admission decisions are policy arithmetic (microseconds
# healthy, milliseconds under event-loop pressure) — log-spaced from
# 50µs so the 10k-requests/s soak's bounded-p99 gate is readable
_FRONTDOOR_ADMISSION_BUCKETS = (
    0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0, float("inf"),
)

# custom-metric contract types this collector implements; anything else
# is rejected with a logged warning, never silently coerced to a gauge
_CUSTOM_METRIC_KINDS = {"gauge", "counter"}


def _exemplar() -> Optional[Dict[str, str]]:
    """The active cycle's trace id as an OpenMetrics exemplar, or None
    outside any span. Rendered only by the OpenMetrics exposition;
    the plain-text scrape contract is untouched."""
    trace_id = current_trace_id()
    return {"trace_id": trace_id} if trace_id else None

_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    return _INVALID_CHARS.sub("_", name)


def _prefix_dedupe(hc: str, metric: str) -> str:
    """Join the hc-name prefix and metric name WITHOUT the reference's
    stutter (collector.go:90 yields names like
    ``tpu_ici_allreduce_ici_allreduce_busbw_gbps``): the longest token
    suffix of the hc name that is also a token prefix of the metric
    name is merged, so that example becomes
    ``tpu_ici_allreduce_busbw_gbps``. Deliberate, documented divergence
    (README metrics table): the per-check prefix survives (dashboards
    can still group by it), the repetition does not. Distinct checks
    whose merged names coincide stay separable via the
    ``healthcheck_name`` label every custom gauge carries."""
    hc_tokens = hc.split("_")
    metric_tokens = metric.split("_")
    for k in range(min(len(hc_tokens), len(metric_tokens)), 0, -1):
        if hc_tokens[-k:] == metric_tokens[:k]:
            return "_".join(hc_tokens + metric_tokens[k:])
    return hc + "_" + metric


class MetricsCollector:
    """Holds a registry; constructible per-test (the reference's global
    registry makes its own tests race — collector_test.go:82-88)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        labels = [LABEL_HC, LABEL_WF]
        # The two counters are exposed as monotonically-increasing gauges:
        # prometheus_client appends "_total" to Counter names in the
        # exposition, the Go client does not — and the scrape contract is
        # the exact name `healthcheck_success_count` (collector.go:20).
        self.monitor_success = Gauge(
            "healthcheck_success_count",
            "The total number of successful healthcheck resources",
            labels,
            registry=self.registry,
        )
        self.monitor_error = Gauge(
            "healthcheck_error_count",
            "The total number of errored healthcheck resources",
            labels,
            registry=self.registry,
        )
        self.monitor_runtime = Gauge(
            "healthcheck_runtime_seconds",
            "Time taken for the workflow to complete.",
            labels,
            registry=self.registry,
        )
        self.monitor_started_time = Gauge(
            "healthcheck_starttime",
            "Time the workflow started.",
            labels,
            registry=self.registry,
        )
        self.monitor_finished_time = Gauge(
            "healthcheck_finishedtime",
            "Time the workflow finished.",
            labels,
            registry=self.registry,
        )
        # beyond the reference (SURVEY.md §5.1): a duration histogram so
        # probe latency distributions are queryable, not just last-run
        self.monitor_runtime_histogram = Histogram(
            "healthcheck_runtime_histogram_seconds",
            "Distribution of workflow run durations.",
            labels,
            registry=self.registry,
            buckets=_PROBE_RUNTIME_BUCKETS,
        )
        # probe-internal phase timings (the stdout contract's "timings"
        # block): where inside the payload the time went — Reframe-style
        # per-phase attribution, not just end-to-end latency
        self.phase_seconds = Histogram(
            "healthcheck_phase_seconds",
            "Distribution of probe payload phase durations, from the "
            "timings block of the probe's stdout contract.",
            [LABEL_HC, "phase"],
            registry=self.registry,
            buckets=_PROBE_RUNTIME_BUCKETS,
        )
        # -- SLO families (obs/slo.py is the single writer). Unlike the
        # reference-parity families these carry a namespace label: SLO
        # gauges are SET per evaluation, and two same-named checks in
        # different namespaces would otherwise flap one series between
        # two unrelated budgets (the same bare-name clobber the
        # reference has in its timer keys)
        slo_labels = [LABEL_HC, "namespace"]
        self.slo_availability = Gauge(
            "healthcheck_slo_availability_ratio",
            "Rolling-window availability of the check against its "
            "declared slo: window",
            slo_labels,
            registry=self.registry,
        )
        self.slo_error_budget = Gauge(
            "healthcheck_error_budget_remaining",
            "Fraction of the window's error budget still unspent "
            "(negative once the budget is blown)",
            slo_labels,
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "healthcheck_slo_burn_rate",
            "Observed failure ratio over the allowed failure ratio "
            "(1.0 = burning exactly at budget)",
            slo_labels,
            registry=self.registry,
        )
        self.fleet_goodput = Gauge(
            "healthcheck_fleet_goodput_ratio",
            "Successful runs over total runs across every check's "
            "rolling window (run-weighted fleet goodput)",
            registry=self.registry,
        )
        # -- lost-goodput attribution (obs/attribution.py is the single
        # writer; docs/observability.md "Goodput attribution"). The
        # per-subsystem values are CONSERVATIVE: they sum to
        # 1 - healthcheck_fleet_goodput_ratio, so a dashboard can stack
        # them under the goodput line without double counting (tested).
        self.goodput_lost = Gauge(
            "healthcheck_goodput_lost_ratio",
            "Fraction of the fleet's windowed runs lost to each "
            "subsystem bucket (ici/hbm/compile/scheduling/"
            "control_plane/unknown); the buckets sum to "
            "1 - healthcheck_fleet_goodput_ratio",
            ["subsystem"],
            registry=self.registry,
        )
        self.goodput_attribution_info = Gauge(
            "healthcheck_goodput_attribution_info",
            "Attribution taxonomy metadata (always 1): the taxonomy "
            "version and the subsystem currently costing the most "
            "goodput ('none' while nothing is lost)",
            ["version", "top"],
            registry=self.registry,
        )
        # -- roofline families (the stdout contract's `roofline` block,
        # obs/roofline.py; docs/observability.md "Reading a roofline").
        # Per-(check, metric-prefix) gauges: the achieved fraction OF
        # ROOFLINE (not flat rated) with the bound as a label, and the
        # arithmetic intensity under it. prometheus_client gauges carry
        # no exemplars, so the runs counter below is the exemplar
        # carrier joining roofline records back to /debug/traces.
        self.probe_roofline_fraction = Gauge(
            "healthcheck_probe_roofline_fraction",
            "Achieved fraction of the probe metric's own roofline "
            "ceiling (bound label: compute/memory/comm — the ceiling "
            "the kernel could ever reach, not the flat rated peak)",
            [LABEL_HC, "metric", "bound"],
            registry=self.registry,
        )
        self.probe_arithmetic_intensity = Gauge(
            "healthcheck_probe_arithmetic_intensity",
            "Arithmetic intensity (FLOPs per HBM byte) of the probe "
            "metric's kernel, from the XLA or analytic cost model",
            [LABEL_HC, "metric"],
            registry=self.registry,
        )
        self.hbm_peak_bytes = Gauge(
            "healthcheck_hbm_peak_bytes",
            "Peak HBM bytes in use during the probe payload (the "
            "roofline block's device-memory snapshot; compare against "
            "the rated HBM capacity)",
            [LABEL_HC],
            registry=self.registry,
        )
        self.probe_roofline_runs = Counter(
            "healthcheck_probe_roofline_runs_total",
            "Probe runs that shipped at least one roofline verdict on "
            "the bound (one increment per run per bound, however many "
            "metrics carried it) — carries the cycle's trace id as an "
            "OpenMetrics exemplar (gauges cannot), joining verdicts to "
            "/debug/traces",
            [LABEL_HC, "bound"],
            registry=self.registry,
        )
        # probe/controller contract drift: timings-block entries the
        # collector had to drop (previously only a log warning —
        # invisible on /metrics)
        self.phase_timings_skipped = Counter(
            "healthcheck_phase_timings_skipped_total",
            "Phase-timing entries dropped while parsing the stdout "
            "contract's timings block (contract drift between probe "
            "and controller versions)",
            ["reason"],
            registry=self.registry,
        )
        # fleet rollup (beyond the reference; cf. ML-productivity-goodput
        # style metrics): what fraction of checks are healthy AND meeting
        # their cadence — the one number a fleet dashboard leads with
        self.cadence_goodput = Gauge(
            "healthcheck_cadence_goodput",
            "Fraction of HealthChecks whose last run succeeded within "
            "2x their interval",
            registry=self.registry,
        )
        # -- resilience families (resilience/ is the single writer;
        # docs/resilience.md). Degraded mode is the one bit a fleet
        # alert pages on: the controller is alive but failing soft —
        # breaker open, cadence stretched, status writes queued.
        self.controller_degraded = Gauge(
            "healthcheck_controller_degraded",
            "1 while the controller runs in degraded mode (the shared "
            "circuit breaker is open or probing); 0 while healthy",
            registry=self.registry,
        )
        self.status_write_queue_depth = Gauge(
            "healthcheck_status_write_queue_depth",
            "Status writes parked for replay while degraded",
            registry=self.registry,
        )
        # per-check containment state as kube-state-metrics-style
        # one-hot series: exactly one of the three state labels reads 1
        self.check_state = Gauge(
            "healthcheck_check_state",
            "Per-check resilience state (healthy/flapping/quarantined); "
            "1 on the current state's series, 0 on the others",
            [LABEL_HC, "namespace", "state"],
            registry=self.registry,
        )
        # -- analysis families (analysis/engine.py is the single
        # writer; docs/analysis.md). Namespace-labeled like the SLO
        # families: these are SET per evaluation, and same-named checks
        # in different namespaces must not flap one series.
        analysis_labels = [LABEL_HC, "namespace", "metric"]
        self.metric_baseline = Gauge(
            "healthcheck_metric_baseline",
            "Learned per-metric baseline statistics (stat label: mean/"
            "std/median/mad/count) for checks with spec.analysis",
            analysis_labels + ["stat"],
            registry=self.registry,
        )
        self.metric_zscore = Gauge(
            "healthcheck_metric_zscore",
            "Robust z-score of the check's latest metric sample against "
            "its learned baseline (median/MAD)",
            analysis_labels,
            registry=self.registry,
        )
        # per-check anomaly verdict as kube-state-metrics-style one-hot
        # series, lazy like healthcheck_check_state: never-anomalous
        # checks carry no series at all
        self.anomaly_state = Gauge(
            "healthcheck_anomaly_state",
            "Per-check anomaly state (ok/warning/degraded) from the "
            "baseline analysis layer; 1 on the current state's series",
            [LABEL_HC, "namespace", "state"],
            registry=self.registry,
        )
        # -- sharding families (controller/sharding.py is the single
        # writer; docs/operations.md "Sharded controller fleet"). Shard
        # ids are label values: a fleet dashboard sums
        # healthcheck_shard_checks across replicas and compares against
        # the check total — the rollup invariant the chaos soak pins.
        self.shard_owned = Gauge(
            "healthcheck_shard_owned",
            "1 while this replica holds the shard's Lease; 0 after a "
            "handoff (lost, shed, or released)",
            ["shard"],
            registry=self.registry,
        )
        self.shard_checks = Gauge(
            "healthcheck_shard_checks",
            "HealthChecks consistent-hash-assigned to a shard this "
            "replica owns (refreshed by the rollup loop)",
            ["shard"],
            registry=self.registry,
        )
        self.shard_handoffs = Counter(
            "healthcheck_shard_handoffs_total",
            "Shard ownership transitions on this replica "
            "(reason: acquired, lost, shed)",
            ["shard", "reason"],
            registry=self.registry,
        )
        self.shard_fenced_writes = Counter(
            "healthcheck_shard_fenced_writes_total",
            "Status writes rejected by the shard fence (the lease was "
            "taken over while this replica was paused — split-brain "
            "protection)",
            ["shard"],
            registry=self.registry,
        )
        self.remedy_runs = Counter(
            "healthcheck_remedy_runs_total",
            "Remedy admission decisions per check: admitted runs and "
            "runs suppressed by the fleet-wide --remedy-rate cap",
            [LABEL_HC, "namespace", "result"],
            registry=self.registry,
        )
        # engine observability: is the per-namespace workflow watch
        # stream (divergence 11) healthy, or is the controller paying
        # direct-GET fallbacks? A sustained 0 here explains elevated
        # apiserver load and slower failure detection
        self.workflow_watch_healthy = Gauge(
            "workflow_watch_healthy",
            "1 while the namespace's workflow watch stream feeds the "
            "status cache; 0 while degraded to direct GETs",
            ["namespace"],
            registry=self.registry,
        )
        # -- controller-runtime parity (the instrumentation the port
        # never reproduced — ISSUE 1): reconcile outcome/latency plus
        # the workqueue families that make a stuck or starved queue
        # visible. prometheus_client strips a trailing "_total" from
        # Counter names and re-adds it in the exposition, so these
        # Counters expose the exact controller-runtime sample names.
        self.reconcile_total = Counter(
            "controller_runtime_reconcile_total",
            "Total number of reconciliations per controller",
            ["controller", "result"],
            registry=self.registry,
        )
        self.reconcile_time = Histogram(
            "controller_runtime_reconcile_time_seconds",
            "Length of time per reconciliation per controller",
            ["controller"],
            registry=self.registry,
            buckets=_DURATION_BUCKETS,
        )
        self.active_workers = Gauge(
            "controller_runtime_active_workers",
            "Number of currently used workers per controller",
            ["controller"],
            registry=self.registry,
        )
        self.max_concurrent_reconciles = Gauge(
            "controller_runtime_max_concurrent_reconciles",
            "Maximum number of concurrent reconciles per controller",
            ["controller"],
            registry=self.registry,
        )
        self.workqueue_depth = Gauge(
            "workqueue_depth",
            "Current depth of workqueue",
            ["name"],
            registry=self.registry,
        )
        self.workqueue_adds = Counter(
            "workqueue_adds_total",
            "Total number of adds handled by workqueue",
            ["name"],
            registry=self.registry,
        )
        self.workqueue_queue_duration = Histogram(
            "workqueue_queue_duration_seconds",
            "How long an item stays in workqueue before being requested",
            ["name"],
            registry=self.registry,
            buckets=_DURATION_BUCKETS,
        )
        self.workqueue_work_duration = Histogram(
            "workqueue_work_duration_seconds",
            "How long processing an item from workqueue takes",
            ["name"],
            registry=self.registry,
            buckets=_DURATION_BUCKETS,
        )
        # engine-boundary counters: how often this controller crosses
        # into the workflow backend (submit/poll volume explains
        # apiserver load; watch restarts explain detection latency)
        self.engine_submits = Counter(
            "engine_submit_total",
            "Workflow submissions per engine backend",
            ["engine"],
            registry=self.registry,
        )
        self.engine_polls = Counter(
            "engine_poll_total",
            "Workflow status polls per engine backend",
            ["engine"],
            registry=self.registry,
        )
        self.watch_restarts = Counter(
            "workflow_watch_restarts_total",
            "Workflow watch stream restarts per namespace",
            ["namespace"],
            registry=self.registry,
        )
        # full_name -> (kind, collector): the declared metrictype is
        # part of a custom metric's identity — a name re-reported under
        # a different type must be rejected, not silently re-typed
        self._custom_metrics: Dict[str, tuple] = {}
        # (hc_name, merged_name) -> raw metric name: two DIFFERENT
        # metrics from one check must never collapse onto one series
        # (e.g. check a-b emitting b-c and c both merge to a_b_c)
        self._custom_origin: Dict[tuple, str] = {}
        self._custom_lock = threading.Lock()
        # (hc_name, namespace) pairs whose check_state trio has been
        # materialized — see set_check_state's lazy-cardinality contract
        self._state_series: set = set()
        # same laziness for the anomaly trio (analysis layer)
        self._anomaly_series: set = set()
        # (hc_name, namespace) -> metric names with baseline/zscore
        # series, so clear_analysis can drop exactly what was exported
        self._analysis_series: Dict[tuple, set] = {}
        # (hc_name, run_id) pairs whose custom metrics were already
        # recorded — a run replayed through a second path (poll AND
        # status replay) must not double-increment counter metrics.
        # Bounded FIFO so a long-lived controller stays O(1) memory.
        self._recorded_runs: "collections.OrderedDict[tuple, bool]" = (
            collections.OrderedDict()
        )
        # the attribution info series' current (version, top) labels, so
        # a top change drops the stale series instead of leaving two 1s
        self._attribution_info: Optional[tuple] = None
        # (hc_name, metric) -> last exported bound label: a kernel
        # crossing the ridge (shape change, new compiler) must move its
        # fraction series to the new bound, not leave both populated
        self._roofline_bounds: Dict[tuple, str] = {}
        # -- scenario-matrix families (analysis/matrix.py is the single
        # writer; docs/observability.md "Reading the matrix"). One
        # bounded series set per declared cell: the matrix spec is
        # config, so cardinality is the config's cell count, not the
        # fleet's.
        self.matrix_cell_value = Gauge(
            "healthcheck_matrix_cell_value",
            "A scenario-matrix cell's headline measurement (metric "
            "label names it; seconds for compute cells) from the last "
            "observed round",
            ["cell", "metric"],
            registry=self.registry,
        )
        self.matrix_cell_state = Gauge(
            "healthcheck_matrix_cell_state",
            "One-hot hysteresis verdict per matrix cell (ok/warning/"
            "degraded — the REPORTED state, which a lone noisy round "
            "never moves)",
            ["cell", "state"],
            registry=self.registry,
        )
        self.matrix_cell_roofline_fraction = Gauge(
            "healthcheck_matrix_cell_roofline_fraction",
            "The cell's achieved fraction of its own roofline ceiling, "
            "with the bound (compute/memory/comm) as a label — the "
            "ceiling a confirmed regression names",
            ["cell", "bound"],
            registry=self.registry,
        )
        self.matrix_cells = Gauge(
            "healthcheck_matrix_cells",
            "Scenario-matrix cells per round status (ok/skipped/error): "
            "skipped cells carry structured reasons in the round "
            "summary, never silent holes",
            ["status"],
            registry=self.registry,
        )
        self.matrix_bisect_runs = Counter(
            "healthcheck_matrix_bisect_runs_total",
            "Auto-bisect re-runs fired by confirmed matrix-cell "
            "regressions, by outcome (reproduced/recovered/error)",
            ["cell", "outcome"],
            registry=self.registry,
        )
        # cells whose state series have materialized, each cell's last
        # exported roofline bound, and every (cell, metric) value
        # series exported last round — same stale-series hygiene as
        # check_state / _roofline_bounds: a cell removed or renamed in
        # the spec must drop its series, not alert forever
        self._matrix_state_series: set = set()
        self._matrix_cell_bounds: Dict[str, str] = {}
        self._matrix_value_series: set = set()
        # -- front-door families (frontdoor/ is the single writer;
        # docs/operations.md "Probe-as-a-service front door"). Tenant
        # cardinality is bounded by the admission config (the quota map
        # plus whoever the default quota admits), outcome/reason/kind by
        # fixed vocabularies.
        self.frontdoor_requests = Counter(
            "healthcheck_frontdoor_requests_total",
            "Front-door check requests per tenant by DECISION-TIME "
            "outcome (cache_hit / joined / run / parked / refused) — "
            "every submitted request lands in exactly one; a parked "
            "request's later pump conversion moves the live ledger "
            "(/statusz, coalesce ratios), not this counter",
            ["tenant", "outcome"],
            registry=self.registry,
        )
        self.frontdoor_refusals = Counter(
            "healthcheck_frontdoor_refusals_total",
            "Front-door refusals per tenant by structured reason "
            "(quota / unknown_tenant / tenant_capacity / parked_full / "
            "abandoned / unrouted); never-seen tenants book under the "
            "shared (overflow) row, so the label space stays bounded",
            ["tenant", "reason"],
            registry=self.registry,
        )
        self.frontdoor_coalesce_ratio = Gauge(
            "healthcheck_frontdoor_coalesce_ratio",
            "Coalescing-cache outcome fractions over admitted lookups "
            "(kind: hit = served from a fresh ring result, join = "
            "fanned in on an in-flight run, miss = demand the cache "
            "could not absorb); hit+join is measurement capacity "
            "returned to real work",
            ["kind"],
            registry=self.registry,
        )
        # children pre-resolved: the front door refreshes these on its
        # admission hot path, and a labels() lookup per request is
        # registry-lock work the 10k-rps soak would pay for nothing
        self._frontdoor_ratio = {
            kind: self.frontdoor_coalesce_ratio.labels(kind)
            for kind in ("hit", "miss", "join")
        }
        self.frontdoor_queue_depth = Gauge(
            "healthcheck_frontdoor_queue_depth",
            "Requests the front door is holding open: degraded-mode "
            "parked requests plus waiters fanned in on in-flight runs",
            registry=self.registry,
        )
        self.frontdoor_admission_seconds = Histogram(
            "healthcheck_frontdoor_admission_seconds",
            "Front-door admission decision latency (submit to "
            "outcome decision — quota check, cache lookup, and the "
            "trigger enqueue; NOT the probe run itself)",
            registry=self.registry,
            buckets=_FRONTDOOR_ADMISSION_BUCKETS,
        )
        # -- durable-journal families (obs/journal.py is the single
        # writer; docs/observability.md "Durable telemetry journal").
        # Stream cardinality is the fixed three-stream vocabulary
        # (result / attribution / arrival).
        self.journal_appended = Counter(
            "healthcheck_journal_appended_total",
            "Telemetry-journal events appended per stream (result / "
            "attribution / arrival) — the durable tail the next boot "
            "replays its SLO windows and workload trace from",
            ["stream"],
            registry=self.registry,
        )
        self.journal_replayed = Counter(
            "healthcheck_journal_replayed_total",
            "Telemetry-journal events replayed into the fresh rings at "
            "boot, per stream; zero on a first boot or after a "
            "fresh-restore (see the journal block's restore_warning)",
            ["stream"],
            registry=self.registry,
        )
        self.journal_dropped = Counter(
            "healthcheck_journal_dropped_total",
            "Telemetry-journal events lost to append failures (full "
            "disk, unwritable directory) or skipped during replay — "
            "durability cost, never a recording-path failure",
            registry=self.registry,
        )
        self.journal_segments = Gauge(
            "healthcheck_journal_segments",
            "Journal segments currently on disk (size-capped rotation, "
            "compaction drops the oldest beyond --journal-max-bytes × "
            "the retained-segment cap)",
            registry=self.registry,
        )
        self.journal_lag_seconds = Gauge(
            "healthcheck_journal_lag_seconds",
            "Seconds between now and the newest journaled event — how "
            "much window a crash right now would lose",
            registry=self.registry,
        )
        # children pre-resolved: the journal appends on the reconciler's
        # record path and the front door's submit path — same hot-path
        # hygiene as the coalesce-ratio gauges above
        self._journal_appended = {
            stream: self.journal_appended.labels(stream)
            for stream in ("result", "attribution", "arrival")
        }
        # -- critical-path families (obs/criticalpath.py is the single
        # writer; docs/observability.md "Reading a waterfall"). Stage
        # cardinality is the fixed vocabulary CRITICAL_PATH_STAGES and
        # quantile the fixed p50/p95/p99 trio — 24 series per check,
        # the same budget line the check-state laziness defends.
        self.critical_path_seconds = Gauge(
            "healthcheck_critical_path_seconds",
            "Per-stage critical-path latency quantiles over the "
            "check's recent runs (queue_wait / admission / schedule / "
            "submit / poll / probe_phase / status_write, with every "
            "uninstrumented second booked as untracked) — the stage "
            "durations of one run sum to its wall span exactly",
            [LABEL_HC, "namespace", "stage", "quantile"],
            registry=self.registry,
        )
        self.profile_captures = Counter(
            "healthcheck_profile_captures_total",
            "Bounded jax.profiler.trace captures fired by "
            "profile-on-anomaly, by trigger reason (degraded / "
            "burn_rate); cooldown-suppressed repeats do not count",
            ["reason"],
            registry=self.registry,
        )
        self._critical_path_series: set = set()
        # -- adaptive-control families (resilience/adapt.py is the
        # single writer; docs/resilience.md "Adaptive control loop").
        # Lever cardinality is the fixed four-lever vocabulary; the
        # per-check cadence gauge is LAZY (series only while an episode
        # is engaged, removed on release/forget) — a fleet at rest
        # carries zero adaptive per-check series.
        self.adaptive_cadence_factor = Gauge(
            "healthcheck_adaptive_cadence_factor",
            "Burn-rate cadence factor folded into the check's "
            "damp_factor composition while an adaptation episode is "
            "engaged (0.5 = probing at 2x cadence); series absent when "
            "no episode is engaged",
            [LABEL_HC, "namespace"],
            registry=self.registry,
        )
        self.adaptive_lever_active = Gauge(
            "healthcheck_adaptive_lever_active",
            "Whether an adaptive-control lever currently touches any "
            "check (1/0), by lever (cadence / remedy / placement / "
            "frontdoor)",
            ["lever"],
            registry=self.registry,
        )
        self.adaptive_transitions = Counter(
            "healthcheck_adaptive_transitions_total",
            "Adaptive-control decisions by lever and action (engage / "
            "release / target) — each increment has a matching "
            "flight-recorder bundle and decision-log entry",
            ["lever", "action"],
            registry=self.registry,
        )
        self.adaptive_freshness_ceiling = Gauge(
            "healthcheck_adaptive_freshness_ceiling_seconds",
            "Front-door staleness ceiling currently in force: the "
            "operator default, stretched while the frontdoor lever is "
            "engaged under a confirmed control-plane burn; 0 when no "
            "front door is wired",
            registry=self.registry,
        )
        self.frontdoor_clamps = Counter(
            "healthcheck_frontdoor_freshness_clamped_total",
            "Front-door requests whose asked freshness exceeded the "
            "ceiling in force and was narrowed (the two-ceiling rule), "
            "by booked tenant and ceiling mode (default / degraded)",
            ["tenant", "mode"],
            registry=self.registry,
        )
        self._adaptive_cadence_series: set = set()
        # -- federation families (federation/ is the single writer;
        # docs/observability.md "Federation"). Cluster cardinality is
        # the registry config (a handful of clusters, operator-bounded);
        # tenant labels carry the BOOKED name, bounded by the global
        # admission config like the frontdoor families above.
        self.federation_clusters = Gauge(
            "healthcheck_federation_clusters",
            "Clusters currently in the federation registry, by health "
            "state (healthy / unhealthy — judged by locally-observed "
            "/statusz movement, never remote wall-clock stamps)",
            ["state"],
            registry=self.registry,
        )
        self.federation_cluster_healthy = Gauge(
            "healthcheck_federation_cluster_healthy",
            "Whether the named cluster's /statusz is still moving "
            "within the liveness window (1/0) — the bit the capability "
            "router keys rerouting on",
            ["cluster"],
            registry=self.registry,
        )
        self.federation_transitions = Counter(
            "healthcheck_federation_transitions_total",
            "Cluster membership/health transitions (cluster-join / "
            "cluster-leave / cluster-unhealthy / cluster-recovered) — "
            "each increment has a matching flight-recorder bundle",
            ["cluster", "kind"],
            registry=self.registry,
        )
        self.federation_requests = Counter(
            "healthcheck_federation_requests_total",
            "Global front-door submissions by chosen cluster and "
            "outcome (cache_hit / joined / run / parked / refused / "
            "forwarded) — the conservation ledger's columns, one level "
            "above the per-cluster frontdoor families",
            ["cluster", "outcome"],
            registry=self.registry,
        )
        self.federation_refusals = Counter(
            "healthcheck_federation_refusals_total",
            "Global front-door structured refusals by booked tenant "
            "and reason (quota / unknown_tenant / no_capable_cluster / "
            "cluster_unattached / the per-cluster door's reasons)",
            ["tenant", "reason"],
            registry=self.registry,
        )
        self.federation_routes = Counter(
            "healthcheck_federation_routes_total",
            "Capability-routing decisions by chosen cluster and match "
            "kind (slice / capability / default; (none) with "
            "no_capable_cluster when nothing healthy qualifies)",
            ["cluster", "matched"],
            registry=self.registry,
        )
        self.federation_goodput_ratio = Gauge(
            "healthcheck_federation_goodput_ratio",
            "Run-weighted goodput ratio over every cluster's latest "
            "observed /statusz — the federation-level twin of "
            "healthcheck_fleet_goodput_ratio, conserving attribution "
            "across clusters exactly as the rollup does across replicas",
            registry=self.registry,
        )
        # children pre-resolved for the registry's sweep-time refresh
        self._federation_clusters = {
            state: self.federation_clusters.labels(state)
            for state in ("healthy", "unhealthy")
        }

        # -- disaggregated serving (ISSUE 20): the prefill/decode pool
        #    split's ledgers on /metrics — prefix-cache traffic, the
        #    pool-boundary migration channel, speculative acceptance,
        #    and per-pool TTFT, fed by the probe's serving_disagg block
        #    through record_custom_metrics -------------------------------
        self.serving_prefix_cache_events = Counter(
            "healthcheck_serving_prefix_cache_events_total",
            "Content-addressed KV prefix-cache events by kind (hit / "
            "miss / insert / evict) — block-granular, the conservation "
            "ledger prompt_tokens == prefix_hits + prefill_tokens "
            "counts the same hits",
            ["event"],
            registry=self.registry,
        )
        self.serving_kv_migration_bytes = Counter(
            "healthcheck_serving_kv_migration_bytes_total",
            "KV bytes handed prefill pool -> decode pool over the "
            "migration channel, by tier (ici intra-slice / dcn "
            "cross-slice; alpha/B-modeled transfers, receipts exact "
            "to the token)",
            ["tier"],
            registry=self.registry,
        )
        self.serving_spec_accept_fraction = Gauge(
            "healthcheck_serving_spec_accept_fraction",
            "Speculative-decode draft acceptance fraction (accepted "
            "drafts over drafted) from the latest disagg serving probe "
            "— the rated-fraction the detector floors judge",
            registry=self.registry,
        )
        self.serving_pool_ttft_seconds = Gauge(
            "healthcheck_serving_pool_ttft_seconds",
            "Time-to-first-token quantiles per serving pool topology "
            "(pool: prefill for the disaggregated split, colocated for "
            "the single-pool baseline) — same requests, same cost model",
            ["pool", "quantile"],
            registry=self.registry,
        )

    # -- run accounting (reference call sites:
    #    healthcheck_controller.go:645-648,673-675,831-834,847-849) ----
    def record_success(
        self, hc_name: str, workflow: str, started: float, finished: float
    ) -> None:
        self.monitor_success.labels(hc_name, workflow).inc()
        self.monitor_runtime.labels(hc_name, workflow).set(finished - started)
        self.monitor_started_time.labels(hc_name, workflow).set(started)
        self.monitor_finished_time.labels(hc_name, workflow).set(finished)
        self.monitor_runtime_histogram.labels(hc_name, workflow).observe(
            max(0.0, finished - started), exemplar=_exemplar()
        )

    def record_failure(
        self, hc_name: str, workflow: str, started: float, finished: float
    ) -> None:
        self.monitor_error.labels(hc_name, workflow).inc()
        self.monitor_started_time.labels(hc_name, workflow).set(started)
        self.monitor_finished_time.labels(hc_name, workflow).set(finished)
        self.monitor_runtime_histogram.labels(hc_name, workflow).observe(
            max(0.0, finished - started), exemplar=_exemplar()
        )

    def record_watch_health(self, namespace: str, healthy: bool) -> None:
        self.workflow_watch_healthy.labels(namespace).set(1.0 if healthy else 0.0)

    def record_watch_restart(self, namespace: str) -> None:
        self.watch_restarts.labels(namespace).inc()

    # -- controller-runtime-parity call sites --------------------------
    def record_reconcile(self, result: str, seconds: float) -> None:
        """One reconcile finished: outcome counter + latency histogram
        (controller-runtime's ReconcileTotal/ReconcileTime pair)."""
        self.reconcile_total.labels(CONTROLLER_NAME, result).inc()
        self.reconcile_time.labels(CONTROLLER_NAME).observe(max(0.0, seconds))

    def record_queue_add(self, depth: int) -> None:
        """An Add() hit the workqueue — counted even when the queue
        coalesces it (client-go semantics: adds_total reads event
        pressure, depth reads what's actually waiting). ``depth`` is
        the post-add depth."""
        self.workqueue_adds.labels(WORKQUEUE_NAME).inc()
        self.workqueue_depth.labels(WORKQUEUE_NAME).set(depth)

    def record_queue_get(self, depth: int, waited_seconds: float) -> None:
        """A worker took a key off the queue after waiting
        ``waited_seconds`` (controller-runtime's queue_duration)."""
        self.workqueue_depth.labels(WORKQUEUE_NAME).set(depth)
        self.workqueue_queue_duration.labels(WORKQUEUE_NAME).observe(
            max(0.0, waited_seconds)
        )

    def record_work_duration(self, seconds: float) -> None:
        self.workqueue_work_duration.labels(WORKQUEUE_NAME).observe(
            max(0.0, seconds)
        )

    def set_active_workers(self, count: int) -> None:
        self.active_workers.labels(CONTROLLER_NAME).set(count)

    def set_max_concurrent(self, count: int) -> None:
        self.max_concurrent_reconciles.labels(CONTROLLER_NAME).set(count)

    def record_engine_submit(self, engine: str) -> None:
        self.engine_submits.labels(engine).inc()

    def record_engine_poll(self, engine: str) -> None:
        self.engine_polls.labels(engine).inc()

    # -- SLO families (written by obs.slo.FleetStatus) -----------------
    def set_slo(
        self,
        hc_name: str,
        namespace: str,
        *,
        availability: float,
        error_budget_remaining: float,
        burn_rate: float,
    ) -> None:
        self.slo_availability.labels(hc_name, namespace).set(availability)
        self.slo_error_budget.labels(hc_name, namespace).set(
            error_budget_remaining
        )
        self.slo_burn_rate.labels(hc_name, namespace).set(burn_rate)

    def clear_slo(self, hc_name: str, namespace: str) -> None:
        """Deleted check (or an slo: block removed from a live spec):
        drop its SLO series so the scrape does not advertise a budget
        that no longer exists."""
        for gauge in (
            self.slo_availability,
            self.slo_error_budget,
            self.slo_burn_rate,
        ):
            try:
                gauge.remove(hc_name, namespace)
            except KeyError:
                pass  # never recorded — nothing to drop

    def set_fleet_goodput(self, ratio: float) -> None:
        self.fleet_goodput.set(ratio)

    def set_goodput_attribution(
        self, ratios: Dict[str, float], top: Optional[str], version: int = 1
    ) -> None:
        """Refresh the lost-goodput decomposition (obs/attribution.py
        is the single writer, off the reconcile path). ``ratios`` maps
        every taxonomy bucket to its lost share; ``top`` is the bucket
        currently costing the most ('none' while nothing is lost)."""
        for subsystem, ratio in ratios.items():
            self.goodput_lost.labels(subsystem).set(ratio)
        labels = (str(version), top or "none")
        if self._attribution_info is not None and self._attribution_info != labels:
            try:
                self.goodput_attribution_info.remove(*self._attribution_info)
            except KeyError:
                pass  # never materialized — nothing to drop
        self._attribution_info = labels
        self.goodput_attribution_info.labels(*labels).set(1.0)

    def record_phase_timing_skipped(self, reason: str) -> None:
        self.phase_timings_skipped.labels(reason).inc()

    # -- resilience families (written by resilience/) ------------------
    def set_degraded(self, degraded: bool) -> None:
        self.controller_degraded.set(1.0 if degraded else 0.0)

    def set_status_write_queue_depth(self, depth: int) -> None:
        self.status_write_queue_depth.set(depth)

    def set_check_state(self, hc_name: str, namespace: str, state: str) -> None:
        """One-hot the check's state series: the current state reads 1,
        the other known states read 0 (so alerts can sum() cleanly).
        LAZY by design: a check that has never left healthy carries no
        state series at all — three series per healthy check would
        dominate the fleet's cardinality budget (the soak tier pins
        ~24 series/check) for zero signal; absence means healthy. Once
        a check has degraded, the full trio persists so the recovery
        transition is visible."""
        key = (hc_name, namespace)
        if state == "Healthy" and key not in self._state_series:
            return
        self._state_series.add(key)
        from activemonitor_tpu.resilience.health import CHECK_STATES

        for known in CHECK_STATES:
            self.check_state.labels(hc_name, namespace, known.lower()).set(
                1.0 if known == state else 0.0
            )

    def clear_check_state(self, hc_name: str, namespace: str) -> None:
        """Deleted check: drop its state series."""
        from activemonitor_tpu.resilience.health import CHECK_STATES

        self._state_series.discard((hc_name, namespace))
        for known in CHECK_STATES:
            try:
                self.check_state.remove(hc_name, namespace, known.lower())
            except KeyError:
                pass  # never recorded — nothing to drop

    def record_remedy_run(self, hc_name: str, namespace: str, result: str) -> None:
        self.remedy_runs.labels(hc_name, namespace, result).inc()

    # -- sharding families (written by controller/sharding.py) ---------
    def set_shard_owned(self, shard: int, owned: bool) -> None:
        self.shard_owned.labels(str(shard)).set(1.0 if owned else 0.0)

    def set_shard_checks(self, shard: int, count: int) -> None:
        self.shard_checks.labels(str(shard)).set(count)

    def clear_shard_checks(self, shard: int) -> None:
        """Shard handed off: its check-count series must not advertise
        a stale ownership claim next to the new owner's."""
        try:
            self.shard_checks.remove(str(shard))
        except KeyError:
            pass  # never recorded — nothing to drop

    def record_shard_handoff(self, shard: int, reason: str) -> None:
        self.shard_handoffs.labels(str(shard), reason).inc()

    def record_fenced_write(self, shard: int) -> None:
        self.shard_fenced_writes.labels(str(shard)).inc()

    # -- analysis families (written by analysis/engine.py) -------------
    def set_metric_baseline(
        self,
        hc_name: str,
        namespace: str,
        metric: str,
        *,
        mean: float,
        std: float,
        median: float,
        mad: float,
        count: float,
    ) -> None:
        series = self._analysis_series.setdefault((hc_name, namespace), set())
        series.add(metric)
        metric = _sanitize(metric)
        for stat, value in (
            ("mean", mean),
            ("std", std),
            ("median", median),
            ("mad", mad),
            ("count", count),
        ):
            self.metric_baseline.labels(hc_name, namespace, metric, stat).set(value)

    def set_metric_zscore(
        self, hc_name: str, namespace: str, metric: str, zscore: float
    ) -> None:
        self._analysis_series.setdefault((hc_name, namespace), set()).add(metric)
        self.metric_zscore.labels(hc_name, namespace, _sanitize(metric)).set(zscore)

    def set_anomaly_state(
        self, hc_name: str, namespace: str, state: str, *, materialize: bool = True
    ) -> None:
        """One-hot the check's anomaly trio. LAZY like set_check_state:
        an ok-only check carries no series (absence means ok — three
        series per healthy check would blow the cardinality budget for
        zero signal). ``materialize=False`` keeps an ok report from
        creating the trio; once any non-ok state (or a restored durable
        mark) materialized it, the full trio persists so the recovery
        transition is visible."""
        key = (hc_name, namespace)
        if state == "ok" and not materialize and key not in self._anomaly_series:
            return
        self._anomaly_series.add(key)
        from activemonitor_tpu.analysis.detector import ANOMALY_STATES

        for known in ANOMALY_STATES:
            self.anomaly_state.labels(hc_name, namespace, known).set(
                1.0 if known == state else 0.0
            )

    def clear_analysis(self, hc_name: str, namespace: str) -> None:
        """Deleted check (or analysis: block removed): drop every
        analysis series the check ever exported."""
        from activemonitor_tpu.analysis.baseline import BASELINE_STATS
        from activemonitor_tpu.analysis.detector import ANOMALY_STATES

        key = (hc_name, namespace)
        for metric in self._analysis_series.pop(key, ()):
            metric = _sanitize(metric)
            for stat in BASELINE_STATS:
                try:
                    self.metric_baseline.remove(hc_name, namespace, metric, stat)
                except KeyError:
                    pass  # stat never exported for this metric
            try:
                self.metric_zscore.remove(hc_name, namespace, metric)
            except KeyError:
                pass  # zscore only exists after warm-up
        if key in self._anomaly_series:
            self._anomaly_series.discard(key)
            for state in ANOMALY_STATES:
                try:
                    self.anomaly_state.remove(hc_name, namespace, state)
                except KeyError:
                    pass  # never recorded

    # -- scenario-matrix families (analysis/matrix.py round summary) ---
    def record_matrix_round(self, summary: dict) -> None:
        """Export one matrix round summary into the pinned
        ``healthcheck_matrix_*`` families. Defensive over the summary
        shape (it also rides bench artifacts and the sidecar, so a
        version-skewed blob must degrade to partial gauges, not raise
        into the observatory)."""
        from activemonitor_tpu.analysis.detector import ANOMALY_STATES

        if not isinstance(summary, dict):
            return
        cells = summary.get("cells")
        cells = cells if isinstance(cells, dict) else {}
        counts = {"ok": 0, "skipped": 0, "error": 0}
        live_values: set = set()
        live_states: set = set()
        live_bounds: set = set()
        for cell_id, entry in cells.items():
            if not isinstance(entry, dict):
                continue
            cell = _sanitize(str(cell_id))
            status = str(entry.get("status", "error"))
            counts[status if status in counts else "error"] += 1
            value = entry.get("value")
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                metric = _sanitize(str(entry.get("metric") or "value"))
                live_values.add((cell, metric))
                self.matrix_cell_value.labels(cell, metric).set(float(value))
            verdict = entry.get("verdict")
            if verdict in ANOMALY_STATES:
                live_states.add(cell)
                # one-hot like check_state, lazily materialized: a cell
                # that has never left ok carries no state series
                if verdict != "ok" or cell in self._matrix_state_series:
                    self._matrix_state_series.add(cell)
                    for state in ANOMALY_STATES:
                        self.matrix_cell_state.labels(cell, state).set(
                            1.0 if state == verdict else 0.0
                        )
            roofline = entry.get("roofline")
            if isinstance(roofline, dict):
                bound = roofline.get("bound")
                fraction = roofline.get("fraction")
                if isinstance(bound, str) and isinstance(fraction, (int, float)):
                    live_bounds.add(cell)
                    previous = self._matrix_cell_bounds.get(cell)
                    if previous is not None and previous != bound:
                        try:
                            self.matrix_cell_roofline_fraction.remove(
                                cell, previous
                            )
                        except KeyError:
                            pass  # never exported under the old bound
                    self._matrix_cell_bounds[cell] = bound
                    self.matrix_cell_roofline_fraction.labels(cell, bound).set(
                        float(fraction)
                    )
        # stale-series hygiene, judged PER SERIES KIND on this round's
        # fresh evidence: a cell renamed away, or one that flipped to
        # skipped/error (no verdict, no roofline this round — e.g. the
        # TPU wedged to a smaller fallback platform), must drop its
        # series with this round, not alert on stale evidence forever
        for cell, metric in self._matrix_value_series - live_values:
            try:
                self.matrix_cell_value.remove(cell, metric)
            except KeyError:
                pass  # already gone
        self._matrix_value_series = live_values
        for cell in list(self._matrix_state_series - live_states):
            self._matrix_state_series.discard(cell)
            for state in ANOMALY_STATES:
                try:
                    self.matrix_cell_state.remove(cell, state)
                except KeyError:
                    pass  # never recorded
        for cell in list(self._matrix_cell_bounds):
            if cell in live_bounds:
                continue
            bound = self._matrix_cell_bounds.pop(cell)
            try:
                self.matrix_cell_roofline_fraction.remove(cell, bound)
            except KeyError:
                pass  # never recorded
        for status, count in counts.items():
            self.matrix_cells.labels(status).set(count)
        for bisect in summary.get("bisects") or []:
            if isinstance(bisect, dict):
                self.matrix_bisect_runs.labels(
                    _sanitize(str(bisect.get("cell", "?"))),
                    str(bisect.get("outcome", "error")),
                ).inc()

    # -- front door (frontdoor/service.py is the single writer) --------
    def record_frontdoor_request(self, tenant: str, outcome: str) -> None:
        self.frontdoor_requests.labels(tenant, outcome).inc()

    def record_frontdoor_refusal(self, tenant: str, reason: str) -> None:
        self.frontdoor_refusals.labels(tenant, reason).inc()

    def set_frontdoor_coalesce(
        self, *, hit: float, miss: float, join: float
    ) -> None:
        self._frontdoor_ratio["hit"].set(hit)
        self._frontdoor_ratio["miss"].set(miss)
        self._frontdoor_ratio["join"].set(join)

    def set_frontdoor_queue_depth(self, depth: int) -> None:
        self.frontdoor_queue_depth.set(depth)

    def observe_frontdoor_admission(self, seconds: float) -> None:
        self.frontdoor_admission_seconds.observe(seconds)

    # -- durable journal (obs/journal.py is the single writer) ---------
    def record_journal_append(self, stream: str) -> None:
        child = self._journal_appended.get(stream)
        if child is None:
            child = self.journal_appended.labels(stream)
        child.inc()

    def record_journal_replayed(self, stream: str, n: int = 1) -> None:
        if n > 0:
            self.journal_replayed.labels(stream).inc(n)

    def record_journal_dropped(self) -> None:
        self.journal_dropped.inc()

    def set_journal_segments(self, count: int) -> None:
        self.journal_segments.set(count)

    def set_journal_lag(self, seconds: float) -> None:
        self.journal_lag_seconds.set(max(0.0, seconds))

    # -- critical-path families (written by obs/criticalpath via
    #    obs/slo.py's refresh loop, off the reconcile path) ------------
    def set_critical_path(
        self, hc_name: str, namespace: str, block: Optional[dict]
    ) -> None:
        """Refresh a check's per-stage quantile gauges from its
        aggregated ``critical_path`` block (same dict /statusz serves,
        so the two surfaces cannot drift). A None/empty block clears
        the series — a check whose window emptied stops advertising a
        stale decomposition."""
        if not block or not block.get("stages"):
            self.clear_critical_path(hc_name, namespace)
            return
        self._critical_path_series.add((hc_name, namespace))
        for stage, quantiles in block["stages"].items():
            for key, value in quantiles.items():
                self.critical_path_seconds.labels(
                    hc_name, namespace, stage, key
                ).set(float(value))

    def clear_critical_path(self, hc_name: str, namespace: str) -> None:
        """Deleted (or windowless) check: drop its stage series."""
        if (hc_name, namespace) not in self._critical_path_series:
            return
        self._critical_path_series.discard((hc_name, namespace))
        from activemonitor_tpu.obs.criticalpath import QUANTILE_KEYS, STAGES

        for stage in STAGES:
            for key in QUANTILE_KEYS:
                try:
                    self.critical_path_seconds.remove(
                        hc_name, namespace, stage, key
                    )
                except KeyError:
                    pass  # never recorded — nothing to drop

    def record_profile_capture(self, reason: str) -> None:
        self.profile_captures.labels(reason).inc()

    # -- adaptive control loop ----------------------------------------

    def set_adaptive_cadence(
        self, hc_name: str, namespace: str, factor: float
    ) -> None:
        """Advertise the burn-driven cadence factor the adaptive
        controller applied to a check (<1 = probing tightened)."""
        self._adaptive_cadence_series.add((hc_name, namespace))
        self.adaptive_cadence_factor.labels(hc_name, namespace).set(float(factor))

    def clear_adaptive_cadence(self, hc_name: str, namespace: str) -> None:
        """Episode released (or check deleted): drop the cadence series
        so a stale factor can't outlive the engagement."""
        if (hc_name, namespace) not in self._adaptive_cadence_series:
            return
        self._adaptive_cadence_series.discard((hc_name, namespace))
        try:
            self.adaptive_cadence_factor.remove(hc_name, namespace)
        except KeyError:
            pass  # never recorded — nothing to drop

    def set_adaptive_lever(self, lever: str, active: bool) -> None:
        self.adaptive_lever_active.labels(lever).set(1.0 if active else 0.0)

    def record_adaptive_transition(self, lever: str, action: str) -> None:
        self.adaptive_transitions.labels(lever, action).inc()

    def set_adaptive_freshness_ceiling(self, seconds: float) -> None:
        self.adaptive_freshness_ceiling.set(float(seconds))

    def record_frontdoor_clamp(self, tenant: str, mode: str) -> None:
        self.frontdoor_clamps.labels(tenant, mode).inc()

    # -- federation (federation/ is the single writer) -----------------
    def set_federation_clusters(self, healthy: int, unhealthy: int) -> None:
        self._federation_clusters["healthy"].set(healthy)
        self._federation_clusters["unhealthy"].set(unhealthy)

    def set_federation_cluster_health(self, cluster: str, healthy: bool) -> None:
        self.federation_cluster_healthy.labels(cluster).set(
            1.0 if healthy else 0.0
        )

    def record_federation_transition(self, cluster: str, kind: str) -> None:
        self.federation_transitions.labels(cluster, kind).inc()

    def record_federation_request(self, cluster: str, outcome: str) -> None:
        self.federation_requests.labels(cluster, outcome).inc()

    def record_federation_refusal(self, tenant: str, reason: str) -> None:
        self.federation_refusals.labels(tenant, reason).inc()

    def record_federation_route(self, cluster: str, matched: str) -> None:
        self.federation_routes.labels(cluster, matched).inc()

    def set_federation_goodput(self, ratio: float) -> None:
        self.federation_goodput_ratio.set(float(ratio))

    # -- dynamic custom metrics ---------------------------------------
    # recorded-run memory bound: at one run a second this is ~34 min of
    # dedupe horizon, far beyond any replay window in the controller
    RECORDED_RUN_CAPACITY = 2048

    def record_custom_metrics(
        self, hc_name: str, workflow_status: dict, run_id: str = ""
    ) -> int:
        """Parse workflow global output parameters for the custom-metric
        contract: ``metrics`` entries become gauges or counters per the
        declared ``metrictype`` (unknown types are rejected with a
        logged warning, not coerced), and a ``timings`` block feeds the
        ``healthcheck_phase_seconds`` histogram with the active cycle's
        trace id as an OpenMetrics exemplar. Returns how many ``metrics``
        entries were recorded.

        ``run_id`` (the workflow object name) dedupes recording per
        run: the reconciler can reach the same terminal status through
        more than one path (the live poll and a replayed/requeued
        status), and counter-type metrics are per-run INCREMENTS — a
        second recording would double-count them. A run id seen before
        records nothing and returns 0.

        Malformed JSON / entries are skipped with a log, never raised
        (reference: collector.go:73-87).
        """
        if run_id:
            dedupe_key = (hc_name, run_id)
            with self._custom_lock:
                if dedupe_key in self._recorded_runs:
                    return 0  # this run's metrics already landed
                self._recorded_runs[dedupe_key] = True
                while len(self._recorded_runs) > self.RECORDED_RUN_CAPACITY:
                    self._recorded_runs.popitem(last=False)
        outputs = (workflow_status or {}).get("outputs") or {}
        parameters = outputs.get("parameters") or []
        recorded = 0
        for parameter in parameters:
            value = parameter.get("value") if isinstance(parameter, dict) else None
            if not isinstance(value, str):
                continue
            try:
                doc = json.loads(value)
            except json.JSONDecodeError:
                continue
            if not isinstance(doc, dict):
                continue
            for raw in doc.get("metrics") or []:
                recorded += self._record_custom_metric(hc_name, raw)
            self._record_phase_timings(hc_name, doc.get("timings"))
            self._record_roofline(hc_name, doc.get("roofline"))
            self._record_serving_disagg(doc.get("serving_disagg"))
        return recorded

    @staticmethod
    def parse_custom_samples(workflow_status: dict) -> Dict[str, float]:
        """The run's numeric samples as ``{metric name: value}`` —
        contract spelling, no prefixing/sanitizing — for the baseline
        analysis layer and the result history. Pure read: records
        nothing, dedupes nothing, skips malformed entries silently
        (the recording path above already logs them)."""
        outputs = (workflow_status or {}).get("outputs") or {}
        parameters = outputs.get("parameters") or []
        samples: Dict[str, float] = {}
        for parameter in parameters:
            value = parameter.get("value") if isinstance(parameter, dict) else None
            if not isinstance(value, str):
                continue
            try:
                doc = json.loads(value)
            except json.JSONDecodeError:
                continue
            if not isinstance(doc, dict):
                continue
            for raw in doc.get("metrics") or []:
                if not isinstance(raw, dict):
                    continue
                name = raw.get("name") or ""
                if not isinstance(name, str) or not name:
                    continue
                try:
                    samples[name] = float(raw.get("value"))
                except (TypeError, ValueError):
                    continue
        return samples

    @staticmethod
    def parse_phase_timings(workflow_status: dict) -> Dict[str, float]:
        """The run's ``timings`` block as ``{phase: seconds}`` —
        contract spelling, no sanitizing — for the result history and
        goodput attribution. Pure read like ``parse_custom_samples``:
        records nothing, counts nothing, skips malformed entries
        silently (the recording path above logs AND counts them)."""
        outputs = (workflow_status or {}).get("outputs") or {}
        parameters = outputs.get("parameters") or []
        timings: Dict[str, float] = {}
        for parameter in parameters:
            value = parameter.get("value") if isinstance(parameter, dict) else None
            if not isinstance(value, str):
                continue
            try:
                doc = json.loads(value)
            except json.JSONDecodeError:
                continue
            if not isinstance(doc, dict):
                continue
            block = doc.get("timings")
            if not isinstance(block, dict):
                continue
            for phase, seconds in block.items():
                if not isinstance(phase, str) or not phase:
                    continue
                try:
                    timings[phase] = float(seconds)
                except (TypeError, ValueError):
                    continue
        return timings

    @staticmethod
    def parse_roofline(workflow_status: dict) -> Dict[str, dict]:
        """The run's contract ``roofline`` block as ``{metric-prefix:
        verdict dict}`` — contract spelling, validated through
        obs/roofline.py (entries the controller cannot trust are
        dropped here, once, for every consumer: the result history,
        attribution, /statusz, flight bundles). Pure read like
        ``parse_phase_timings``."""
        from activemonitor_tpu.obs import roofline as roofline_model

        outputs = (workflow_status or {}).get("outputs") or {}
        parameters = outputs.get("parameters") or []
        block: Dict[str, dict] = {}
        for parameter in parameters:
            value = parameter.get("value") if isinstance(parameter, dict) else None
            if not isinstance(value, str):
                continue
            try:
                doc = json.loads(value)
            except json.JSONDecodeError:
                continue
            if not isinstance(doc, dict):
                continue
            raw = doc.get("roofline")
            if not isinstance(raw, dict):
                continue
            for prefix, entry in raw.items():
                if not isinstance(prefix, str) or not prefix:
                    continue
                if roofline_model.valid_entry(entry):
                    block[prefix] = entry
        return block

    def _record_roofline(self, hc_name: str, block) -> None:
        """The contract's ``roofline`` block -> the pinned roofline
        families. Per entry: fraction gauge under its bound label (a
        bound flip drops the stale series), intensity gauge, and the
        exemplar-carrying runs counter; the device-memory snapshot's
        peak feeds ``healthcheck_hbm_peak_bytes`` (max over entries —
        they all observed the same device). Invalid entries are skipped
        silently: the probe-side details already carry the structured
        skip, and this path must never raise."""
        if not isinstance(block, dict) or not block:
            return
        from activemonitor_tpu.obs import roofline as roofline_model

        exemplar = _exemplar()
        peak = 0.0
        bounds_seen = set()
        for prefix, entry in block.items():
            if not isinstance(prefix, str) or not prefix:
                continue
            if not roofline_model.valid_entry(entry):
                continue
            metric = _sanitize(prefix)
            bound = str(entry["bound"])
            key = (hc_name, metric)
            previous = self._roofline_bounds.get(key)
            if previous is not None and previous != bound:
                try:
                    self.probe_roofline_fraction.remove(hc_name, metric, previous)
                except KeyError:
                    pass  # never materialized — nothing to drop
            self._roofline_bounds[key] = bound
            self.probe_roofline_fraction.labels(hc_name, metric, bound).set(
                float(entry["fraction"])
            )
            self.probe_arithmetic_intensity.labels(hc_name, metric).set(
                float(entry["intensity"])
            )
            bounds_seen.add(bound)
            try:
                peak = max(peak, float(entry.get("hbm_peak_bytes") or 0.0))
            except (TypeError, ValueError):
                pass  # snapshot field is optional garnish
        # one increment per run per bound (a battery block carries many
        # metrics; counting entries would inflate the run count any
        # coverage dashboard divides by)
        for bound in sorted(bounds_seen):
            self.probe_roofline_runs.labels(hc_name, bound).inc(
                1.0, exemplar=exemplar
            )
        if peak > 0:
            self.hbm_peak_bytes.labels(hc_name).set(peak)

    def _record_serving_disagg(self, block) -> None:
        """The contract's ``serving_disagg`` block (probes/serving.
        run_disagg details) -> the ISSUE 20 families. Same posture as
        ``_record_roofline``: malformed fields are skipped, never
        raised — the probe-side details carry the authoritative copy."""
        if not isinstance(block, dict) or not block:
            return
        counters = block.get("prefix_counters")
        if isinstance(counters, dict):
            for event, key in (
                ("hit", "hits"),
                ("miss", "misses"),
                ("insert", "inserted"),
                ("evict", "evictions"),
            ):
                try:
                    count = float(counters.get(key) or 0.0)
                except (TypeError, ValueError):
                    continue
                if count > 0:
                    self.serving_prefix_cache_events.labels(event).inc(count)
        by_tier = block.get("migration_by_tier")
        if isinstance(by_tier, dict):
            for tier, row in by_tier.items():
                if not isinstance(tier, str) or not isinstance(row, dict):
                    continue
                try:
                    n_bytes = float(row.get("bytes") or 0.0)
                except (TypeError, ValueError):
                    continue
                if n_bytes > 0:
                    self.serving_kv_migration_bytes.labels(tier).inc(n_bytes)
        acceptance = block.get("spec_acceptance")
        if isinstance(acceptance, (int, float)):
            self.serving_spec_accept_fraction.set(float(acceptance))
        for pool, key in (
            ("prefill", "disagg_ttft_p99_ms"),
            ("colocated", "colocated_ttft_p99_ms"),
        ):
            try:
                ttft_ms = float(block.get(key))
            except (TypeError, ValueError):
                continue
            self.serving_pool_ttft_seconds.labels(pool, "p99").set(
                ttft_ms / 1e3
            )

    def _record_custom_metric(self, hc_name: str, raw) -> int:
        """One contract entry -> one sample; returns 1 when recorded."""
        if not isinstance(raw, dict):
            return 0
        metric_name = raw.get("name") or ""
        try:
            metric_value = float(raw.get("value"))
        except (TypeError, ValueError):
            log.error("skipping custom metric with bad value: %r", raw)
            return 0
        if not metric_name:
            log.error("skipping invalid custom metric for %s: %r", hc_name, raw)
            return 0
        kind = str(raw.get("metrictype") or "gauge").lower()
        if kind not in _CUSTOM_METRIC_KINDS:
            log.warning(
                "skipping custom metric %r of %s: unknown metrictype %r "
                "(supported: %s)",
                metric_name,
                hc_name,
                raw.get("metrictype"),
                ", ".join(sorted(_CUSTOM_METRIC_KINDS)),
            )
            return 0
        if kind == "counter" and metric_value < 0:
            # the counter contract is a per-run increment; a negative
            # delta would make prometheus_client raise
            log.error(
                "skipping counter metric %r of %s: negative increment %r",
                metric_name,
                hc_name,
                metric_value,
            )
            return 0
        full_name = _prefix_dedupe(_sanitize(hc_name), _sanitize(metric_name))
        with self._custom_lock:
            origin = self._custom_origin.setdefault(
                (hc_name, full_name), metric_name
            )
            if origin != metric_name:
                # same check, different raw metric, same merged name:
                # recording would silently overwrite the other metric's
                # series — skip loudly instead (never-raise contract,
                # like the registration collision below)
                log.error(
                    "custom metric %r of %s merges to %s, already "
                    "taken by metric %r of the same check; skipping",
                    metric_name,
                    hc_name,
                    full_name,
                    origin,
                )
                return 0
            known = self._custom_metrics.get(full_name)
            if known is not None and known[0] != kind:
                log.error(
                    "custom metric %s of %s re-declared as %s (registered "
                    "as %s); skipping",
                    full_name,
                    hc_name,
                    kind,
                    known[0],
                )
                return 0
            if known is None:
                family = Counter if kind == "counter" else Gauge
                try:
                    collector = family(
                        full_name,
                        str(raw.get("help") or full_name),
                        [LABEL_HC],
                        registry=self.registry,
                    )
                except ValueError:
                    # name collides with an already-registered metric
                    # (e.g. a static vec) — skip, keep the never-raise
                    # contract
                    log.error(
                        "custom metric %s collides with an existing "
                        "registration; skipping",
                        full_name,
                    )
                    return 0
                known = self._custom_metrics[full_name] = (kind, collector)
        _, collector = known
        if kind == "counter":
            # the reported value is this run's delta (counters cannot be
            # set); the scraped series is the monotonic total
            collector.labels(hc_name).inc(metric_value)
        else:
            collector.labels(hc_name).set(metric_value)
        return 1

    def _record_phase_timings(self, hc_name: str, timings) -> None:
        """The contract's ``timings`` block -> phase histogram samples,
        exemplar-stamped with the cycle's trace id. Dropped entries are
        COUNTED (``healthcheck_phase_timings_skipped_total{reason}``),
        not just logged — contract drift between probe and controller
        versions must be visible on /metrics, not only in scrollback."""
        if timings is None:
            return
        if not isinstance(timings, dict):
            log.warning(
                "skipping timings block for %s: expected an object, got %r",
                hc_name,
                type(timings).__name__,
            )
            self.record_phase_timing_skipped("not_object")
            return
        exemplar = _exemplar()
        for phase, seconds in timings.items():
            try:
                seconds = float(seconds)
            except (TypeError, ValueError):
                log.warning(
                    "skipping phase timing %r of %s: bad value %r",
                    phase,
                    hc_name,
                    seconds,
                )
                self.record_phase_timing_skipped("bad_value")
                continue
            if not isinstance(phase, str) or not phase:
                log.warning("skipping unnamed phase timing of %s", hc_name)
                self.record_phase_timing_skipped("unnamed")
                continue
            self.phase_seconds.labels(hc_name, _sanitize(phase)).observe(
                max(0.0, seconds), exemplar=exemplar
            )

    # -- exposition ----------------------------------------------------
    OPENMETRICS_CONTENT_TYPE = (
        "application/openmetrics-text; version=1.0.0; charset=utf-8"
    )

    def exposition(self, openmetrics: bool = False) -> bytes:
        """Scrape text. The default (Prometheus text format) is the
        reference's exact contract; OpenMetrics is the format that
        carries the trace-id exemplars on the latency histograms —
        served when the scraper asks for it (Accept negotiation in the
        manager's /metrics handler)."""
        if openmetrics:
            from prometheus_client.openmetrics.exposition import (
                generate_latest as om_generate_latest,
            )

            return om_generate_latest(self.registry)
        from prometheus_client import generate_latest

        return generate_latest(self.registry)

    def sample_value(self, name: str, labels: dict) -> Optional[float]:
        """Test helper: read a sample from the registry."""
        return self.registry.get_sample_value(name, labels)
