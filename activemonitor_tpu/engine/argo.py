"""Argo workflow engine — real Workflow CRs via the Kubernetes API.

Capability-parity backend for cluster deployments
(reference: healthcheck_controller.go:502-534 create, :617 dynamic-client
poll), on the framework's own REST layer — the Argo controller is an
external process; this engine only creates Workflow objects and polls
``status.phase``, exactly the process boundary the reference keeps.
"""

from __future__ import annotations

from typing import Optional

from activemonitor_tpu.kube import ApiError, KubeApi, api_path

WF_GROUP = "argoproj.io"
WF_VERSION = "v1alpha1"
WF_PLURAL = "workflows"


class ArgoWorkflowEngine:
    def __init__(self, api: Optional[KubeApi] = None):
        self._api = api if api is not None else KubeApi.from_default_config()

    async def submit(self, manifest: dict) -> str:
        namespace = manifest.get("metadata", {}).get("namespace", "default")
        created = await self._api.create(
            api_path(WF_GROUP, WF_VERSION, WF_PLURAL, namespace), manifest
        )
        return created["metadata"]["name"]

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        try:
            return await self._api.get(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, namespace, name)
            )
        except ApiError as e:
            if e.not_found:
                return None
            raise
