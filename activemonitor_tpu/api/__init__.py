"""API types for the HealthCheck resource (group activemonitor.keikoproj.io/v1alpha1)."""

from activemonitor_tpu.api.types import (
    ArtifactLocation,
    FileArtifact,
    HealthCheck,
    HealthCheckList,
    HealthCheckSpec,
    HealthCheckStatus,
    ObjectMeta,
    OwnerReference,
    PolicyRule,
    RemedyWorkflow,
    ResourceObject,
    ScheduleSpec,
    SLOSpec,
    URLArtifact,
    Workflow,
)

__all__ = [
    "ArtifactLocation",
    "FileArtifact",
    "HealthCheck",
    "HealthCheckList",
    "HealthCheckSpec",
    "HealthCheckStatus",
    "ObjectMeta",
    "OwnerReference",
    "PolicyRule",
    "RemedyWorkflow",
    "ResourceObject",
    "ScheduleSpec",
    "SLOSpec",
    "URLArtifact",
    "Workflow",
]
