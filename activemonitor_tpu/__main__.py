"""Controller CLI — ``python -m activemonitor_tpu <command>``.

``run`` mirrors the reference's process flags (reference:
cmd/main.go:138-144 — metrics-bind-address :8443,
health-probe-bind-address :8081, leader-elect off, max-workers 10) and
adds the engine/store selection this framework's local mode needs.
``apply``/``get``/``delete`` give the kubectl-equivalent UX against the
file-backed store; ``crd`` prints the generated CRD manifest.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="activemonitor_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run the controller")
    run.add_argument(
        "--metrics-bind-address",
        default=":8443",
        help="metrics endpoint address ('0' to disable)",
    )
    run.add_argument(
        "--metrics-secure",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="serve metrics over TLS (self-signed unless cert/key given; "
        "reference parity: secure by default on :8443)",
    )
    run.add_argument(
        "--metrics-cert-file",
        default="",
        help="PEM certificate for the metrics endpoint",
    )
    run.add_argument(
        "--metrics-key-file",
        default="",
        help="PEM private key for the metrics endpoint",
    )
    run.add_argument(
        "--metrics-auth-token-file",
        default="",
        help="file holding a static bearer token required to scrape "
        "/metrics (fallback credential; see --metrics-k8s-auth)",
    )
    run.add_argument(
        "--metrics-k8s-auth",
        choices=("auto", "on", "off"),
        default="auto",
        help="authenticate+authorize /metrics scrapes through the "
        "cluster (TokenReview + SubjectAccessReview, the reference's "
        "WithAuthenticationAndAuthorization filter, cmd/main.go:74-81). "
        "'auto' enables it whenever cluster credentials are in use "
        "(--client k8s / --engine argo); a static token file, if also "
        "given, stays honored as a fallback credential",
    )
    run.add_argument(
        "--health-probe-bind-address",
        default=":8081",
        help="health/readiness probe address ('0' to disable)",
    )
    run.add_argument(
        "--leader-elect",
        action="store_true",
        help="enable leader election for multi-replica HA "
        "(active/standby; superseded by --shards > 1, where per-shard "
        "Leases ARE the election)",
    )
    run.add_argument(
        "--shards",
        type=int,
        default=1,
        help="shard the reconcile fleet across N controller replicas: "
        "checks are consistent-hash-assigned to shards, each shard is "
        "owned via its own coordination.k8s.io Lease, and a dead "
        "shard's checks are adopted by the survivors without dropping "
        "or double-firing a scheduled run (docs/operations.md "
        "\"Sharded controller fleet\"). Needs --client k8s. 1 disables "
        "sharding",
    )
    run.add_argument(
        "--shard-id",
        type=int,
        default=0,
        help="this replica's home shard in [0, --shards): acquired "
        "eagerly (a fast restart reclaims it within the standby grace; "
        "after a longer outage an adopting peer hands it back once this "
        "replica is live again); every other shard is stood by for and "
        "adopted if its owner dies",
    )
    run.add_argument(
        "--max-workers",
        type=int,
        default=10,
        help="maximum concurrent reconciles",
    )
    run.add_argument(
        "--remedy-rate",
        type=float,
        default=0.0,
        metavar="PER_MINUTE",
        help="fleet-wide remedy rate cap in remedy runs per minute "
        "(token bucket; layered on top of each check's "
        "remedyRunsLimit/remedyResetInterval so one bad rollout can't "
        "launch hundreds of self-healing workflows at once). With "
        "--shards N the cap is apportioned by ownership: each "
        "replica's bucket refills at rate x owned-shards/N — "
        "re-applied on every handoff — so the fleet total stays at "
        "the configured value even when survivors carry adopted "
        "shards. 0 disables the cap. Suppressed runs are evented and "
        "counted in healthcheck_remedy_runs_total{result=\"suppressed\"}",
    )
    run.add_argument(
        "--engine",
        choices=["local", "argo"],
        default="local",
        help="workflow execution backend",
    )
    run.add_argument(
        "--client",
        choices=["file", "k8s"],
        default=None,
        help="HealthCheck store: file directory or the Kubernetes API "
        "(default: k8s when --engine=argo, else file)",
    )
    run.add_argument(
        "--store",
        default="./healthchecks",
        help="directory of HealthCheck YAML specs (file-backed store)",
    )
    run.add_argument(
        "--kubeconfig",
        default=None,
        help="kubeconfig path for cluster mode (default: $KUBECONFIG, "
        "then in-cluster credentials, then ~/.kube/config)",
    )
    run.add_argument(
        "-f",
        "--filename",
        action="append",
        default=[],
        help="HealthCheck manifest(s) to apply at startup",
    )
    run.add_argument("--log-level", default="INFO")
    run.add_argument(
        "--log-format",
        choices=("text", "json"),
        default="text",
        help="console text or structured JSON lines "
        "(reference parity: zap --zap-encoder, cmd/main.go:146-152)",
    )
    run.add_argument(
        "--trace-export",
        default="",
        metavar="PATH",
        help="on shutdown, dump the retained reconcile-cycle traces as "
        "JSON lines (one trace per line) to PATH; live traces are "
        "always available at /debug/traces on the health endpoint",
    )
    run.add_argument(
        "--flight-dir",
        default="",
        metavar="DIR",
        help="persist degradation flight-recorder bundles (confirmed "
        "ok->degraded transitions, breaker trips, quarantines, shard "
        "handoffs — each with its correlated spans/history/baseline "
        "evidence) as JSONL under DIR; live bundles are always served "
        "at /debug/flightrec on the health endpoint "
        "(docs/operations.md \"Reading a flight recording\")",
    )
    run.add_argument(
        "--frontdoor",
        action="store_true",
        help="serve the probe-as-a-service front door on the health "
        "endpoint (POST /frontdoor/submit): tenants submit one-shot "
        "check requests or probe DAGs at high QPS without touching "
        "the apiserver — per-tenant quota admission, request "
        "coalescing against the result rings (N identical questions "
        "share one probe run), degraded-mode parking "
        "(docs/operations.md \"Probe-as-a-service front door\")",
    )
    run.add_argument(
        "--frontdoor-quota",
        type=float,
        default=600.0,
        metavar="PER_MINUTE",
        help="default per-tenant admission quota in requests/minute "
        "(token bucket per tenant, lazily created — an open fleet "
        "where every tenant gets this budget; refusals are structured "
        "and counted in healthcheck_frontdoor_refusals_total)",
    )
    run.add_argument(
        "--frontdoor-freshness",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="default freshness window: a cached result younger than "
        "this satisfies a front-door request without a new probe run "
        "(requests may narrow it per call; the coalescing-vs-staleness "
        "tradeoff is documented in docs/operations.md)",
    )
    run.add_argument(
        "--matrix-state",
        default="",
        metavar="PATH",
        help="serve the scenario matrix's latest round from this "
        "durable sidecar (bench.py's BENCH_BASELINES.json) in the "
        "/statusz fleet block and `am-tpu matrix`; a corrupt or "
        "version-skewed sidecar reports a structured warning instead "
        "of failing the payload (docs/observability.md \"Reading the "
        "matrix\")",
    )
    run.add_argument(
        "--journal-dir",
        default="",
        metavar="DIR",
        help="durable telemetry journal (docs/observability.md "
        "\"Durable telemetry journal\"): append check results, "
        "attribution verdicts, and front-door arrival events as "
        "segmented JSONL under DIR, and replay the tail at boot so "
        "SLO windows, error-budget burn, and goodput attribution "
        "survive restarts; the arrival stream doubles as the workload "
        "trace `am-tpu replay` and the frontdoor-replay matrix cell "
        "consume",
    )
    run.add_argument(
        "--journal-max-bytes",
        type=int,
        default=0,
        metavar="BYTES",
        help="journal segment size cap before rotation (0: the "
        "journal's default, 1 MiB); compaction drops the oldest "
        "segments beyond the retained-segment cap so the directory "
        "stays bounded",
    )
    run.add_argument(
        "--profile-on-anomaly",
        default="",
        metavar="DIR",
        help="capture ONE bounded jax.profiler trace of the next probe "
        "run after a confirmed degradation or an SLO burn-rate breach, "
        "writing the capture under DIR (per-check cooldown, directory "
        "size-capped, off by default — docs/observability.md "
        "\"Profile-on-anomaly\")",
    )
    run.add_argument(
        "--profile-cooldown",
        type=float,
        default=600.0,
        metavar="SECONDS",
        help="minimum seconds between profile-on-anomaly captures for "
        "the SAME check (default 600); the captured run re-confirms "
        "its own anomaly, so the cooldown is what stops a degraded "
        "check from profiling every cycle",
    )
    run.add_argument(
        "--profile-max-bytes",
        type=int,
        default=0,
        metavar="BYTES",
        help="total size cap on the profile-on-anomaly directory "
        "(0: the default, 256 MiB); oldest captures are pruned first, "
        "the newest always survives",
    )
    run.add_argument(
        "--federation-config",
        default="",
        metavar="PATH",
        help="YAML federation document (liveness_seconds + clusters "
        "with name/url/device_kind/chips/topology/slices/dcn_gbps, see "
        "examples/federation/): this controller polls every listed "
        "cluster's /statusz, judges liveness by payload MOVEMENT, "
        "routes capability-constrained checks, and serves the "
        "federation block on its own /statusz (docs/operations.md "
        "\"Federating clusters\")",
    )

    def add_client_flags(p) -> None:
        """kubectl-verb parity: every CLI verb can target the file store
        (local mode) or the cluster (--client k8s)."""
        p.add_argument("--store", default="./healthchecks")
        p.add_argument("--client", choices=["file", "k8s"], default="file")
        p.add_argument("--kubeconfig", default=None)

    for name, help_text in [
        ("apply", "apply a HealthCheck manifest to the store"),
        ("delete", "delete a HealthCheck from the store"),
    ]:
        p = sub.add_parser(name, help=help_text)
        add_client_flags(p)
        if name == "apply":
            p.add_argument("-f", "--filename", required=True)
        else:
            p.add_argument("name")
            p.add_argument("--namespace", "-n", default="default")

    get = sub.add_parser("get", help="list HealthChecks (kubectl get hc)")
    get.add_argument("resource", nargs="?", default="hc", choices=["hc", "hcs", "healthchecks", "healthcheck"])
    get.add_argument("name", nargs="?", default=None)
    add_client_flags(get)
    get.add_argument("--namespace", "-n", default=None)
    get.add_argument(
        "-o", "--output", choices=["table", "yaml", "json"], default="table"
    )
    get.add_argument(
        "--watch",
        "-w",
        action="store_true",
        help="keep printing the table as it changes",
    )

    describe = sub.add_parser(
        "describe", help="spec + status + recent events for one HealthCheck"
    )
    describe.add_argument("name")
    add_client_flags(describe)
    describe.add_argument("--namespace", "-n", default="default")

    def add_statusz_flags(p) -> None:
        """The /statusz fetch knobs every fleet-introspection verb
        shares (status/why/goodput): repeatable --url for sharded
        fleets, bearer token for merged auth-filtered sites. ONE
        definition so a future knob cannot silently skip a verb."""
        p.add_argument(
            "--url",
            action="append",
            default=None,
            help="the controller's /statusz endpoint (default "
            "http://127.0.0.1:8081/statusz — the health-probe address; "
            "point at the metrics address when the sites are merged). "
            "Repeat once per replica of a SHARDED fleet: the payloads "
            "are rolled up into one fleet view (checks deduped, "
            "per-shard ownership counts summed)",
        )
        p.add_argument(
            "--token",
            default="",
            help="bearer token, needed only against a merged site whose "
            "/metrics is auth-filtered",
        )

    status = sub.add_parser(
        "status",
        help="fleet SLO summary from a running controller's /statusz",
    )
    add_statusz_flags(status)
    status.add_argument(
        "--federation",
        action="store_true",
        help="treat each --url as a CLUSTER (not a replica of one "
        "sharded fleet) and merge at the federation level: per-cluster "
        "rows, run-weighted global goodput, old-binary clusters folded "
        "into the unknown attribution bucket "
        "(docs/operations.md \"Federating clusters\")",
    )
    status.add_argument(
        "-o", "--output", choices=["table", "json"], default="table"
    )

    clusters = sub.add_parser(
        "clusters",
        help="the federation registry from a running federating "
        "controller's /statusz: one row per member cluster with "
        "health state, capability card, and movement age "
        "(docs/operations.md \"Federating clusters\")",
    )
    add_statusz_flags(clusters)
    clusters.add_argument(
        "-o", "--output", choices=["table", "json"], default="table"
    )

    why = sub.add_parser(
        "why",
        help="explain what is costing ONE check goodput: its lost-"
        "goodput attribution, the evidence line, and trace deep links",
    )
    why.add_argument("name", help="HealthCheck name")
    why.add_argument(
        "--namespace",
        "-n",
        default=None,
        help="namespace filter (default: every namespace with that name)",
    )
    add_statusz_flags(why)
    why.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    waterfall = sub.add_parser(
        "waterfall",
        help="ONE check's critical-path latency decomposition: per-"
        "stage p50/p95/p99 over the SLO window (queue-wait/admission/"
        "schedule/submit/poll/probe-phase/status-write, gaps booked as "
        "untracked) plus an ASCII waterfall of the last run "
        "(docs/observability.md \"Reading a waterfall\")",
    )
    waterfall.add_argument("name", help="HealthCheck name")
    waterfall.add_argument(
        "--namespace",
        "-n",
        default=None,
        help="namespace filter (default: every namespace with that name)",
    )
    add_statusz_flags(waterfall)
    waterfall.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    roofline = sub.add_parser(
        "roofline",
        help="cost-model evidence under ONE check's fractions: per "
        "metric, the arithmetic intensity, compute/memory/comm bound, "
        "ceiling, achieved rate and fraction-of-roofline "
        "(docs/observability.md \"Reading a roofline\")",
    )
    roofline.add_argument("name", help="HealthCheck name")
    roofline.add_argument(
        "--namespace",
        "-n",
        default=None,
        help="namespace filter (default: every namespace with that name)",
    )
    add_statusz_flags(roofline)
    roofline.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    matrix = sub.add_parser(
        "matrix",
        help="the scenario matrix's latest round: one row per cell "
        "(op x mesh x dtype x schedule) with VERDICT/CEILING/"
        "VS-BASELINE, structured skip reasons, and any confirmed "
        "regressions with their bisect outcomes "
        "(docs/observability.md \"Reading the matrix\")",
    )
    add_statusz_flags(matrix)
    matrix.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    goodput = sub.add_parser(
        "goodput",
        help="fleet lost-goodput attribution: which subsystem (ici/hbm/"
        "compile/scheduling/control_plane/unknown) is costing goodput "
        "right now, and the top offending checks",
    )
    add_statusz_flags(goodput)
    goodput.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    journal = sub.add_parser(
        "journal",
        help="durable telemetry journal: segment table, per-stream "
        "event counts, replay coverage of the recorded workload trace "
        "(docs/observability.md \"Durable telemetry journal\")",
    )
    journal.add_argument(
        "--journal-dir",
        default="",
        metavar="DIR",
        help="inspect a journal directory on disk instead of a running "
        "controller's /statusz journal block (the on-disk view adds "
        "the replay-coverage line — coverage needs the recorded "
        "events, not just the counters)",
    )
    add_statusz_flags(journal)
    journal.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    record = sub.add_parser(
        "record",
        help="record a seeded front-door traffic trace into a journal "
        "directory: drives open-loop Poisson check requests through a "
        "real front door on a fake clock, journaling every arrival "
        "(docs/operations.md \"Recording and replaying a traffic "
        "trace\")",
    )
    record.add_argument(
        "--journal-dir", required=True, metavar="DIR",
        help="journal directory the arrival trace is appended to",
    )
    record.add_argument(
        "--requests", type=int, default=64,
        help="number of requests to drive (default 64)",
    )
    record.add_argument(
        "--rate", type=float, default=200.0,
        help="offered load in requests/second (default 200)",
    )
    record.add_argument(
        "--seed", type=int, default=17,
        help="rng seed — same seed, same byte-identical schedule",
    )
    record.add_argument(
        "--check", action="append", default=None, metavar="NS/NAME",
        help="check identity in the offered set (repeatable; default "
        "bench/hc-a bench/hc-b bench/hc-c). A SMALL set is the point: "
        "duplicates exercise the coalescing cache",
    )
    record.add_argument(
        "--tenant", action="append", default=None,
        help="tenant in the round-robin mix (repeatable; default "
        "tenant-a tenant-b)",
    )
    record.add_argument(
        "--freshness", type=float, default=30.0,
        help="cache-freshness window in seconds (default 30; pass the "
        "same value to `replay` to reproduce the outcome sequence)",
    )
    record.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    replay = sub.add_parser(
        "replay",
        help="replay a recorded traffic trace through a fresh front "
        "door on a fake clock: same tenant mix, same arrival order, "
        "deterministic outcomes (docs/operations.md \"Recording and "
        "replaying a traffic trace\")",
    )
    replay.add_argument(
        "--journal-dir", required=True, metavar="DIR",
        help="journal directory holding the recorded arrival stream",
    )
    replay.add_argument(
        "--freshness", type=float, default=30.0,
        help="cache-freshness window in seconds (default 30; match the "
        "recording's)",
    )
    replay.add_argument(
        "-o", "--output", choices=["text", "json"], default="text"
    )

    sub.add_parser("crd", help="print the HealthCheck CRD manifest")
    sub.add_parser("version", help="print version")
    return parser


async def _run(args) -> int:
    from activemonitor_tpu.utils.logfmt import configure_logging

    configure_logging(args.log_level, getattr(args, "log_format", "text"))
    client_kind = args.client or ("k8s" if args.engine == "argo" else "file")
    # one REST session shared by every cluster-facing component
    kube_api = None
    kube_cfg = None
    if client_kind == "k8s" or args.engine == "argo":
        from activemonitor_tpu.kube import KubeApi
        from activemonitor_tpu.kube.config import load_kube_config

        kube_cfg = load_kube_config(getattr(args, "kubeconfig", None))
        kube_api = KubeApi(kube_cfg)
    # the session must outlive everything built on it and close on EVERY
    # exit path, including construction failures — hence the try begins
    # immediately after the session exists
    try:
        return await _run_controller(args, client_kind, kube_api, kube_cfg)
    finally:
        if kube_api is not None:
            await kube_api.close()


async def _run_controller(args, client_kind, kube_api, kube_cfg) -> int:
    from activemonitor_tpu.api.types import HealthCheck
    from activemonitor_tpu.controller.leader import AlwaysLeader, FileLeaderElector
    from activemonitor_tpu.controller.manager import Manager
    from activemonitor_tpu.controller.rbac import InMemoryRBACBackend, RBACProvisioner
    from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
    from activemonitor_tpu.metrics.collector import MetricsCollector

    from activemonitor_tpu.errors import ConfigurationError as _ConfigError

    metrics = MetricsCollector()
    shards = getattr(args, "shards", 1)
    shard_id = getattr(args, "shard_id", 0)
    coordinator = None
    if shards < 1:
        # a typo'd 0/negative must not silently run UNSHARDED with no
        # election — four such replicas would all reconcile everything
        raise _ConfigError(
            f"--shards must be >= 1 (got {shards}); 1 disables sharding"
        )
    if not (0 <= shard_id < shards):
        raise _ConfigError(
            f"--shard-id {shard_id} outside [0, {shards}) (--shards)"
        )
    if shards > 1:
        if client_kind != "k8s":
            raise _ConfigError(
                "--shards needs the Kubernetes store (--client k8s): "
                "shard ownership lives in coordination.k8s.io Leases"
            )
        from activemonitor_tpu.controller.sharding import ShardCoordinator

        coordinator = ShardCoordinator(
            api=kube_api,
            namespace=kube_cfg.namespace or "default",
            shards=shards,
            shard_id=shard_id,
            metrics=metrics,
        )

    if client_kind == "k8s":
        from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
        from activemonitor_tpu.controller.events import KubernetesEventRecorder

        client = KubernetesHealthCheckClient(
            kube_api,
            # shard-aware list/watch filtering: this replica parses and
            # reconciles only the shards it owns (live predicate)
            owns=coordinator.owns_event if coordinator is not None else None,
        )
        recorder = KubernetesEventRecorder(kube_api)
    else:
        from activemonitor_tpu.controller.client_file import FileHealthCheckClient
        from activemonitor_tpu.controller.events import FileEventRecorder

        client = FileHealthCheckClient(args.store)
        recorder = FileEventRecorder(args.store)
    if kube_api is not None:
        # whenever a cluster is in play (k8s store OR argo engine), the
        # per-check RBAC that submitted workflows reference must be real
        # cluster state (reference: healthcheck_controller.go:302-415,
        # 1128-1443) — an in-memory SA would leave probe pods Forbidden
        from activemonitor_tpu.controller.rbac import KubernetesRBACBackend

        rbac_backend = KubernetesRBACBackend(kube_api)
    else:
        rbac_backend = InMemoryRBACBackend()
    if args.engine == "argo":
        from activemonitor_tpu.engine.argo import ArgoWorkflowEngine

        engine = ArgoWorkflowEngine(
            kube_api,
            on_watch_health=metrics.record_watch_health,
            on_watch_restart=metrics.record_watch_restart,
        )
    else:
        from activemonitor_tpu.engine.local import LocalProcessEngine

        engine = LocalProcessEngine()

    if args.leader_elect and coordinator is None:
        if client_kind == "k8s":
            from activemonitor_tpu.controller.leader import KubernetesLeaseElector

            # the Lease lives in the namespace the controller runs in
            # (in-cluster SA namespace / kubeconfig context namespace)
            elector = KubernetesLeaseElector(
                kube_api, namespace=kube_cfg.namespace or "default"
            )
        else:
            # flock is per-host: only meaningful for co-hosted replicas
            elector = FileLeaderElector()
    else:
        elector = AlwaysLeader()

    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(rbac_backend),
        recorder=recorder,
        metrics=metrics,
    )
    if kube_api is not None:
        # the shared circuit breaker observes every request crossing the
        # cluster transport and gates the mutating ones (leases exempt)
        # — the signal source for degraded mode (docs/resilience.md)
        kube_api.set_breaker(reconciler.resilience.breaker)
    matrix_state = getattr(args, "matrix_state", "")
    if matrix_state:
        # /statusz serves the scenario matrix's latest round from the
        # durable sidecar bench.py maintains (read-only: the controller
        # did not run the round, it reports the evidence)
        from activemonitor_tpu.analysis.matrix import SidecarView

        reconciler.fleet.matrix = SidecarView(matrix_state)
    frontdoor = None
    if getattr(args, "frontdoor", False):
        # probe-as-a-service ingestion (frontdoor/service.py): quota
        # admission rides the storm token bucket per tenant, routing
        # rides the SAME consistent-hash ring the sharded fleet uses,
        # and triggered runs ride the manager's enqueue path below
        from activemonitor_tpu.controller.sharding import ShardRouter
        from activemonitor_tpu.frontdoor import (
            AdmissionController,
            FrontDoor,
            TenantQuota,
        )

        quota = getattr(args, "frontdoor_quota", 600.0)
        if quota <= 0:
            raise _ConfigError(
                f"--frontdoor-quota must be > 0 (got {quota})"
            )
        frontdoor = FrontDoor(
            reconciler.fleet.history,
            AdmissionController(
                default_quota=TenantQuota(rate_per_minute=quota),
                router=ShardRouter(shards) if shards > 1 else None,
            ),
            metrics=metrics,
            resilience=reconciler.resilience,
            default_freshness=getattr(args, "frontdoor_freshness", 30.0),
        )
    journal_dir = getattr(args, "journal_dir", "")
    journal_max_bytes = getattr(args, "journal_max_bytes", 0) or 0
    if journal_max_bytes < 0:
        raise _ConfigError(
            f"--journal-max-bytes must be >= 0 (got {journal_max_bytes}); "
            "0 uses the journal's default segment cap"
        )
    if journal_max_bytes and not journal_dir:
        raise _ConfigError(
            "--journal-max-bytes needs --journal-dir (no journal to cap)"
        )
    profile_dir = getattr(args, "profile_on_anomaly", "")
    profile_cooldown = getattr(args, "profile_cooldown", 600.0)
    profile_max_bytes = getattr(args, "profile_max_bytes", 0) or 0
    if profile_cooldown < 0:
        raise _ConfigError(
            f"--profile-cooldown must be >= 0 (got {profile_cooldown})"
        )
    if profile_max_bytes < 0:
        raise _ConfigError(
            f"--profile-max-bytes must be >= 0 (got {profile_max_bytes}); "
            "0 uses the default directory cap"
        )
    if profile_max_bytes and not profile_dir:
        raise _ConfigError(
            "--profile-max-bytes needs --profile-on-anomaly "
            "(no capture directory to cap)"
        )
    federation = None
    federation_config = getattr(args, "federation_config", "")
    if federation_config:
        # the federation document is config, not a manifest: parse and
        # shape-check it HERE so a typo'd file is a usage error before
        # the Manager (and its bound sockets) exist
        import yaml as _yaml

        from activemonitor_tpu.federation import FederationPlane

        try:
            with open(federation_config) as f:
                fed_doc = _yaml.safe_load(f.read())
        except (OSError, _yaml.YAMLError) as e:
            raise _ConfigError(
                f"cannot read federation config {federation_config!r}: {e}"
            ) from e
        if not isinstance(fed_doc, dict):
            raise _ConfigError(
                f"federation config {federation_config!r} must be a "
                "mapping (liveness_seconds + clusters)"
            )
        entries = fed_doc.get("clusters") or []
        names = [str(entry.get("name") or "") for entry in entries]
        if not names:
            raise _ConfigError(
                f"federation config {federation_config!r} lists no "
                "clusters (nothing to federate)"
            )
        if "" in names or len(set(names)) != len(names):
            raise _ConfigError(
                f"federation config {federation_config!r}: every "
                "cluster needs a unique non-empty name"
            )
        liveness = float(fed_doc.get("liveness_seconds") or 90.0)
        if liveness <= 0:
            raise _ConfigError(
                f"federation config {federation_config!r}: "
                f"liveness_seconds must be > 0 (got {liveness:g})"
            )
        federation = FederationPlane.from_config(fed_doc, metrics=metrics)

    metrics_authorizer = None
    k8s_auth = getattr(args, "metrics_k8s_auth", "auto")
    if k8s_auth == "on" and kube_api is None:
        from activemonitor_tpu.errors import ConfigurationError

        raise ConfigurationError(
            "--metrics-k8s-auth on needs cluster credentials "
            "(--client k8s or --engine argo)"
        )
    if kube_api is not None and k8s_auth in ("auto", "on"):
        from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

        metrics_authorizer = KubeScrapeAuthorizer(kube_api)

    # Manager construction validates the flag combination BEFORE the -f
    # manifests are applied (no side effects on a usage error)
    manager = Manager(
        client=client,
        reconciler=reconciler,
        max_parallel=args.max_workers,
        metrics_bind_address=(
            "" if args.metrics_bind_address == "0" else args.metrics_bind_address
        ),
        health_probe_bind_address=(
            ""
            if args.health_probe_bind_address == "0"
            else args.health_probe_bind_address
        ),
        leader_elector=elector,
        metrics_secure=args.metrics_secure,
        metrics_cert_file=args.metrics_cert_file,
        metrics_key_file=args.metrics_key_file,
        metrics_auth_token_file=args.metrics_auth_token_file,
        metrics_authorizer=metrics_authorizer,
        # the FLEET rate: the manager apportions it by owned shards
        # (rate × owned/N, re-applied on handoff) so the per-replica
        # buckets sum to the configured cap even when survivors carry
        # adopted shards — a static rate/replica split would silently
        # multiply the budget, a static rate/N split would shrink it
        remedy_rate=args.remedy_rate,
        shard_coordinator=coordinator,
        flight_dir=getattr(args, "flight_dir", ""),
        frontdoor=frontdoor,
        journal_dir=journal_dir,
        journal_max_bytes=journal_max_bytes,
        profile_on_anomaly_dir=profile_dir,
        profile_cooldown=profile_cooldown,
        profile_max_bytes=profile_max_bytes,
        federation=federation,
    )
    for path in args.filename:
        await client.apply(_load_manifest(HealthCheck, path))

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    # start as a task: a standby replica blocks inside the election until
    # it wins, and SIGTERM must still shut it down gracefully meanwhile
    start_task = asyncio.create_task(manager.start())
    stop_wait = asyncio.ensure_future(stop.wait())
    lost_leadership = False
    try:
        await asyncio.wait(
            {start_task, stop_wait}, return_when=asyncio.FIRST_COMPLETED
        )
        if not start_task.done():
            # signalled while standing by for leadership
            start_task.cancel()
            await asyncio.gather(start_task, return_exceptions=True)
            return 0
        start_task.result()  # propagate startup failures
        logging.getLogger("activemonitor").info(
            "controller running: store=%s engine=%s workers=%d",
            args.store,
            args.engine,
            args.max_workers,
        )
        # stop on signal OR on the manager stopping itself (leadership lost)
        stopping_wait = asyncio.ensure_future(manager.stopping.wait())
        await asyncio.wait(
            {stop_wait, stopping_wait}, return_when=asyncio.FIRST_COMPLETED
        )
        stopping_wait.cancel()
        # a self-initiated stop without a signal means leadership was
        # lost: exit non-zero so the orchestrator restarts this replica
        # into the candidate pool (controller-runtime exits fatally too)
        lost_leadership = manager.stopping.is_set() and not stop.is_set()
    finally:
        # teardown runs on every path, including startup failures —
        # otherwise bound sockets stay held
        stop_wait.cancel()
        await manager.stop()
        closer = getattr(engine, "close", None)
        if closer is not None:
            await closer()  # stop workflow watch streams
        trace_path = getattr(args, "trace_export", "")
        if trace_path:
            # after manager.stop(): the reconciler has shut down, so
            # every in-flight cycle's spans have landed in the ring
            try:
                count = reconciler.tracer.export_jsonl(trace_path)
                logging.getLogger("activemonitor").info(
                    "exported %d trace(s) to %s", count, trace_path
                )
            except OSError as e:
                # best-effort on the way out: a bad path must not turn
                # a clean shutdown into a crash
                logging.getLogger("activemonitor").error(
                    "trace export to %s failed: %s", trace_path, e
                )
    return 1 if lost_leadership else 0


def _load_manifest(model, path: str):
    """Parse a user-supplied manifest, converting parse/validation
    failures into usage errors — ONLY at this boundary, so internal
    ValidationErrors elsewhere keep their tracebacks."""
    import yaml as _yaml

    from pydantic import ValidationError

    from activemonitor_tpu.errors import ConfigurationError

    try:
        with open(path) as f:
            return model.from_yaml(f.read())
    except (ValidationError, _yaml.YAMLError) as e:
        raise ConfigurationError(f"invalid manifest {path!r}: {e}") from e
    except OSError as e:
        raise ConfigurationError(f"cannot read manifest {path!r}: {e}") from e


def _cli_client(args):
    """(client, kube_api-or-None) for a CLI verb, honoring --client."""
    if getattr(args, "client", "file") == "k8s":
        from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
        from activemonitor_tpu.kube import KubeApi
        from activemonitor_tpu.kube.config import load_kube_config

        api = KubeApi(load_kube_config(getattr(args, "kubeconfig", None)))
        return KubernetesHealthCheckClient(api), api
    from activemonitor_tpu.controller.client_file import FileHealthCheckClient

    return FileHealthCheckClient(args.store), None


async def _apply(args) -> int:
    from activemonitor_tpu.api.types import HealthCheck

    hc = _load_manifest(HealthCheck, args.filename)
    client, kube_api = _cli_client(args)
    try:
        hc = await client.apply(hc)
    finally:
        if kube_api is not None:
            await kube_api.close()
    print(f"healthcheck.{hc.api_version.split('/')[0]}/{hc.metadata.name} applied")
    return 0


async def _delete(args) -> int:
    from activemonitor_tpu.controller.client import NotFoundError

    client, kube_api = _cli_client(args)
    try:
        await client.delete(args.namespace, args.name)
    except NotFoundError:
        print(f"healthcheck {args.namespace}/{args.name} not found", file=sys.stderr)
        return 1
    finally:
        if kube_api is not None:
            await kube_api.close()
    print(f"healthcheck {args.namespace}/{args.name} deleted")
    return 0


async def _get(args) -> int:
    if args.watch and args.output != "table":
        print("--watch only supports table output", file=sys.stderr)
        return 2
    client, kube_api = _cli_client(args)
    try:
        return await _get_inner(args, client)
    finally:
        if kube_api is not None:
            await kube_api.close()


async def _get_inner(args, client) -> int:
    import json as _json

    import yaml as _yaml

    # name lookups are namespace-scoped like kubectl (default ns when
    # -n is unset) so the output shape never depends on collisions
    namespace = args.namespace or ("default" if args.name else None)

    async def fetch():
        checks = await client.list(namespace)
        if args.name:
            checks = [hc for hc in checks if hc.metadata.name == args.name]
        return checks

    checks = await fetch()
    if args.name and not checks:
        print(f"healthcheck {args.name!r} not found", file=sys.stderr)
        return 1
    if args.output in ("yaml", "json"):
        docs = [hc.to_dict() for hc in checks]
        if args.output == "yaml":
            print(_yaml.safe_dump_all(docs, sort_keys=False), end="")
        else:
            # stable shape for scripts: a name lookup returns one object
            # (namespace-scoped, so exactly one), a listing an array
            payload = docs[0] if args.name else docs
            print(_json.dumps(payload, indent=2, default=str))
        return 0
    def print_table(checks) -> None:
        rows = [hc.printer_row() for hc in checks]
        if not rows:
            print("No resources found.")
            return
        headers = list(rows[0].keys())
        widths = [
            max(len(str(h)), *(len(str(r[h])) for r in rows)) for h in headers
        ]
        print("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
        for r in rows:
            print("  ".join(str(r[h]).ljust(w) for h, w in zip(headers, widths)))

    print_table(checks)
    if args.watch:
        last = [hc.to_dict() for hc in checks]

        async def refresh() -> None:
            nonlocal last
            current_checks = await fetch()
            current = [hc.to_dict() for hc in current_checks]
            if current != last:
                last = current
                print()
                print_table(current_checks)

        try:
            if getattr(args, "client", "file") == "k8s":
                # event-driven but rate-limited: events only mark dirty;
                # one LIST refresh at most per second coalesces bursts
                # (the initial synthetic-ADDED replay, reconcile churn)
                dirty = asyncio.Event()

                async def mark_dirty() -> None:
                    async for _event in client.watch():
                        dirty.set()

                marker = asyncio.create_task(mark_dirty())
                try:
                    while True:
                        await dirty.wait()
                        dirty.clear()
                        try:
                            await refresh()
                        except Exception as e:
                            # transient LIST failure must not kill a
                            # long-running watch (the stream reconnects;
                            # so do we, on the next event)
                            print(f"refresh failed ({e}); retrying", file=sys.stderr)
                        await asyncio.sleep(1.0)
                finally:
                    marker.cancel()
                    await asyncio.gather(marker, return_exceptions=True)
            else:
                # the file store is written by other processes — no
                # cross-process change feed, so poll
                while True:
                    await asyncio.sleep(1.0)
                    await refresh()
        except (KeyboardInterrupt, asyncio.CancelledError):
            return 0
    return 0


def _fmt_ratio(value) -> str:
    return "-" if value is None else f"{value * 100:.1f}%"


def _fmt_seconds(value) -> str:
    return "-" if value is None else f"{value:.2f}s"


def _why_cell(attribution) -> str:
    """The status table's WHY cell: `bucket:lost%` for a check losing
    goodput, "-" otherwise — one token, so the table stays greppable."""
    if not attribution or not attribution.get("top"):
        return "-"
    return "{}:{:.0f}%".format(
        attribution["top"], 100 * (attribution.get("lost_ratio") or 0)
    )


def _adapt_cell(adapt) -> str:
    """The status table's ADAPT cell: the levers currently holding the
    check, with the cadence factor inlined (`cadence:0.5+placement`) —
    one token, "-" while no lever touches it."""
    if not adapt or not adapt.get("levers"):
        return "-"
    parts = []
    for lever in adapt["levers"]:
        if lever == "cadence" and adapt.get("cadence_factor") is not None:
            parts.append("cadence:{:g}".format(adapt["cadence_factor"]))
        else:
            parts.append(lever)
    return "+".join(parts)


def render_status_table(payload: dict) -> str:
    """The /statusz payload as the `am-tpu status` table. Pure so tests
    pin the rendering against a canned payload."""
    fleet = payload.get("fleet") or {}
    fleet_line = "FLEET  checks={}  window_runs={}  goodput={}".format(
        fleet.get("checks", 0),
        fleet.get("window_runs", 0),
        _fmt_ratio(fleet.get("goodput_ratio")),
    )
    if fleet.get("degraded"):
        breaker = fleet.get("breaker") or {}
        fleet_line += "  DEGRADED(breaker={}, queued_writes={})".format(
            breaker.get("state", "open"),
            fleet.get("status_writes_queued", 0),
        )
    if fleet.get("remedy_tokens") is not None:
        fleet_line += f"  remedy_tokens={fleet['remedy_tokens']:.1f}"
    if fleet.get("replicas") is not None:
        fleet_line += f"  replicas={fleet['replicas']}"
    if fleet.get("clusters") is not None:
        fleet_line += f"  clusters={fleet['clusters']}"
    lines = [fleet_line]
    per_cluster = fleet.get("per_cluster")
    if per_cluster:
        # federation-level view (`status --federation`): one line per
        # member cluster; SKEWED marks an old-binary cluster whose
        # goodput evidence folded into the unknown bucket
        for name in sorted(per_cluster):
            row = per_cluster[name]
            line = (
                "CLUSTER {}  replicas={}  checks={}  window_runs={}  "
                "goodput={}".format(
                    name,
                    row.get("replicas", 0),
                    row.get("checks", 0),
                    row.get("window_runs", 0),
                    _fmt_ratio(row.get("goodput_ratio")),
                )
            )
            if row.get("degraded"):
                line += "  DEGRADED"
            if row.get("skewed"):
                line += "  SKEWED(old binary: goodput under unknown)"
            lines.append(line)
    federation = fleet.get("federation")
    if federation:
        # a federating controller's own /statusz: the registry headline
        # (`am-tpu clusters` has the full per-member table)
        registry = federation.get("registry") or {}
        line = "FEDERATION  clusters={}  healthy={}  unhealthy={}".format(
            len(registry.get("clusters") or {}),
            registry.get("healthy", 0),
            registry.get("unhealthy", 0),
        )
        door = federation.get("door")
        if door and not door.get("conservation_ok", True):
            line += "  CONSERVATION-BROKEN"
        lines.append(line)
    frontdoor = fleet.get("frontdoor")
    if frontdoor:
        # the probe-as-a-service ingestion line: offered load, how much
        # of it the coalescing cache absorbed, what the door is holding
        # open, and who is being refused (docs/operations.md
        # "Probe-as-a-service front door")
        coalescing = frontdoor.get("coalescing") or {}
        requests = frontdoor.get("requests") or {}
        line = (
            "FRONTDOOR  qps={:.1f}  hit={}  join={}  queue_depth={}  "
            "runs={}".format(
                frontdoor.get("qps") or 0.0,
                _fmt_ratio(coalescing.get("hit")),
                _fmt_ratio(coalescing.get("join")),
                frontdoor.get("queue_depth", 0),
                requests.get("probe_runs", 0),
            )
        )
        refusals = {
            tenant: row["refused"]
            for tenant, row in (frontdoor.get("tenants") or {}).items()
            if row.get("refused")
        }
        if refusals:
            line += "  refusals={" + ", ".join(
                f"{tenant}: {count}"
                for tenant, count in sorted(refusals.items())
            ) + "}"
        if not frontdoor.get("conservation_ok", True):
            line += "  CONSERVATION-BROKEN"
        lines.append(line)
    adaptive = fleet.get("adaptive")
    if adaptive and adaptive.get("engaged"):
        # the closed-loop control line: which levers hold how many
        # checks, and the front-door degraded posture while it lasts
        # (docs/resilience.md "Adaptive control loop")
        levers = adaptive.get("levers") or {}
        held = {k: v for k, v in sorted(levers.items()) if v}
        line = "ADAPTIVE  levers={" + ", ".join(
            f"{lever}: {count}" for lever, count in held.items()
        ) + "}"
        adaptive_frontdoor = adaptive.get("frontdoor") or {}
        if adaptive_frontdoor.get("engaged"):
            line += "  DEGRADED-FRONTDOOR(ceiling={:g}s, shed=x{:g})".format(
                adaptive_frontdoor.get("freshness_ceiling") or 0.0,
                adaptive_frontdoor.get("shed_factor") or 0.0,
            )
        lines.append(line)
    sharding = fleet.get("sharding")
    if sharding:
        from activemonitor_tpu.obs.slo import shard_sort_key

        def shard_order(keys):
            return sorted(keys, key=shard_sort_key)

        owned = sharding.get("owned")
        owners = sharding.get("owners")
        if owners:  # rolled-up fleet view: shard -> owning replica
            detail = "  ".join(
                f"{shard}:{owners[shard]}" for shard in shard_order(owners)
            )
        else:  # single replica's own block
            detail = "owned=" + ",".join(str(s) for s in owned or [])
        per_shard = sharding.get("checks_per_shard") or {}
        lines.append(
            "SHARDS {}  {}  checks_per_shard={}".format(
                sharding.get("shards", 0),
                detail,
                "{" + ", ".join(
                    f"{shard}: {per_shard[shard]}"
                    for shard in shard_order(per_shard)
                ) + "}",
            )
        )
    headers = [
        "NAME", "NAMESPACE", "STATUS", "STATE", "ANOMALY", "RUNS", "AVAIL",
        "P50", "P95", "P99", "BUDGET", "BURN", "REMEDY", "ADAPT", "WHY",
        "LAST TRACE",
    ]
    rows = []
    for check in payload.get("checks") or []:
        window = check.get("window") or {}
        slo = check.get("slo")
        analysis = check.get("analysis")
        attribution = check.get("attribution")
        remedy_budget = check.get("remedy_budget_remaining")
        rows.append(
            [
                check.get("healthcheck", ""),
                check.get("namespace", ""),
                check.get("last_status", "") or "-",
                check.get("state", "") or "healthy",
                # baseline-analysis verdict; "-" when the check declares
                # no analysis: block
                (analysis or {}).get("state") or "-",
                str(window.get("results", 0)),
                _fmt_ratio(window.get("availability")),
                _fmt_seconds(window.get("p50_seconds")),
                _fmt_seconds(window.get("p95_seconds")),
                _fmt_seconds(window.get("p99_seconds")),
                _fmt_ratio(slo.get("error_budget_remaining")) if slo else "-",
                (
                    f"{slo['burn_rate']:.2f}"
                    if slo and slo.get("burn_rate") is not None
                    else "-"
                ),
                "-" if remedy_budget is None else str(remedy_budget),
                # adaptive levers currently reshaping this check's
                # schedule ("-" while the loop leaves it alone)
                _adapt_cell(check.get("adapt")),
                # goodput attribution headline: the bucket costing this
                # check goodput right now ("-" while nothing is lost);
                # `am-tpu why <check>` has the full evidence
                _why_cell(attribution),
                (check.get("last_trace_id") or "-")[:16],
            ]
        )
    if not rows:
        lines.append("No HealthChecks found.")
        return "\n".join(lines)
    widths = [
        max(len(h), *(len(r[i]) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


async def _fetch_statusz_payloads(args):
    """Fetch /statusz from every --url (default: the local health
    endpoint) concurrently, returning the ordered ``(url, payload)``
    pairs that answered (warnings on stderr for the ones that did
    not). The merge policy — replica rollup vs federation merge — is
    the CALLER's: this helper only gathers the payloads."""
    import aiohttp

    urls = args.url or ["http://127.0.0.1:8081/statusz"]
    headers = {"Authorization": f"Bearer {args.token}"} if args.token else {}
    payloads = []
    failures = []
    # per-URL failures are warnings, not fatal, fetched concurrently
    # under a short timeout: the failover runbook has the operator
    # watching the rollup WHILE a replica is dead — all-or-nothing (or a
    # black-holed node serially eating aiohttp's 300s default) would
    # blind the CLI during the exact window it exists to observe.
    # Connect/read-gap timeouts, NOT total: a 50k-check /statusz body is
    # tens of MB and a total cap would misreport a healthy replica as
    # unreachable just for being slow to stream it
    timeout = aiohttp.ClientTimeout(connect=5, sock_connect=5, sock_read=15)
    async with aiohttp.ClientSession(timeout=timeout) as session:

        async def fetch(url):
            async with session.get(url, headers=headers) as resp:
                if resp.status != 200:
                    return url, None, f"{url} returned {resp.status}"
                return url, await resp.json(), None

        results = await asyncio.gather(
            *(fetch(url) for url in urls), return_exceptions=True
        )
    for url, result in zip(urls, results):
        if isinstance(result, BaseException):
            failures.append(f"cannot reach {url}: {result}")
        elif result[2] is not None:
            failures.append(result[2])
        else:
            payloads.append((url, result[1]))
    for failure in failures:
        print(f"warning: {failure}", file=sys.stderr)
    if not payloads:
        print(
            "error: no replica reachable (is the controller running with "
            "a health-probe address?)",
            file=sys.stderr,
        )
        return None
    if failures:
        print(
            f"warning: partial fleet view ({len(payloads)}/{len(urls)} "
            "replicas reporting)",
            file=sys.stderr,
        )
    return payloads


async def _fetch_fleet_payload(args):
    """Fetch /statusz from every --url and return ONE fleet payload —
    rolled up across replicas when more than one answered — or None
    when none did. Shared by the status/why/goodput verbs so they all
    see the same fleet view."""
    payloads = await _fetch_statusz_payloads(args)
    if payloads is None:
        return None
    if len(payloads) == 1:
        return payloads[0][1]
    # sharded fleet: merge the per-replica payloads into one view
    # (obs/slo.rollup_statusz — checks deduped by key, per-shard
    # ownership counts summed, goodput the run-weighted mean of
    # the replicas' own ratios, attribution merged run-weighted)
    from activemonitor_tpu.obs.slo import rollup_statusz

    return rollup_statusz([payload for _, payload in payloads])


def _cluster_name_for_url(url: str) -> str:
    """A stable cluster label for `status --federation`'s per-URL
    payloads: the URL's host:port (the part an operator recognizes),
    falling back to the raw URL."""
    from urllib.parse import urlsplit

    try:
        return urlsplit(url).netloc or url
    except ValueError:
        return url


async def _status(args) -> int:
    import json as _json

    if getattr(args, "federation", False):
        # each --url is a CLUSTER: merge at the federation level
        # (federation/rollup.federate_statusz — per-cluster rows kept,
        # goodput run-weighted, old binaries folded into unknown)
        pairs = await _fetch_statusz_payloads(args)
        if pairs is None:
            return 1
        from activemonitor_tpu.federation import federate_statusz

        payload = federate_statusz(
            {_cluster_name_for_url(url): body for url, body in pairs}
        )
    else:
        payload = await _fetch_fleet_payload(args)
        if payload is None:
            return 1
    if args.output == "json":
        print(_json.dumps(payload, indent=2))
        return 0
    print(render_status_table(payload))
    return 0


def render_clusters(federation: dict) -> str:
    """The `am-tpu clusters` table over a /statusz ``federation``
    block: one row per member cluster. Pure so tests pin the
    rendering against a canned block."""
    registry = (federation or {}).get("registry") or {}
    members = registry.get("clusters") or {}
    lines = [
        "FEDERATION  clusters={}  healthy={}  unhealthy={}  "
        "liveness={:g}s".format(
            len(members),
            registry.get("healthy", 0),
            registry.get("unhealthy", 0),
            registry.get("liveness_seconds") or 0.0,
        )
    ]
    door = (federation or {}).get("door")
    if door:
        requests = door.get("requests") or {}
        line = (
            "GLOBAL-DOOR  submitted={}  refused={}  forwarded={}".format(
                requests.get("submitted", 0),
                requests.get("refused", 0),
                requests.get("forwarded", 0),
            )
        )
        if not door.get("conservation_ok", True):
            line += "  CONSERVATION-BROKEN"
        lines.append(line)
    headers = [
        "NAME", "STATE", "GEN", "CHIPS", "TOPOLOGY", "DCN", "SLICES",
        "MOVED", "TRANSITIONS",
    ]
    rows = []
    for name in sorted(members):
        member = members[name]
        age = member.get("movement_age_seconds")
        rows.append(
            [
                name,
                member.get("state", ""),
                member.get("generation", "") or "-",
                str(member.get("chips", 0)),
                member.get("topology", "") or "-",
                "{:g}".format(member.get("dcn_gbps") or 0.0),
                ",".join(member.get("slices") or []) or "-",
                "-" if age is None else f"{age:.0f}s ago",
                str(member.get("transitions", 0)),
            ]
        )
    if not rows:
        lines.append("No clusters joined.")
        return "\n".join(lines)
    widths = [
        max(len(h), *(len(r[i]) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


async def _clusters(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    federation = (payload.get("fleet") or {}).get("federation")
    if not federation:
        print(
            "error: no federation block on /statusz (is the controller "
            "running with --federation-config?)",
            file=sys.stderr,
        )
        return 1
    if args.output == "json":
        print(_json.dumps(federation, indent=2))
        return 0
    print(render_clusters(federation))
    return 0


def render_goodput(payload: dict) -> str:
    """The `am-tpu goodput` report: the fleet's lost-goodput
    decomposition plus the top offending checks. Pure over a /statusz
    (or rollup) payload so tests pin the rendering."""
    fleet = payload.get("fleet") or {}
    block = fleet.get("goodput") or {}
    ratios = block.get("attribution") or {}
    lost_runs = block.get("lost_runs") or {}
    lines = [
        "FLEET  goodput={}  lost={}  window_runs={}  top={}".format(
            _fmt_ratio(fleet.get("goodput_ratio")),
            _fmt_ratio(block.get("lost_ratio") or 0.0),
            block.get("window_runs", fleet.get("window_runs", 0)),
            block.get("top") or "none",
        )
    ]
    lines.append("SUBSYSTEM        LOST    RUNS")
    for bucket in sorted(ratios, key=lambda b: -(ratios[b] or 0)):
        runs = lost_runs.get(bucket, 0)
        lines.append(
            "{:<13}  {:>6}  {:>6}".format(
                bucket,
                _fmt_ratio(ratios[bucket] or 0.0),
                f"{runs:.0f}" if isinstance(runs, float) else str(runs),
            )
        )
    offenders = []
    for check in payload.get("checks") or []:
        attribution = check.get("attribution")
        if attribution and attribution.get("lost_runs"):
            offenders.append((attribution["lost_runs"], check, attribution))
    offenders.sort(key=lambda item: -item[0])
    if offenders:
        lines.append("TOP OFFENDERS")
        for lost, check, attribution in offenders[:10]:
            lines.append(
                "  {}/{}  lost={}  {}  {}".format(
                    check.get("namespace", ""),
                    check.get("healthcheck", ""),
                    _fmt_ratio(attribution.get("lost_ratio")),
                    _why_cell(attribution),
                    (attribution.get("why") or "")[:60],
                ).rstrip()
            )
    return "\n".join(lines)


async def _goodput(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    if args.output == "json":
        fleet = payload.get("fleet") or {}
        print(_json.dumps(fleet.get("goodput") or {}, indent=2))
        return 0
    print(render_goodput(payload))
    return 0


def render_why(check: dict) -> str:
    """One check's `am-tpu why` explanation: verdict, attribution
    decomposition, the evidence line, and /debug deep links. Pure over
    a /statusz check entry so tests pin the rendering."""
    key = check.get("key") or "{}/{}".format(
        check.get("namespace", ""), check.get("healthcheck", "")
    )
    window = check.get("window") or {}
    analysis = check.get("analysis")
    attribution = check.get("attribution")
    lines = [
        "{}  state={}  anomaly={}  last={}".format(
            key,
            check.get("state") or "healthy",
            (analysis or {}).get("state") or "-",
            check.get("last_status") or "-",
        ),
        "  window: {} runs / {:.0f}s, availability {}".format(
            window.get("results", 0),
            window.get("seconds") or 0,
            _fmt_ratio(window.get("availability")),
        ),
    ]
    if not attribution or not attribution.get("lost_runs"):
        lines.append("  no goodput lost in the window")
    else:
        parts = [
            "{} {} ({} runs)".format(
                bucket, _fmt_ratio(ratio), attribution["counts"][bucket]
            )
            for bucket, ratio in sorted(
                (attribution.get("buckets") or {}).items(),
                key=lambda kv: -(kv[1] or 0),
            )
            if ratio
        ]
        lines.append(
            "  lost {} of goodput: {}".format(
                _fmt_ratio(attribution.get("lost_ratio")), ", ".join(parts)
            )
        )
    if attribution and attribution.get("why"):
        lines.append(f"  why: {attribution['why']}")
    adapt = check.get("adapt")
    if adapt:
        # the adaptation episode: which levers hold this check, why,
        # and since when — the operator's answer to "who changed my
        # probe cadence" (docs/resilience.md "Adaptive control loop")
        held = "+".join(adapt.get("levers") or [])
        line = f"  adaptation: {held}"
        if adapt.get("cadence_factor") is not None:
            line += "  interval x{:g}".format(adapt["cadence_factor"])
        if adapt.get("cause"):
            line += "  cause={}".format(adapt["cause"])
        if adapt.get("since"):
            line += "  since={}".format(adapt["since"])
        lines.append(line)
        if adapt.get("cohort"):
            lines.append(
                "    placement: cohort {} contended — probes parked at "
                "wider cadence".format(adapt["cohort"])
            )
        if adapt.get("remedy_bucket"):
            lines.append(
                "    remedy: byBucket[{}] targeted over the plain "
                "fallback".format(adapt["remedy_bucket"])
            )
    lost_tail = [
        entry
        for entry in check.get("history") or []
        if not entry.get("ok") or entry.get("bucket")
    ]
    if lost_tail:
        lines.append("  recent attributed runs:")
        for entry in lost_tail[-5:]:
            lines.append(
                "    {}  {}  {:<13} trace={}  {}".format(
                    entry.get("ts", ""),
                    "FAIL" if not entry.get("ok") else "ok  ",
                    entry.get("bucket") or "-",
                    (entry.get("trace_id") or "-")[:16],
                    (entry.get("why") or "")[:60],
                ).rstrip()
            )
        last = lost_tail[-1]
        if last.get("trace_id"):
            lines.append(
                "  deep link: /debug/traces?trace_id={}  (all cycles: "
                "/debug/traces?check={})".format(last["trace_id"], key)
            )
    return "\n".join(lines)


async def _why(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    matches = [
        check
        for check in payload.get("checks") or []
        if check.get("healthcheck") == args.name
        and (args.namespace is None or check.get("namespace") == args.namespace)
    ]
    if not matches:
        where = f" in namespace {args.namespace!r}" if args.namespace else ""
        print(
            f"healthcheck {args.name!r}{where} not found in the fleet view",
            file=sys.stderr,
        )
        return 1
    if args.output == "json":
        docs = [
            {
                "key": check.get("key"),
                "attribution": check.get("attribution"),
                "analysis": check.get("analysis"),
                "adapt": check.get("adapt"),
                "history": check.get("history"),
            }
            for check in matches
        ]
        print(_json.dumps(docs[0] if len(docs) == 1 else docs, indent=2))
        return 0
    print("\n".join(render_why(check) for check in matches))
    return 0


def _fmt_secs(value) -> str:
    """A stage duration cell: millisecond precision below a second,
    so a 3 ms schedule stage doesn't render as an all-zero 0.00s."""
    if not isinstance(value, (int, float)):
        return "-"
    if value < 1.0:
        return f"{value * 1e3:.1f}ms"
    return f"{value:.2f}s"


def render_waterfall(check: dict, width: int = 40) -> str:
    """One check's `am-tpu waterfall` report: the per-stage percentile
    table over the SLO window plus an ASCII waterfall of the last run's
    segments. Pure over a /statusz check entry so tests pin the
    rendering byte-for-byte."""
    from activemonitor_tpu.obs.criticalpath import (
        QUANTILE_KEYS,
        STAGES,
    )

    key = check.get("key") or "{}/{}".format(
        check.get("namespace", ""), check.get("healthcheck", "")
    )
    block = check.get("critical_path")
    if not block or not block.get("stages"):
        return (
            f"{key}: no critical-path evidence in the window yet "
            "(runs need a retained trace to decompose)"
        )
    skewed = block.get("skewed_runs") or 0
    header = "{}  dominant={}  runs={}{}  wall p95 {}".format(
        key,
        block.get("dominant_stage", "-"),
        block.get("runs", 0),
        f" ({skewed} skewed)" if skewed else "",
        _fmt_secs((block.get("wall") or {}).get("p95")),
    )
    lines = [header]
    headers = ["STAGE", "P50", "P95", "P99"]
    rows = []
    stages = block["stages"]
    for stage in STAGES:
        quantiles = stages.get(stage)
        if not quantiles:
            continue
        rows.append(
            [stage]
            + [_fmt_secs(quantiles.get(q)) for q in QUANTILE_KEYS]
        )
    widths = [
        max(len(h), *(len(r[i]) for r in rows))
        for i, h in enumerate(headers)
    ]
    lines.append(
        "  " + "  ".join(h.ljust(w) for h, w in zip(headers, widths))
    )
    for row in rows:
        lines.append(
            "  " + "  ".join(c.ljust(w) for c, w in zip(row, widths))
        )
    last = block.get("last")
    if last and last.get("segments") and last.get("wall_seconds"):
        wall = last["wall_seconds"]
        lines.append(
            "  last run (trace {}, wall {}):".format(
                (last.get("trace_id") or "-")[:16], _fmt_secs(wall)
            )
        )
        label_w = max(len(seg.get("stage", "")) for seg in last["segments"])
        for seg in last["segments"]:
            offset = max(0.0, min(seg.get("offset_seconds", 0.0), wall))
            seconds = max(0.0, min(seg.get("seconds", 0.0), wall - offset))
            lead = int(round(width * offset / wall))
            bar = max(1, int(round(width * seconds / wall)))
            bar = min(bar, width - min(lead, width - 1))
            lines.append(
                "  {}  |{}|  {}".format(
                    seg.get("stage", "").ljust(label_w),
                    (" " * lead + "#" * bar).ljust(width),
                    _fmt_secs(seg.get("seconds")),
                )
            )
    return "\n".join(lines)


async def _waterfall(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    matches = [
        check
        for check in payload.get("checks") or []
        if check.get("healthcheck") == args.name
        and (args.namespace is None or check.get("namespace") == args.namespace)
    ]
    if not matches:
        where = f" in namespace {args.namespace!r}" if args.namespace else ""
        print(
            f"healthcheck {args.name!r}{where} not found in the fleet view",
            file=sys.stderr,
        )
        return 1
    if args.output == "json":
        docs = [
            {
                "key": check.get("key"),
                "critical_path": check.get("critical_path"),
            }
            for check in matches
        ]
        print(_json.dumps(docs[0] if len(docs) == 1 else docs, indent=2))
        return 0
    print("\n".join(render_waterfall(check) for check in matches))
    return 0


def _fmt_rate(value, bound: str) -> str:
    """Human ceiling/achieved cell: TFLOP/s on the compute/memory
    rooflines, GB/s on the comm one (where the block's *_flops fields
    carry byte/s by convention — obs/roofline.classify_comm)."""
    if not isinstance(value, (int, float)):
        return "-"
    if bound == "comm":
        return f"{value / 1e9:.1f} GB/s"
    return f"{value / 1e12:.1f} TF/s"


def render_roofline(check: dict) -> str:
    """One check's `am-tpu roofline` table: per-metric intensity,
    bound, ceiling, achieved and fraction-of-roofline, with the cost
    source spelled out. Pure over a /statusz check entry so tests pin
    the rendering."""
    key = check.get("key") or "{}/{}".format(
        check.get("namespace", ""), check.get("healthcheck", "")
    )
    snapshot = check.get("roofline")
    if not snapshot or not snapshot.get("metrics"):
        return f"{key}: no roofline evidence recorded yet (quick-mode runs and old probes ship none)"
    lines = [
        "{}  worst={} {:.2f} ({}-bound)  run {}  trace={}".format(
            key,
            snapshot.get("worst", "-"),
            snapshot.get("worst_fraction") or 0.0,
            snapshot.get("worst_bound", "?"),
            snapshot.get("ts", "-"),
            (snapshot.get("trace_id") or "-")[:16],
        )
    ]
    headers = [
        "METRIC", "BOUND", "INTENSITY", "RIDGE", "CEILING", "ACHIEVED",
        "FRACTION", "SOURCE",
    ]
    rows = []
    for metric in sorted(snapshot["metrics"]):
        entry = snapshot["metrics"][metric]
        bound = entry.get("bound", "?")
        rows.append(
            [
                metric,
                bound,
                f"{entry.get('intensity', 0.0):.3g} F/B",
                (
                    f"{entry.get('ridge', 0.0):.3g} F/B"
                    if bound != "comm"
                    else "-"
                ),
                _fmt_rate(entry.get("ceiling_flops"), bound),
                _fmt_rate(entry.get("achieved_flops"), bound),
                f"{entry.get('fraction', 0.0):.3f}",
                entry.get("cost_source", "?"),
            ]
        )
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) for i, h in enumerate(headers)
    ]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    for row in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    if any(r[7] == "model" for r in rows):
        lines.append(
            "note: 'model' rows are analytic estimates (interpret mode / "
            "old JAX) — informational, never compared against a TPU bar"
        )
    return "\n".join(lines)


async def _roofline(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    matches = [
        check
        for check in payload.get("checks") or []
        if check.get("healthcheck") == args.name
        and (args.namespace is None or check.get("namespace") == args.namespace)
    ]
    if not matches:
        where = f" in namespace {args.namespace!r}" if args.namespace else ""
        print(
            f"healthcheck {args.name!r}{where} not found in the fleet view",
            file=sys.stderr,
        )
        return 1
    if args.output == "json":
        docs = [
            {"key": check.get("key"), "roofline": check.get("roofline")}
            for check in matches
        ]
        print(_json.dumps(docs[0] if len(docs) == 1 else docs, indent=2))
        return 0
    print("\n".join(render_roofline(check) for check in matches))
    return 0


def render_matrix(block) -> str:
    """The /statusz fleet ``matrix`` block as the `am-tpu matrix` cell
    table: per cell the hysteresis VERDICT, the roofline CEILING a
    regression would name, and VS-BASELINE against the learned median.
    Pure over the payload block so tests pin the rendering."""
    if not block:
        return (
            "no scenario-matrix rounds recorded yet (run bench.py, or "
            "point the controller at the sidecar with --matrix-state)"
        )
    lines = [
        "matrix round {}  interpret_mode={}  ok={} skipped={} error={}".format(
            block.get("generated_at", "-"),
            str(bool(block.get("interpret_mode"))).lower(),
            (block.get("counts") or {}).get("ok", 0),
            (block.get("counts") or {}).get("skipped", 0),
            (block.get("counts") or {}).get("error", 0),
        )
    ]
    if block.get("fallback_reason"):
        lines.append(f"fallback_reason: {block['fallback_reason']}")
    warning = block.get("restore_warning")
    if warning:
        lines.append(
            "sidecar restored fresh: {} ({})".format(
                warning.get("reason", "?"), warning.get("detail", "")
            )
        )
    cells = block.get("cells") or {}
    headers = [
        "CELL", "STATUS", "VERDICT", "VALUE", "VS-BASELINE", "CEILING",
        "SCHED", "REASON",
    ]
    rows = []
    for cell_id in sorted(cells):
        entry = cells[cell_id]
        roofline = entry.get("roofline") or {}
        if entry.get("status") == "ok":
            ceiling = (
                roofline.get("bound", "-")
                if "bound" in roofline
                else f"({roofline.get('skipped', 'no verdict')[:28]})"
            )
        else:
            ceiling = "-"
        value = entry.get("value")
        vs_baseline = entry.get("vs_baseline")
        rows.append(
            [
                cell_id,
                entry.get("status", "?"),
                entry.get("verdict", "-"),
                (
                    f"{value:.4g}{entry.get('unit', '')}"
                    if isinstance(value, (int, float))
                    else "-"
                ),
                (
                    f"{vs_baseline:.2f}x"
                    if isinstance(vs_baseline, (int, float))
                    else "-"
                ),
                ceiling,
                entry.get("schedule") or "-",
                (entry.get("reason") or "")[:60],
            ]
        )
    if rows:
        widths = [
            max(len(h), *(len(r[i]) for r in rows))
            for i, h in enumerate(headers)
        ]
        lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
        for row in rows:
            lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    for regression in block.get("regressions") or []:
        lines.append(
            "REGRESSION {}: {} {} -> {}  ceiling={}  bisect={}".format(
                regression.get("cell", "?"),
                regression.get("metric", "?"),
                *(regression.get("transition") or ["?", "?"])[:2],
                regression.get("ceiling") or "unstamped",
                regression.get("bisect_outcome", "not-run"),
            )
        )
    if block.get("interpret_mode"):
        lines.append(
            "note: interpret-mode round — analytic cost models and CPU "
            "timings, never compared against a TPU bar"
        )
    return "\n".join(lines)


async def _matrix(args) -> int:
    import json as _json

    payload = await _fetch_fleet_payload(args)
    if payload is None:
        return 1
    block = (payload.get("fleet") or {}).get("matrix")
    if args.output == "json":
        print(_json.dumps(block, indent=2))
        return 0
    print(render_matrix(block))
    return 0


def render_journal(block) -> str:
    """The `am-tpu journal` report: segment table, per-stream event
    counts, replay coverage. Pure over either journal view — the
    on-disk block (``--journal-dir``: segments + ``events`` counts +
    ``coverage``) or the /statusz fleet block (``appended`` /
    ``replayed`` counters, possibly rolled up across replicas) — so
    tests pin the rendering."""
    if not block:
        return (
            "no journal recorded (run the controller with "
            "--journal-dir, or point --journal-dir here at a journal "
            "directory)"
        )
    header = "journal"
    if block.get("dir"):
        header += " {}".format(block["dir"])
    header += "  segments={}".format(
        block.get("segment_count", len(block.get("segments") or []))
    )
    if block.get("replicas"):
        header += "  replicas={}".format(block["replicas"])
    if isinstance(block.get("lag_seconds"), (int, float)):
        header += "  lag={:.1f}s".format(block["lag_seconds"])
    lines = [header]
    warning = block.get("restore_warning")
    if warning:
        lines.append(
            "restored fresh: {} ({})".format(
                warning.get("reason", "?"), warning.get("detail", "")
            )
        )
    segments = block.get("segments") or []
    if segments:
        lines.append("SEGMENT              BYTES  ACTIVE")
        for seg in segments:
            lines.append(
                "{:<19}  {:>5}  {}".format(
                    seg.get("name", seg.get("segment", "?")),
                    seg.get("bytes", 0),
                    "*" if seg.get("active") else "",
                ).rstrip()
            )
    events = block.get("events")
    if events:
        lines.append("STREAM        EVENTS")
        for stream in sorted(events):
            lines.append("{:<12}  {:>6}".format(stream, events[stream]))
    appended = block.get("appended")
    if appended:
        replayed = block.get("replayed") or {}
        lines.append("STREAM        APPENDED  REPLAYED")
        for stream in sorted(appended):
            lines.append(
                "{:<12}  {:>8}  {:>8}".format(
                    stream, appended[stream], replayed.get(stream, 0)
                )
            )
    if "dropped" in block:
        lines.append(
            "dropped={}  compacted_segments={}".format(
                block.get("dropped", 0), block.get("compacted_segments", 0)
            )
        )
    coverage = block.get("coverage")
    if coverage is not None:
        lines.append(
            "replay coverage: {} arrivals over {:.1f}s  tenants={}  "
            "checks={}".format(
                coverage.get("events", 0),
                coverage.get("span_seconds") or 0.0,
                ",".join(coverage.get("tenants") or []) or "-",
                ",".join(coverage.get("checks") or []) or "-",
            )
        )
    return "\n".join(lines)


def _local_journal_block(journal_dir: str):
    """The on-disk journal view the `am-tpu journal --journal-dir`
    path renders: segment table from the directory, per-stream event
    counts and replay coverage from an all-or-nothing read (a torn
    journal shows the structured warning and zero events, exactly what
    a restart would restore). None when the directory does not exist."""
    import os

    from activemonitor_tpu.obs.journal import (
        STREAM_ARRIVAL,
        STREAMS,
        list_segments,
        read_journal,
    )
    from activemonitor_tpu.obs.replay import RecordedArrivals

    if not os.path.isdir(journal_dir):
        return None
    events, warnings = read_journal(journal_dir)
    counts = {stream: 0 for stream in STREAMS}
    for event in events:
        stream = event.get("stream")
        if stream in counts:
            counts[stream] += 1
    schedule = RecordedArrivals(
        [ev for ev in events if ev.get("stream") == STREAM_ARRIVAL]
    )
    pairs = list_segments(journal_dir)
    segments = []
    for seq, path in pairs:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        segments.append(
            {
                "segment": seq,
                "name": os.path.basename(path),
                "bytes": size,
                "active": seq == pairs[-1][0],
            }
        )
    return {
        "dir": journal_dir,
        "segment_count": len(segments),
        "segments": segments,
        "events": counts,
        "coverage": schedule.coverage(),
        "restore_warning": warnings[0] if warnings else None,
    }


async def _journal(args) -> int:
    import json as _json

    journal_dir = getattr(args, "journal_dir", "")
    if journal_dir:
        block = _local_journal_block(journal_dir)
        if block is None:
            print(
                f"error: {journal_dir} is not a directory", file=sys.stderr
            )
            return 1
    else:
        payload = await _fetch_fleet_payload(args)
        if payload is None:
            return 1
        block = (payload.get("fleet") or {}).get("journal")
    if args.output == "json":
        print(_json.dumps(block, indent=2))
        return 0
    print(render_journal(block))
    return 0


def render_drive_summary(verb: str, summary: dict) -> str:
    """The shared `am-tpu record`/`replay` report: how many requests
    were driven, the tenant mix, the outcome ledger and whether the
    per-tenant conservation identity held. Pure over the
    ``drive_requests`` summary so tests pin the rendering."""
    mix = summary.get("tenant_mix") or {}
    outcomes = summary.get("outcome_counts") or {}
    lines = [
        "{}: {} requests driven  conservation={}".format(
            verb,
            summary.get("requests", 0),
            "ok" if summary.get("conservation_ok") else "VIOLATED",
        ),
        "tenant mix: "
        + (
            "  ".join(f"{t}={mix[t]}" for t in sorted(mix)) or "none"
        ),
        "outcomes:   "
        + (
            "  ".join(f"{o}={outcomes[o]}" for o in sorted(outcomes))
            or "none"
        ),
    ]
    return "\n".join(lines)


async def _record(args) -> int:
    import json as _json

    from activemonitor_tpu.errors import ConfigurationError as _ConfigError
    from activemonitor_tpu.frontdoor.traffic import open_loop_checks
    from activemonitor_tpu.obs.journal import TelemetryJournal
    from activemonitor_tpu.obs.replay import drive_requests

    if args.requests < 1:
        raise _ConfigError(f"--requests must be >= 1, got {args.requests}")
    if args.rate <= 0:
        raise _ConfigError(f"--rate must be > 0, got {args.rate}")
    checks = tuple(args.check or ("bench/hc-a", "bench/hc-b", "bench/hc-c"))
    tenants = tuple(args.tenant or ("tenant-a", "tenant-b"))
    requests = open_loop_checks(
        args.requests, args.rate, args.seed, checks, tenants=tenants
    )
    journal = TelemetryJournal(args.journal_dir)
    try:
        summary = await drive_requests(
            requests, journal=journal, default_freshness=args.freshness
        )
    finally:
        journal.close()
    if args.output == "json":
        doc = dict(summary)
        doc["journal"] = journal.snapshot()
        print(_json.dumps(doc, indent=2))
        return 0 if summary["conservation_ok"] else 1
    lines = [render_drive_summary("recorded", summary)]
    lines.append(
        "journal:    {}  segments={}  arrivals appended={}".format(
            args.journal_dir,
            len(journal.segments()),
            journal.appended.get("arrival", 0),
        )
    )
    print("\n".join(lines))
    return 0 if summary["conservation_ok"] else 1


async def _replay(args) -> int:
    import json as _json

    from activemonitor_tpu.frontdoor.traffic import replayed_checks
    from activemonitor_tpu.obs.replay import drive_requests, load_trace

    schedule, warnings = load_trace(args.journal_dir)
    if warnings:
        warning = warnings[0]
        print(
            "error: journal unusable: {} ({})".format(
                warning.get("reason", "?"), warning.get("detail", "")
            ),
            file=sys.stderr,
        )
        return 1
    if not len(schedule):
        print(
            f"error: no arrival events recorded in {args.journal_dir} "
            "(run `am-tpu record` first, or point a controller at it "
            "with --journal-dir)",
            file=sys.stderr,
        )
        return 1
    coverage = schedule.coverage()
    requests = replayed_checks(schedule)
    summary = await drive_requests(
        requests, default_freshness=args.freshness
    )
    if args.output == "json":
        doc = dict(summary)
        doc["coverage"] = coverage
        print(_json.dumps(doc, indent=2))
        return 0 if summary["conservation_ok"] else 1
    lines = [render_drive_summary("replayed", summary)]
    lines.append(
        "coverage:   {} arrivals over {:.1f}s".format(
            coverage.get("events", 0), coverage.get("span_seconds") or 0.0
        )
    )
    print("\n".join(lines))
    return 0 if summary["conservation_ok"] else 1


async def _describe(args) -> int:
    import yaml as _yaml

    client, kube_api = _cli_client(args)
    try:
        hc = await client.get(args.namespace, args.name)
        if hc is None:
            print(
                f"healthcheck {args.namespace}/{args.name} not found", file=sys.stderr
            )
            return 1
        events = await _describe_events(args, kube_api)
    finally:
        if kube_api is not None:
            await kube_api.close()

    def print_indented(doc) -> None:
        for line in _yaml.safe_dump(doc, sort_keys=False).splitlines():
            print(f"  {line}")

    print(f"Name:       {hc.metadata.name}")
    print(f"Namespace:  {hc.metadata.namespace}")
    print(f"Status:     {hc.status.status or '<none>'}")
    print("Spec:")
    print_indented(hc.spec.to_json_dict())
    print("Status detail:")
    print_indented(hc.status.to_json_dict())
    print(f"Events ({len(events)} recorded):")
    for ev in events[-20:]:
        print(f"  {ev.get('time', '')}  {ev.get('type', ''):8} {ev.get('message', '')}")
    return 0


async def _describe_events(args, kube_api) -> list:
    """Recent events for the check: the Events API in cluster mode
    (what kubectl describe shows), the JSONL sidecars in file mode."""
    if kube_api is not None:
        from activemonitor_tpu.kube import core_path

        # server-side filtering like kubectl; the client-side filter
        # below stays as a belt (not every server honors the selector)
        raw = await kube_api.get(
            core_path("events", args.namespace),
            params={
                "fieldSelector": (
                    f"involvedObject.name={args.name},"
                    "involvedObject.kind=HealthCheck"
                )
            },
        )
        out = []
        for ev in raw.get("items", []):
            involved = ev.get("involvedObject") or {}
            if involved.get("kind") == "HealthCheck" and involved.get("name") == args.name:
                out.append(
                    {
                        # events.k8s.io-created events carry null first/
                        # lastTimestamp (eventTime instead) — never None
                        "time": (
                            ev.get("lastTimestamp")
                            or ev.get("firstTimestamp")
                            or ev.get("eventTime")
                            or ""
                        ),
                        "type": ev.get("type", ""),
                        "reason": ev.get("reason", ""),
                        "message": ev.get("message", ""),
                    }
                )
        return sorted(out, key=lambda e: e["time"])
    from activemonitor_tpu.controller.events import FileEventRecorder

    return FileEventRecorder.read_events(args.store, args.namespace, args.name)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        from activemonitor_tpu import __version__

        print(__version__)
        return 0
    if args.command == "crd":
        from activemonitor_tpu.api.crd import crd_yaml

        print(crd_yaml(), end="")
        return 0
    handler = {
        "run": _run,
        "apply": _apply,
        "delete": _delete,
        "get": _get,
        "describe": _describe,
        "status": _status,
        "clusters": _clusters,
        "why": _why,
        "waterfall": _waterfall,
        "goodput": _goodput,
        "roofline": _roofline,
        "matrix": _matrix,
        "journal": _journal,
        "record": _record,
        "replay": _replay,
    }[args.command]
    if args.command == "run":
        # pre-import the controller's heavy dependency graph BEFORE the
        # event loop exists: the per-verb lazy imports keep `--help`/
        # `version`/`crd` fast, but resolved on-loop they block it for
        # ~0.7 s right as the controller starts (pydantic, prometheus,
        # requests, the reconciler graph). Harmless here — this process
        # is about to run a controller anyway.
        import activemonitor_tpu.controller.manager  # noqa: F401
        import activemonitor_tpu.controller.reconciler  # noqa: F401
        import activemonitor_tpu.engine.argo  # noqa: F401
        import activemonitor_tpu.engine.local  # noqa: F401
        import activemonitor_tpu.metrics.collector  # noqa: F401
    from activemonitor_tpu.errors import MissingDependencyError

    from activemonitor_tpu.errors import ConfigurationError

    try:
        return asyncio.run(handler(args))
    except (MissingDependencyError, ConfigurationError) as e:
        # configuration problems (missing credentials, invalid flag
        # combinations, bad manifests — wrapped as ConfigurationError at
        # the parse site) read as usage errors, not crashes. Deliberately
        # NOT every ValueError/ValidationError: those would eat
        # tracebacks for internal bugs in a long-running controller
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
