"""DCN (cross-host) all-reduce probe — the multi-slice/multi-host check.

Runs on every host of a multi-host slice (or multislice topology) with
jax.distributed initialized, builds the hierarchical (dcn, ici) mesh,
and measures the all-reduce over the cross-host axis — traffic that
rides DCN between slices (or the host interconnect within one) rather
than intra-host ICI. Two correctness gates catch broken cross-host
collectives outright: a psum of a known payload over all hosts, and
the HIERARCHICAL composition (parallel/schedules.hier_all_reduce —
intra-slice reduce-scatter over ICI, cross-slice exchange over DCN,
all-gather back) against the same psum reference over the full
two-tier mesh.

Per-tier exports (the ("dcn", "ici") spelling of the ici probe's
north-star gauges; pinned in docs/probes.md):

- ``dcn-xslice-busbw-gbps`` — cross-slice all-reduce busbw over the
  DCN tier (one representative device per host, so per-host NIC
  contention doesn't understate the number)
- ``dcn-xslice-fraction-of-rated`` — busbw / rated per-host DCN
  egress (probes/rated.RatedSpec.dcn_gbps; TPU + known rating only)
- ``dcn-hier-allreduce-correct`` — 1 when the hierarchical
  composition matches psum over the full (dcn, ici) mesh

Every worker of the workflow runs the same command; exit codes combine
through the workflow's parallel steps:

    python -m activemonitor_tpu.probes --distributed dcn-allreduce

(GKE multi-host TPU pods need no explicit coordinator — JAX
auto-detects; elsewhere pass --coordinator host:port --num-processes N
--process-id I.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel.collectives import all_reduce_bandwidth
from activemonitor_tpu.parallel.mesh import make_multihost_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for


def run(size_mb: float = 16.0, iters: int = 4) -> ProbeResult:
    n_proc = jax.process_count()
    local = jax.local_device_count()
    if n_proc < 2:
        return ProbeResult(
            ok=True,
            summary=(
                "single process — no cross-host axis to measure "
                "(initialize jax.distributed across hosts first)"
            ),
            metrics=[
                ProbeMetric(
                    "dcn-hosts", 1, help="Number of hosts in the distributed run"
                )
            ],
            details={
                "processes": 1,
                "skipped": True,
                # the two-tier shape the probe WOULD have measured —
                # so a skip in a fleet rollup still says what topology
                # was absent (the run_per_axis skip contract)
                "mesh": {"dcn": 1, "ici": local},
            },
        )

    mesh = make_multihost_mesh()

    # correctness: psum over the dcn axis of a rank-tagged payload must
    # equal the sum over all hosts, identically on every host
    from activemonitor_tpu.parallel.partition import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P("dcn", None),
        out_specs=P(None, None),
        check_vma=False,
    )
    def cross_host_sum(x):
        return jax.lax.psum(x, "dcn")

    local = mesh.shape["ici"]
    x = jnp.arange(n_proc * local, dtype=jnp.float32).reshape(n_proc, local)
    got = cross_host_sum(x)
    expected = jnp.broadcast_to(x.sum(axis=0), (1, local))
    correct = bool(jnp.allclose(got, expected))

    # the hierarchical composition over the FULL (dcn, ici) mesh —
    # reduce-scatter inside the slice over ICI, exchange over DCN,
    # gather back — must agree with the joint psum: this is the
    # schedule the two-tier grad sync / autotune surface dispatches,
    # proven on the very topology it targets
    from activemonitor_tpu.parallel.schedules import hier_all_reduce

    rows = 4 * n_proc * local

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(("dcn", "ici"), None),
        out_specs=P(None, None),
        check_vma=False,
    )
    def hier_vs_psum(v):
        got = hier_all_reduce(v, "dcn", "ici", n_proc, local)
        want = jax.lax.psum(v, ("dcn", "ici"))
        # pmax replicates the verdict: a mismatch on ANY device must
        # reach the one shard the host reads, not just a first-device
        # local diff (check_vma=False would silently read one shard)
        return jax.lax.pmax(
            jnp.max(jnp.abs(got - want)), ("dcn", "ici")
        )[None, None]

    payload = jnp.arange(rows * 3, dtype=jnp.float32).reshape(rows, 3) % 11
    hier_diff = float(hier_vs_psum(payload)[0, 0])
    hier_correct = hier_diff == 0.0

    # bandwidth is measured over ONE device per host: on the full
    # (dcn, ici) mesh the payload would be replicated across the ici
    # axis and every local device would run an identical concurrent
    # psum group, contending for the same NICs while the accounting
    # counted only one group's bytes — understating busbw by the
    # per-host device count.
    representatives = [mesh.devices[p, 0] for p in range(n_proc)]
    from activemonitor_tpu.parallel.mesh import make_1d_mesh

    bw_mesh = make_1d_mesh("dcn", devices=representatives)
    result = all_reduce_bandwidth(bw_mesh, size_mb=size_mb, iters=iters, axis="dcn")
    metrics = [
        ProbeMetric("dcn-hosts", n_proc, help="Number of hosts in the distributed run"),
        ProbeMetric(
            "dcn-allreduce-busbw-gbps",
            result.busbw_gbps,
            help="Cross-host all-reduce bus bandwidth, GB/s",
        ),
        ProbeMetric(
            "dcn-xslice-busbw-gbps",
            result.busbw_gbps,
            help="Cross-slice (DCN tier) all-reduce bus bandwidth, GB/s "
            "— the slow tier of the (dcn, ici) hierarchy",
        ),
        ProbeMetric(
            "dcn-allreduce-correct",
            1.0 if correct else 0.0,
            help="1 when the cross-host psum result is correct",
        ),
        ProbeMetric(
            "dcn-hier-allreduce-correct",
            1.0 if hier_correct else 0.0,
            help="1 when the hierarchical (ICI reduce-scatter → DCN "
            "exchange → ICI all-gather) composition matches psum over "
            "the full two-tier mesh",
        ),
    ]
    details = {
        "processes": n_proc,
        "local_devices": local,
        "mesh": {"dcn": n_proc, "ici": local},
        "payload_mb": result.payload_bytes / 1e6,
        "seconds_per_op": result.seconds_per_op,
        "hier_allreduce_max_diff": hier_diff,
    }

    # rated comparison: the DCN tier gets the same fraction-of-rated
    # treatment the ICI probe's north-star gauge has — per-host egress
    # is the ceiling one cross-host ring direction can use. TPU with a
    # known DCN rating only: CPU two-process runs are a CI substrate,
    # never judged against a datacenter NIC.
    devices = jax.devices()
    rated = rated_for(devices[0].device_kind)
    if (
        rated is not None
        and rated.dcn_gbps > 0
        and devices[0].platform == "tpu"
    ):
        fraction = result.busbw_gbps / rated.dcn_gbps
        metrics.append(
            ProbeMetric(
                "dcn-xslice-fraction-of-rated",
                fraction,
                help="Cross-slice busbw / rated per-host DCN egress "
                "(ACTIVEMONITOR_RATED_DCN_GBPS overrides)",
            )
        )
        details["rated_dcn_gbps"] = rated.dcn_gbps
        details["xslice_fraction_of_rated"] = round(fraction, 3)

    ok = correct and hier_correct
    return ProbeResult(
        ok=ok,
        summary=(
            f"cross-host all-reduce over {n_proc} hosts: "
            f"{result.busbw_gbps:.2f} GB/s busbw, "
            f"correctness {'OK' if correct else 'MISMATCH'}, "
            f"hierarchical {'OK' if hier_correct else 'MISMATCH'}"
        ),
        metrics=metrics,
        details=details,
    )
