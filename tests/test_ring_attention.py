"""Ring attention tests — sequence parallelism on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.ring_attention import reference_attention, ring_attention
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes import ring as ring_probe


@pytest.fixture(scope="module")
def mesh():
    return make_1d_mesh("sp")


def qkv(seq=64, batch=2, heads=4, head_dim=16, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(0), 3)
    return tuple(
        jax.random.normal(k, (batch, seq, heads, head_dim), dtype) for k in keys
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(mesh, causal):
    q, k, v = qkv()
    got = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_flash_block_compute_matches_reference(mesh, causal):
    # the fused per-step block compute (flash_attention_partial under
    # the ring's lax.switch) must agree with both the XLA path and the
    # single-device reference
    q, k, v = qkv(seq=128)
    flash = ring_attention(q, k, v, mesh, "sp", causal=causal, use_flash=True)
    plain = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(flash - want))) < 1e-5
    assert float(jnp.max(jnp.abs(flash - plain))) < 1e-5


@pytest.mark.slow  # probe plumbing for use_flash; the kernel paths have direct tier-1 tests
def test_probe_flash_mode(mesh):
    # overlap_metrics=False: every cross-schedule check is another
    # interpret-mode flash compile; the fused bidir/serial paths get
    # direct coverage below at a fraction of the cost
    result = ring_probe.run(
        batch=1, seq_per_device=16, heads=2, head_dim=16, iters=2,
        use_flash=True, overlap_metrics=False,
    )
    assert result.ok
    assert result.details["block_compute"] == "flash"


def test_matches_reference_bf16(mesh):
    q, k, v = qkv(dtype=jnp.bfloat16)
    got = ring_attention(q, k, v, mesh, "sp")
    want = reference_attention(q, k, v)
    assert (
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))) < 2e-2
    )


def test_jit_compatible(mesh):
    q, k, v = qkv()
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, "sp"))
    out = fn(q, k, v)
    assert out.shape == q.shape
    assert jnp.isfinite(out).all()


def test_single_query_block_first_row(mesh):
    """Causality: token 0 attends only to itself — output equals v[0]."""
    q, k, v = qkv()
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    assert jnp.allclose(out[:, 0], v[:, 0], atol=1e-5)


def test_probe_runs_and_reports(mesh):
    result = ring_probe.run(seq_per_device=16, heads=2, head_dim=8, iters=2)
    assert result.ok
    names = {m.name for m in result.metrics}
    assert names == {
        "ring-attention-max-error",
        "ring-attention-tokens-per-second",
        "ring-attention-tflops",
        "ring-overlap-efficiency",
        "ring-attention-busbw-gbps",
        # roofline evidence (ISSUE 9): intensity always; the fraction
        # needs a rated spec, absent on the CPU mesh (structured skip)
        "ring-attention-arithmetic-intensity",
    }
    assert "skipped" in result.details["roofline"]["ring-attention"]
    assert result.details["devices"] == 8
    assert result.details["seq"] == 16 * 8
    assert result.details["variant"] == "overlap"
    # the bit-compat cross-check ran and held
    assert result.details["overlap_vs_serial_max_error"] == 0.0
    assert result.details["bidir_max_error"] <= 2e-2
    assert result.details["overlap_efficiency"] > 0


def test_probe_bidir_variant_and_optional_overlap_metrics(mesh):
    # one probe run covers both: the bidir schedule drives the
    # throughput chain, and overlap_metrics=False drops the serial
    # baseline pass (and with it the efficiency/busbw gauges)
    result = ring_probe.run(
        seq_per_device=16, heads=2, head_dim=8, iters=2,
        variant="bidir", overlap_metrics=False,
    )
    assert result.ok
    assert result.details["variant"] == "bidir"
    names = {m.name for m in result.metrics}
    assert "ring-overlap-efficiency" not in names
    assert "ring-attention-busbw-gbps" not in names
    with pytest.raises(ValueError, match="variant"):
        ring_probe.run(seq_per_device=16, iters=1, variant="bogus")


def test_distributed_detection(monkeypatch):
    from activemonitor_tpu.parallel.distributed import detect_multihost_env

    monkeypatch.delenv("ACTIVEMONITOR_DISTRIBUTED", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a")
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b")
    assert detect_multihost_env()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("ACTIVEMONITOR_DISTRIBUTED", "1")
    assert detect_multihost_env()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
def test_gradients_match_reference(mesh, causal, use_flash):
    """The custom-VJP backward (second K/V ring pass against the saved
    global logsumexp) must agree with autodiff through single-device
    attention — for the XLA einsum blocks AND the fused kernel blocks."""
    q, k, v = qkv()

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp", causal=causal, use_flash=use_flash
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_gradients_bf16(mesh):
    """bf16 inputs keep bf16 on the wire in BOTH ring passes; gradients
    still track the float32 reference within bf16 rounding."""
    q, k, v = qkv(dtype=jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(a, b, c, mesh, "sp")),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(
            a.astype(jnp.float32), b.astype(jnp.float32), c.astype(jnp.float32)
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(g_ring, g_ref):
        norm = max(1e-9, float(jnp.max(jnp.abs(want))))
        rel = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want))) / norm
        assert rel < 5e-2


def test_train_step_ring_attention():
    """attention="ring" trains: a dp×tp×sp composed step through ring
    attention's custom VJP produces a finite loss that decreases."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.parallel.mesh import make_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    sp_mesh = make_mesh(("data", "model", "sp"), (2, 2, 2))
    cfg = tiny_config()
    step, params, opt, data_sh = build_sharded_train_step(
        cfg, sp_mesh, attention="ring"
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(3), (4, 17), 0, cfg.vocab_size),
        data_sh,
    )
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tokens)
        losses.append(float(loss))
    assert all(l == l for l in losses), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.slow  # GQA x flash grads matrix; tier-1 anchors: test_bidir_gqa_matches_reference + test_train_step_ring_attention
def test_gqa_matches_reference(mesh, causal, use_flash):
    """Grouped K/V heads ride the ring with the NARROW head count on
    the wire (the GQA bandwidth win applies to ICI traffic too);
    gradients come back group-summed in K/V's own shape."""
    keys = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(keys[0], (2, 64, 4, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 64, 2, 16), jnp.float32)
    got = ring_attention(q, k, v, mesh, "sp", causal=causal, use_flash=use_flash)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == q.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp", causal=causal, use_flash=use_flash
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert g_ring[1].shape == k.shape  # group already summed
    for a, b in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.slow  # covered by test_probes' ring train-step + test_bidir_gqa
def test_train_step_ring_attention_gqa():
    """A GQA config trains through sequence-parallel ring attention."""
    from activemonitor_tpu.models.probe_model import ProbeModelConfig
    from activemonitor_tpu.parallel.mesh import make_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    cfg = ProbeModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    sp_mesh = make_mesh(("data", "model", "sp"), (2, 2, 2))
    step, params, opt, data_sh = build_sharded_train_step(
        cfg, sp_mesh, attention="ring"
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(5), (4, 33), 0, cfg.vocab_size),
        data_sh,
    )
    _, _, loss = step(params, opt, tokens)
    value = float(loss)
    assert value == value and 0 < value < 10


def test_ring_attention_fn_validates_axes():
    from activemonitor_tpu.models.probe_model import ring_attention_fn, tiny_config
    from activemonitor_tpu.parallel.mesh import make_mesh

    cfg = tiny_config()
    with pytest.raises(ValueError, match="'sp' mesh axis"):
        ring_attention_fn(cfg, make_mesh(("data", "model"), (2, 4)))
    with pytest.raises(ValueError, match="divisible"):
        # tiny_config has 4 heads; tp axis of 8 cannot split them
        ring_attention_fn(cfg, make_mesh(("model", "sp"), (8, 1)))


@pytest.mark.slow  # model-level composition; probe/dryrun cover the path
def test_context_parallel_forward_matches_dense(mesh):
    """The long-context model path (seq sharded + ring attention) must
    agree with the dense single-device forward."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from activemonitor_tpu.models.probe_model import (
        forward,
        forward_context_parallel,
        init_params,
        tiny_config,
    )

    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab_size)
    sharded = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    got = forward_context_parallel(params, sharded, cfg, mesh)
    want = forward(params, tokens, cfg)
    assert jnp.max(jnp.abs(got - want)) < 3e-2  # bf16 compute tolerance



# -- compute–communication overlap layer -------------------------------
# The three rotation schedules (serial baseline, double-buffered
# overlap, bidirectional halves) share one merge contract: overlap is
# BITWISE serial (same blocks merged in the same order — only the
# transfer timing differs), bidir merges halves in a different order
# and gets numerical tolerance against the single-device reference.
# The default-variant ("overlap") coverage above — reference match,
# flash blocks, gradients, GQA, bf16, train step — already exercises
# the overlapped schedule everywhere; the tests below pin the serial/
# bidir cross-checks, the global-lse contract, and the hop budgets,
# consolidated into few compiles (every eager shard_map call compiles a
# fresh program on the CPU mesh, the dominant cost of this file).


def submesh(n):
    from activemonitor_tpu.parallel.mesh import make_1d_mesh as mk

    return mk("sp", devices=jax.devices()[:n])


def _sharded_fwd(m, n, variant, causal=True, unroll=False):
    """shard_map the internal forward so tests see (out, lse)."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from activemonitor_tpu.ops.ring_attention import _ring_attention_sharded
    from activemonitor_tpu.parallel.partition import shard_map

    spec = P(None, "sp", None, None)
    lse_spec = P(None, None, "sp")

    @_partial(
        shard_map, mesh=m, in_specs=(spec,) * 3,
        out_specs=(spec, lse_spec), check_vma=False,
    )
    def fwd(q, k, v):
        return _ring_attention_sharded(
            q, k, v, axis_name="sp", n_devices=n, causal=causal,
            use_flash=False, variant=variant, unroll=unroll,
        )

    return fwd


@pytest.mark.parametrize("n_devices", [2, 8])
def test_variants_match_reference_out_and_lse(n_devices):
    """Forward out AND global lse (the backward residual) per schedule:
    overlap bitwise-equals serial, bidir within reference tolerance."""
    m = submesh(n_devices)
    q, k, v = qkv(seq=8 * n_devices, batch=1, heads=2, head_dim=8)
    want = reference_attention(q, k, v, causal=True)
    out_s, lse_s = _sharded_fwd(m, n_devices, "serial")(q, k, v)
    out_o, lse_o = _sharded_fwd(m, n_devices, "overlap")(q, k, v)
    out_b, lse_b = _sharded_fwd(m, n_devices, "bidir")(q, k, v)
    assert jnp.array_equal(out_s, out_o)
    assert jnp.array_equal(lse_s, lse_o)
    assert float(jnp.max(jnp.abs(out_s - want))) < 1e-5
    assert float(jnp.max(jnp.abs(out_b - want))) < 1e-5
    assert float(jnp.max(jnp.abs(lse_b - lse_s))) < 1e-5


def test_bidir_matches_reference_non_causal():
    # serial non-causal is covered by test_matches_reference[False]
    # through its bitwise overlap twin; bidir needs its own pass
    m = submesh(8)
    q, k, v = qkv(seq=64, batch=1, heads=2, head_dim=8)
    want = reference_attention(q, k, v, causal=False)
    bidir = ring_attention(q, k, v, m, "sp", causal=False, variant="bidir")
    assert float(jnp.max(jnp.abs(bidir - want))) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_bidir_flash_blocks_match_reference(mesh, causal):
    # bidir under the fused Pallas block compute (interpret mode): the
    # diagonal runs the causal kernel, the 8-aligned halves the
    # unmasked one — same merge contract as the einsum path
    q, k, v = qkv(seq=128)
    got = ring_attention(
        q, k, v, mesh, "sp", causal=causal, use_flash=True, variant="bidir"
    )
    want = reference_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


@pytest.mark.parametrize("use_flash", [False, True])
def test_bidir_gradients_match_reference(mesh, use_flash):
    """Bidirectional backward (half accumulators riding both ring
    directions home) against autodiff through the reference; under
    use_flash the diagonal uses the fused backward kernel while halves
    take the einsum path (square-block kernel contract)."""
    q, k, v = qkv(seq=128 if use_flash else 64, heads=2, head_dim=8)

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c)), argnums=(0, 1, 2)
    )(q, k, v)
    g = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp", use_flash=use_flash, variant="bidir"
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(g, g_ref):
        assert float(jnp.max(jnp.abs(got - want))) < 1e-4


def test_overlap_gradients_bitwise_serial():
    """The overlapped backward merges the same per-block contributions
    in the same order as serial — gradients must be bit-identical."""
    m = submesh(2)
    q, k, v = qkv(seq=16, batch=1, heads=2, head_dim=8)

    def grads(variant):
        def loss(a, b, c):
            return jnp.sum(
                ring_attention(
                    a, b, c, m, "sp", variant=variant
                ).astype(jnp.float32) ** 2
            )

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for got, want in zip(grads("overlap"), grads("serial")):
        assert jnp.array_equal(got, want)


def test_odd_shard_shapes():
    # seq_local = 9: bidir halves split 4/5 (einsum block compute);
    # serial covers overlap (bitwise twins) and bidir gets the
    # reference tolerance, forward and gradients
    n = 4
    m = submesh(n)
    q, k, v = qkv(seq=9 * n, batch=1, heads=2, head_dim=8)
    want = reference_attention(q, k, v, causal=True)
    serial = ring_attention(q, k, v, m, "sp", causal=True, variant="serial")
    bidir = ring_attention(q, k, v, m, "sp", causal=True, variant="bidir")
    assert float(jnp.max(jnp.abs(serial - want))) < 1e-5
    assert float(jnp.max(jnp.abs(bidir - want))) < 1e-5

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, m, "sp", variant="bidir"
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want_g in zip(g, g_ref):
        assert float(jnp.max(jnp.abs(got - want_g))) < 1e-4


def test_bidir_gqa_matches_reference():
    """Grouped K/V heads ride both ring directions with the NARROW head
    count on the wire; dK/dV come back group-summed in K/V's shape."""
    m = submesh(4)
    keys = jax.random.split(jax.random.key(6), 3)
    q = jax.random.normal(keys[0], (1, 32, 4, 8), jnp.float32)
    k = jax.random.normal(keys[1], (1, 32, 2, 8), jnp.float32)
    v = jax.random.normal(keys[2], (1, 32, 2, 8), jnp.float32)
    got = ring_attention(q, k, v, m, "sp", variant="bidir")
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g = jax.grad(
        loss(lambda a, b, c: ring_attention(a, b, c, m, "sp", variant="bidir")),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c)), argnums=(0, 1, 2)
    )(q, k, v)
    assert g[1].shape == k.shape  # group already summed
    for a, b in zip(g, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize(
    "n_devices",
    [2, pytest.param(4, marks=pytest.mark.slow)],
)
def test_ring_performs_exactly_n_minus_1_kv_hops(n_devices):
    """The n−1-hop contract: the homeward K/V rotation is gone. With
    unroll=True the python-loop schedule (same body — numerically
    checked against the scan form in test_unroll_matches_scan) traces
    each hop individually, so the module's _HOP_LOG records real
    transfers per direction."""
    import collections

    import activemonitor_tpu.ops.ring_attention as ra

    m = submesh(n_devices)
    # unique shapes per case so cached traces can't swallow the log
    q, k, v = qkv(seq=4 * n_devices, batch=1, heads=2, head_dim=8 + n_devices)
    for variant in ("serial", "overlap", "bidir"):
        ra._HOP_LOG = log = []
        try:
            out = ring_attention(q, k, v, m, "sp", variant=variant, unroll=True)
        finally:
            ra._HOP_LOG = None
        assert bool(jnp.isfinite(out).all())
        hops = collections.Counter(log)
        assert hops[("k", "cw")] == n_devices - 1, (variant, hops)
        assert hops[("v", "cw")] == n_devices - 1, (variant, hops)
        if variant == "bidir":
            assert hops[("k", "ccw")] == n_devices - 1, hops
            assert hops[("v", "ccw")] == n_devices - 1, hops
        else:
            assert hops[("k", "ccw")] == 0, (variant, hops)


@pytest.mark.slow  # scan-vs-loop equivalence at n=4; n=2 hop test keeps the counter honest in tier-1
def test_unroll_matches_scan():
    """The python-loop schedule used for hop counting is the same
    computation as the lax.scan form — agreement to float tolerance
    (XLA's fusion/FMA choices differ once the loop is flat, so bitwise
    equality is not the contract here). bidir is the hairiest schedule
    (pre-loop hops, offset scan window, final in-place step) — if the
    two forms agree there, the simpler variants share the same driver."""
    m = submesh(4)
    q, k, v = qkv(seq=32, batch=1, heads=2, head_dim=16)
    rolled = ring_attention(q, k, v, m, "sp", variant="bidir")
    unrolled = ring_attention(q, k, v, m, "sp", variant="bidir", unroll=True)
    assert float(jnp.max(jnp.abs(rolled - unrolled))) < 1e-6


def test_backward_hop_budget():
    """Backward: K/V make n−1 hops per direction (prefetched under each
    gradient step) and the dK/dV accumulators make n — the n-th is the
    homeward hop that carries real gradients."""
    import collections

    import activemonitor_tpu.ops.ring_attention as ra

    n = 2
    m = submesh(n)
    q, k, v = qkv(seq=4 * n, batch=1, heads=2, head_dim=30)

    def loss(a, b, c):
        return jnp.sum(
            ring_attention(
                a, b, c, m, "sp", variant="overlap", unroll=True
            ).astype(jnp.float32) ** 2
        )

    ra._HOP_LOG = log = []
    try:
        jax.grad(loss, argnums=(0,))(q, k, v)
    finally:
        ra._HOP_LOG = None
    hops = collections.Counter(log)
    # forward ran once inside the VJP: n−1 K/V hops each way again
    assert hops[("k", "cw")] == 2 * (n - 1), hops
    assert hops[("v", "cw")] == 2 * (n - 1), hops
    assert hops[("dk", "cw")] == n, hops
    assert hops[("dv", "cw")] == n, hops


def test_bidir_rejects_unsplittable_shards():
    m = submesh(2)
    q = jnp.zeros((1, 2, 2, 8))  # 1 token per shard: nothing to halve
    with pytest.raises(ValueError, match="2 tokens per shard"):
        ring_attention(q, q, q, m, "sp", variant="bidir")
    with pytest.raises(ValueError, match="variant"):
        ring_attention(q, q, q, m, "sp", variant="nope")
