"""URL artifact reader (reference: internal/store/url.go:20-57).

Secure by default: TLS certificates are verified unless the spec
explicitly sets verifyCert: false (reference: url.go:29-32).
"""

from __future__ import annotations

import logging

import requests

from activemonitor_tpu.api.types import URLArtifact

log = logging.getLogger(__name__)

_TIMEOUT_SECONDS = 30.0


class URLReader:
    """Fetches a manifest over HTTP(S)."""

    def __init__(self, url_artifact: URLArtifact):
        if url_artifact is None or not url_artifact.path:
            raise ValueError("URLArtifact cannot be empty")
        self._artifact = url_artifact

    def read(self) -> bytes:
        # Only an explicit verifyCert: false disables verification.
        verify = self._artifact.verify_cert is not False
        if not verify:
            log.warning(
                "TLS certificate verification is disabled for %s", self._artifact.path
            )
        resp = requests.get(
            self._artifact.path, verify=verify, timeout=_TIMEOUT_SECONDS
        )
        if resp.status_code != 200:
            raise IOError(f"status code {resp.status_code}")
        return resp.content
