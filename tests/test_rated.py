"""probes/rated.py env-override parsing: the rated tables are the
denominator of every fraction-of-rated verdict, so a malformed override
must fall back to the table value with a warning — never crash a probe
or hand it a zero/negative/NaN denominator."""

import logging

import pytest

from activemonitor_tpu.probes.rated import _override, rated_for

ENV = "ACTIVEMONITOR_RATED_BF16_TFLOPS"


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    monkeypatch.delenv(ENV, raising=False)
    yield


def test_unset_env_uses_table_value():
    assert _override(197.0, ENV) == 197.0


def test_valid_override_applies(monkeypatch):
    monkeypatch.setenv(ENV, "210.5")
    assert _override(197.0, ENV) == 210.5


@pytest.mark.parametrize("raw", ["", "   "])
def test_empty_env_falls_back_silently(monkeypatch, raw, caplog):
    monkeypatch.setenv(ENV, raw)
    with caplog.at_level(logging.WARNING):
        assert _override(197.0, ENV) == 197.0
    assert caplog.records == []  # empty = unset, not an error


@pytest.mark.parametrize("raw", ["fast", "1.2.3", "12 tflops"])
def test_non_numeric_env_falls_back_with_warning(monkeypatch, raw, caplog):
    monkeypatch.setenv(ENV, raw)
    with caplog.at_level(logging.WARNING):
        assert _override(197.0, ENV) == 197.0
    assert any("not a number" in r.message for r in caplog.records)


@pytest.mark.parametrize("raw", ["-45", "0", "nan", "inf", "-inf"])
def test_nonpositive_or_nonfinite_env_falls_back_with_warning(
    monkeypatch, raw, caplog
):
    monkeypatch.setenv(ENV, raw)
    with caplog.at_level(logging.WARNING):
        assert _override(197.0, ENV) == 197.0
    assert any("positive and finite" in r.message for r in caplog.records)


def test_rated_for_survives_bad_override_end_to_end(monkeypatch, caplog):
    """The probe-facing entry point: a bad env never crashes rated_for
    and the returned spec carries the table figures."""
    monkeypatch.setenv(ENV, "garbage")
    monkeypatch.setenv("ACTIVEMONITOR_RATED_ICI_GBPS", "-1")
    with caplog.at_level(logging.WARNING):
        spec = rated_for("TPU v5 lite")
    assert spec is not None
    assert spec.bf16_tflops == 197.0
    assert spec.ici_unidir_gbps == 45.0
    assert len(caplog.records) >= 2


def test_rated_for_applies_good_override(monkeypatch):
    monkeypatch.setenv(ENV, "200")
    spec = rated_for("TPU v5 lite")
    assert spec.bf16_tflops == 200.0


def test_every_generation_has_a_finite_ridge_point():
    """ISSUE-9 satellite: the roofline ridge point (peak FLOP/s over
    HBM byte/s) must be derivable — positive and finite — for every
    generation in the table; it is the pivot of every bound
    classification (obs/roofline.py)."""
    import math

    from activemonitor_tpu.probes.rated import _RATED, ridge_point

    for _needle, spec in _RATED:
        ridge = spec.ridge_flops_per_byte
        assert math.isfinite(ridge) and ridge > 0, spec.generation
        assert ridge == spec.bf16_tflops * 1e12 / (spec.hbm_gbps * 1e9)
        assert ridge_point(spec) == ridge  # no override set


def test_ridge_point_override_follows_hbm_override(monkeypatch):
    """The ridge derives from the (already validated) bf16/HBM figures,
    so overriding the HBM bandwidth moves the ridge consistently; the
    direct ridge override then wins, with the same fallback rules."""
    from activemonitor_tpu.probes.rated import rated_for, ridge_point

    monkeypatch.setenv("ACTIVEMONITOR_RATED_HBM_GBPS", "1638")  # 2x v5e
    spec = rated_for("TPU v5 lite")
    assert spec.hbm_gbps == 1638.0
    assert spec.ridge_flops_per_byte == spec.bf16_tflops * 1e12 / 1638e9
    monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", "99.5")
    assert ridge_point(spec) == 99.5
    monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", "-4")
    assert ridge_point(spec) == spec.ridge_flops_per_byte


# -- capability_summary (the federation's capability card) -------------


def test_capability_summary_matches_the_rated_spec():
    from activemonitor_tpu.probes.rated import capability_summary, ridge_point

    card = capability_summary("TPU v5p")
    spec = rated_for("TPU v5p")
    assert card == {
        "generation": "v5p",
        "bf16_tflops": spec.bf16_tflops,
        "int8_tops": spec.int8_tops,
        "hbm_gbps": spec.hbm_gbps,
        "ici_unidir_gbps": spec.ici_unidir_gbps,
        "ici_links": spec.ici_links,
        "dcn_gbps": spec.dcn_gbps,
        "ridge_flops_per_byte": ridge_point(spec),
    }


def test_capability_summary_unknown_hardware_is_none():
    from activemonitor_tpu.probes.rated import capability_summary

    assert capability_summary("FPGA x1") is None
    assert capability_summary("") is None


def test_capability_summary_applies_validated_env_overrides(
    monkeypatch, caplog
):
    from activemonitor_tpu.probes.rated import capability_summary

    monkeypatch.setenv(ENV, "500")
    assert capability_summary("TPU v5e")["bf16_tflops"] == 500.0
    # a malformed override falls back to the table figure, warned —
    # the federation's routing denominators get the same validation
    # the probe verdict denominators do
    monkeypatch.setenv(ENV, "garbage")
    with caplog.at_level(logging.WARNING):
        card = capability_summary("TPU v5e")
    assert card["bf16_tflops"] == 197.0
    assert any("not a number" in r.message for r in caplog.records)
