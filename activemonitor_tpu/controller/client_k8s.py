"""Kubernetes-backed HealthCheck client — cluster mode.

Watches HealthCheck CRs through the API server exactly as the reference
controller does (reference: cached client + status subresource writes,
healthcheck_controller.go:175,208-215,1445-1462). Import of the
``kubernetes`` package is deferred to construction so the rest of the
framework works where it isn't installed.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import AsyncIterator, List, Optional

from activemonitor_tpu import GROUP, VERSION

from activemonitor_tpu.errors import MissingDependencyError
from activemonitor_tpu.api.types import HealthCheck
from activemonitor_tpu.controller.client import (
    ConflictError,
    NotFoundError,
    WatchEvent,
)

log = logging.getLogger(__name__)

PLURAL = "healthchecks"


class KubernetesHealthCheckClient:
    def __init__(self, api_client=None):  # pragma: no cover - needs a cluster
        try:
            from kubernetes import client, config  # type: ignore
        except ImportError as e:
            raise MissingDependencyError(
                "the 'kubernetes' package is required for cluster mode; "
                "use the file-backed store (--client file) instead"
            ) from e
        if api_client is None:
            try:
                config.load_incluster_config()
            except Exception:
                config.load_kube_config()
        self._api = client.CustomObjectsApi(api_client)

    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]:
        from kubernetes.client.rest import ApiException  # type: ignore

        try:
            obj = await asyncio.to_thread(
                self._api.get_namespaced_custom_object,
                GROUP,
                VERSION,
                namespace,
                PLURAL,
                name,
            )
        except ApiException as e:
            if e.status == 404:
                return None
            raise
        return HealthCheck.from_dict(obj)

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]:
        if namespace:
            raw = await asyncio.to_thread(
                self._api.list_namespaced_custom_object,
                GROUP,
                VERSION,
                namespace,
                PLURAL,
            )
        else:
            raw = await asyncio.to_thread(
                self._api.list_cluster_custom_object, GROUP, VERSION, PLURAL
            )
        return [HealthCheck.from_dict(item) for item in raw.get("items", [])]

    async def apply(self, hc: HealthCheck) -> HealthCheck:
        from kubernetes.client.rest import ApiException  # type: ignore

        body = hc.to_dict()
        body.pop("status", None)
        try:
            created = await asyncio.to_thread(
                self._api.create_namespaced_custom_object,
                GROUP,
                VERSION,
                hc.metadata.namespace,
                PLURAL,
                body,
            )
        except ApiException as e:
            if e.status != 409:
                raise
            created = await asyncio.to_thread(
                self._api.patch_namespaced_custom_object,
                GROUP,
                VERSION,
                hc.metadata.namespace,
                PLURAL,
                hc.metadata.name,
                {"spec": body.get("spec", {})},
            )
        return HealthCheck.from_dict(created)

    async def update_status(self, hc: HealthCheck) -> HealthCheck:
        from kubernetes.client.rest import ApiException  # type: ignore

        body = {
            "metadata": {"resourceVersion": hc.metadata.resource_version or None},
            "status": hc.status.to_json_dict(),
        }
        try:
            updated = await asyncio.to_thread(
                self._api.patch_namespaced_custom_object_status,
                GROUP,
                VERSION,
                hc.metadata.namespace,
                PLURAL,
                hc.metadata.name,
                body,
            )
        except ApiException as e:
            if e.status == 409:
                raise ConflictError(hc.key) from e
            if e.status == 404:
                raise NotFoundError(hc.key) from e
            raise
        return HealthCheck.from_dict(updated)

    async def delete(self, namespace: str, name: str) -> None:
        from kubernetes.client.rest import ApiException  # type: ignore

        try:
            await asyncio.to_thread(
                self._api.delete_namespaced_custom_object,
                GROUP,
                VERSION,
                namespace,
                PLURAL,
                name,
            )
        except ApiException as e:
            if e.status == 404:
                raise NotFoundError(f"{namespace}/{name}") from e
            raise

    def watch(self) -> AsyncIterator[WatchEvent]:
        """API-server watch pumped from a thread into an asyncio queue.
        The stream (and its registration) starts at call time."""
        from kubernetes import watch as k8s_watch  # type: ignore

        loop = asyncio.get_event_loop()
        queue: asyncio.Queue = asyncio.Queue()
        stop = threading.Event()

        def pump() -> None:
            while not stop.is_set():
                w = k8s_watch.Watch()
                try:
                    for event in w.stream(
                        self._api.list_cluster_custom_object,
                        GROUP,
                        VERSION,
                        PLURAL,
                        timeout_seconds=300,
                    ):
                        obj = event.get("object", {})
                        meta = obj.get("metadata", {})
                        loop.call_soon_threadsafe(
                            queue.put_nowait,
                            WatchEvent(
                                type=event.get("type", "MODIFIED"),
                                namespace=meta.get("namespace", ""),
                                name=meta.get("name", ""),
                            ),
                        )
                except Exception:
                    log.exception("watch stream broke; re-establishing")
                    stop.wait(1.0)

        thread = threading.Thread(target=pump, daemon=True, name="hc-watch")
        thread.start()

        async def gen() -> AsyncIterator[WatchEvent]:
            try:
                while True:
                    yield await queue.get()
            finally:
                stop.set()

        return gen()
