"""Self-signed certificate generation for the metrics endpoint.

The reference serves metrics on :8443 secure-by-default, falling back
to a generated self-signed certificate when none is supplied
(reference: cmd/main.go:74-81 via controller-runtime's metrics-server
self-signed fallback). Same contract here, via ``cryptography``.
"""

from __future__ import annotations

import datetime
import ssl
import tempfile
from typing import Tuple


def generate_self_signed_cert(
    common_name: str = "active-monitor-tpu-metrics", days: int = 365
) -> Tuple[bytes, bytes]:
    """Returns (cert_pem, key_pem) for an ephemeral self-signed cert."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName("localhost"), x509.DNSName(common_name)]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


def server_ssl_context(cert_file: str = "", key_file: str = "") -> ssl.SSLContext:
    """An SSLContext from the given PEM files, or from a freshly
    generated self-signed pair when none are supplied."""
    from activemonitor_tpu.errors import ConfigurationError

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    if bool(cert_file) != bool(key_file):
        # silently serving a self-signed cert instead of the operator's
        # half-supplied pair would fail Prometheus verification with no
        # hint of the misconfiguration (Manager validates this earlier;
        # kept here for direct callers)
        raise ConfigurationError(
            "metrics TLS needs BOTH --metrics-cert-file and "
            "--metrics-key-file (got only one)"
        )
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
        return ctx
    cert_pem, key_pem = generate_self_signed_cert()
    # load_cert_chain only takes paths; stage the ephemeral pair
    with tempfile.NamedTemporaryFile(suffix=".pem") as cert_tmp, \
            tempfile.NamedTemporaryFile(suffix=".pem") as key_tmp:
        cert_tmp.write(cert_pem)
        cert_tmp.flush()
        key_tmp.write(key_pem)
        key_tmp.flush()
        ctx.load_cert_chain(cert_tmp.name, key_tmp.name)
    return ctx
