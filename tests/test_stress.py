"""Concurrency stress — the race-detection tier (SURVEY.md §5.2).

The reference relies on manual lock discipline and leaves one
documented race; here scheduler state is single-owner on the event
loop, so the invariants under load are: no cross-check contamination,
no lost or duplicated runs, no concurrent reconcile of one key.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine, fail_after, succeed_after
from activemonitor_tpu.metrics import MetricsCollector

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"

N_CHECKS = 40


def make_hc(i: int):
    # odd checks fail, even succeed — cross-contamination would show up
    # as wrong counters on either side
    return HealthCheck.from_dict(
        {
            "metadata": {"name": f"stress-{i:03d}", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 3600,
                "level": "cluster",
                "workflow": {
                    "generateName": f"stress-{i:03d}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": f"sa-{i:03d}",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


@pytest.mark.asyncio
async def test_many_checks_under_concurrent_reconciles():
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    for i in range(1, N_CHECKS, 2):
        engine.on_prefix(f"stress-{i:03d}-", fail_after(1, f"fail-{i:03d}"))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(capacity=100000),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        # apply all checks concurrently + storm duplicate events
        await asyncio.gather(*(client.apply(make_hc(i)) for i in range(N_CHECKS)))
        for _ in range(3):
            for i in range(N_CHECKS):
                manager.enqueue("health", f"stress-{i:03d}")
            await asyncio.sleep(0.01)

        async def settled():
            for _ in range(400):
                await asyncio.sleep(0.025)
                done = 0
                for i in range(N_CHECKS):
                    hc = await client.get("health", f"stress-{i:03d}")
                    if hc.status.total_healthcheck_runs >= 1:
                        done += 1
                if done == N_CHECKS:
                    return True
            return False

        assert await settled(), "not all checks completed a run"
        await reconciler.wait_watches()

        for i in range(N_CHECKS):
            hc = await client.get("health", f"stress-{i:03d}")
            if i % 2:
                assert hc.status.status == "Failed", i
                assert hc.status.failed_count == 1, (i, hc.status)
                assert hc.status.error_message == f"fail-{i:03d}", i
                assert hc.status.success_count == 0, i
            else:
                assert hc.status.status == "Succeeded", i
                assert hc.status.success_count == 1, (i, hc.status)
                assert hc.status.failed_count == 0, i
            # exactly one workflow per check despite the event storm
            prefix = f"stress-{i:03d}-"
            count = sum(
                1
                for wf in engine.submitted
                if wf["metadata"]["generateName"] == prefix
            )
            assert count == 1, (i, count)
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_interleaved_apply_delete_storm():
    """Rapid create/delete cycles must end clean: no timers or watches
    left for deleted checks, no crash."""
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        for cycle in range(5):
            await asyncio.gather(*(client.apply(make_hc(i)) for i in range(10)))
            await asyncio.sleep(0.05)
            for i in range(10):
                try:
                    await client.delete("health", f"stress-{i:03d}")
                except Exception:
                    pass
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)
        await reconciler.wait_watches()
        # all deleted: no pending timers may survive
        for i in range(10):
            assert not reconciler.timers.pending(f"health/stress-{i:03d}")
    finally:
        await manager.stop()
