"""API types for the HealthCheck resource (group activemonitor.keikoproj.io/v1alpha1)."""

from activemonitor_tpu.api.types import (
    AnalysisSpec,
    ArtifactLocation,
    FileArtifact,
    HealthCheck,
    HealthCheckList,
    HealthCheckSpec,
    HealthCheckStatus,
    ObjectMeta,
    OwnerReference,
    PolicyRule,
    RemedyWorkflow,
    ResourceObject,
    ScheduleSpec,
    SLOSpec,
    URLArtifact,
    Workflow,
)

__all__ = [
    "AnalysisSpec",
    "ArtifactLocation",
    "FileArtifact",
    "HealthCheck",
    "HealthCheckList",
    "HealthCheckSpec",
    "HealthCheckStatus",
    "ObjectMeta",
    "OwnerReference",
    "PolicyRule",
    "RemedyWorkflow",
    "ResourceObject",
    "ScheduleSpec",
    "SLOSpec",
    "URLArtifact",
    "Workflow",
]
