"""Leader election — multi-replica controller safety.

The reference gets HA via controller-runtime's Lease-based leader
election (reference: cmd/main.go:87-88, election ID
"689451f8.keikoproj.io"). Equivalents here:

- :class:`FileLeaderElector` — flock-based, for multiple controller
  processes sharing a host/volume (the local deployment mode).
- :class:`KubernetesLeaseElector` — coordination.k8s.io/v1 Lease
  objects with continuous renewal, preconditioned takeover and a
  ``lost`` signal, on the native REST layer (activemonitor_tpu.kube).
- :class:`AlwaysLeader` — single-replica default (election off, like
  the reference's default ``--leader-elect=false``).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Protocol

from activemonitor_tpu.kube import ApiError, api_path
from activemonitor_tpu.utils.clock import micro_time

log = logging.getLogger("activemonitor.leader")

ELECTION_ID = "689451f8.keikoproj.io"  # parity with the reference


class LeaderElector(Protocol):
    async def acquire(self) -> None:
        """Blocks until this process holds leadership."""
        ...

    def release(self) -> None: ...


class AlwaysLeader:
    async def acquire(self) -> None:
        return None

    def release(self) -> None:
        return None


class FileLeaderElector:
    """flock-based election for co-hosted replicas."""

    def __init__(self, path: str = "", poll_seconds: float = 1.0):
        self._path = path or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"activemonitor-{ELECTION_ID}.lock"
        )
        self._poll = poll_seconds
        self._fd = None

    async def acquire(self) -> None:
        import fcntl

        self._fd = open(self._path, "w")
        while True:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fd.write(str(os.getpid()))
                self._fd.flush()
                return
            except BlockingIOError:
                await asyncio.sleep(self._poll)

    def release(self) -> None:
        if self._fd is not None:
            import fcntl

            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                self._fd.close()
                self._fd = None


class KubernetesLeaseElector:
    """coordination.k8s.io Lease election with continuous renewal.

    Semantics match controller-runtime's leaderelection (the reference's
    HA mode, cmd/main.go:87-88): the winner renews ``spec.renewTime``
    every ``lease_seconds/3``; challengers take over only when the lease
    has not been renewed for ``lease_seconds``; every takeover/renewal
    PUT replays the resourceVersion it just read, so the API server
    rejects the loser of any write race with a 409 and two challengers
    can never both win the same takeover; on renewal failure or holder
    change the
    :attr:`lost` event fires and the manager must stop reconciling
    (the reference terminates the process)."""

    LEASE_GROUP = "coordination.k8s.io"
    LEASE_VERSION = "v1"
    LEASE_PLURAL = "leases"

    def __init__(
        self,
        api=None,
        namespace: str = "health",
        name: str = ELECTION_ID,
        identity: str = "",
        lease_seconds: float = 15.0,
        clock=None,
        annotations=None,
        takeover_grace: float = 0.0,
    ):
        import socket
        import uuid

        from activemonitor_tpu.utils.clock import Clock

        if api is None:
            from activemonitor_tpu.kube import KubeApi

            api = KubeApi.from_default_config()
        self._api = api
        self._namespace = namespace
        self._name = name
        self._identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self._lease_seconds = float(lease_seconds)
        self._clock = clock or Clock()
        # optional zero-arg callable merged into lease metadata.annotations
        # on every write — rides the renewal PUT (a separate PATCH would
        # race the renew loop's GET→PUT into a self-inflicted conflict).
        # The sharded fleet publishes workqueue depth through this.
        self._annotations = annotations
        # extra staleness beyond lease_seconds before an EXPIRED holder
        # is taken over. The sharded fleet hands every non-home standby
        # one lease of grace so a prioritized claimant — the shard's
        # restarted home replica, which contends with zero grace — wins
        # the reclaim race whenever it comes back within the grace
        # window.
        self._takeover_grace = float(takeover_grace)
        # a RELINQUISHED lease (empty holder: voluntary shed or home-
        # return) is taken at once by a zero-grace claimant, but graced
        # standbys sit out this shorter vacancy window first — longer
        # than the prioritized claimant's poll period (lease/3), so a
        # home-return relinquish deterministically lands HOME instead of
        # on whichever peer polls first (each miss would cost a full
        # adoption resync plus another return hop)
        self._vacancy_grace = min(
            self._takeover_grace, self._lease_seconds / 2.0
        )
        self._stop = False
        self._acquired = False
        self._renew_task = None
        self._relinquish_task = None
        self.lost = asyncio.Event()
        # fencing token: the lease resourceVersion after OUR last
        # successful write, and when it landed (monotonic). A paused
        # holder whose token no longer matches the server has been
        # taken over — the sharding layer rejects its late writes.
        self.fence_rv: str = ""
        self.last_write: float = 0.0

    # -- lease plumbing -------------------------------------------------
    def _path(self) -> str:
        return api_path(
            self.LEASE_GROUP, self.LEASE_VERSION, self.LEASE_PLURAL,
            self._namespace, self._name,
        )

    @property
    def path(self) -> str:
        """The lease object's REST path (fence verification reads it)."""
        return self._path()

    def _note_write(self, obj: dict) -> None:
        """Record the fencing token from a successful lease write."""
        rv = ((obj or {}).get("metadata") or {}).get("resourceVersion")
        if rv:
            self.fence_rv = str(rv)
        self.last_write = self._clock.monotonic()

    def _apply_annotations(self, obj: dict) -> None:
        if self._annotations is None:
            return
        try:
            extra = self._annotations() or {}
        except Exception:
            log.exception("lease annotations callback failed")
            return
        if extra:
            meta = obj.setdefault("metadata", {})
            merged = dict(meta.get("annotations") or {})
            merged.update({str(k): str(v) for k, v in extra.items()})
            meta["annotations"] = merged

    def _collection_path(self) -> str:
        return api_path(
            self.LEASE_GROUP, self.LEASE_VERSION, self.LEASE_PLURAL, self._namespace
        )

    def _spec(self, acquire_time: str = "") -> dict:
        spec = {
            "holderIdentity": self._identity,
            "leaseDurationSeconds": int(self._lease_seconds),
            "renewTime": micro_time(self._clock.now()),
        }
        if acquire_time:
            spec["acquireTime"] = acquire_time
        return spec

    # -- election -------------------------------------------------------
    async def acquire(self) -> None:
        """Blocks until this process holds the lease, then starts the
        background renewal loop. Every API failure here is transient by
        definition — a candidate has nothing to lose by retrying, so
        it never crashes the process (an unreachable API server during
        a rollout must not kill a standby replica).

        Expiry is timed from the moment THIS process last observed the
        lease record change (resourceVersion movement on our own clock),
        never from the holder's renewTime wall-clock timestamp — a
        leader on a skewed clock must not look expired while it is
        renewing fine (controller-runtime does the same)."""
        observed_rv: str | None = None
        observed_at = 0.0
        absent_since: float | None = None
        while not self._stop:
            try:
                try:
                    existing = await self._api.get(self._path())
                    absent_since = None
                except ApiError as e:
                    if not e.not_found:
                        raise
                    if self._takeover_grace > 0:
                        # a graced contender must not win the CREATE race
                        # either: at cold boot every shard lease is 404
                        # and the prioritized claimant (the home replica,
                        # grace 0) gets first crack at creating it — only
                        # after a full grace of continuous absence does a
                        # standby conclude nobody prioritized is coming
                        now = self._clock.monotonic()
                        if absent_since is None:
                            absent_since = now
                        if now - absent_since < self._takeover_grace:
                            await self._clock.sleep(self._lease_seconds / 3)
                            continue
                    # no lease yet: create it (a losing racer sees 409)
                    body = {
                        "apiVersion": f"{self.LEASE_GROUP}/{self.LEASE_VERSION}",
                        "kind": "Lease",
                        "metadata": {"name": self._name, "namespace": self._namespace},
                        "spec": self._spec(
                            acquire_time=micro_time(self._clock.now())
                        ),
                    }
                    self._apply_annotations(body)
                    try:
                        created = await self._api.create(
                            self._collection_path(), body
                        )
                        self._note_write(created)
                        self._start_renewal()
                        return
                    except ApiError as e2:
                        if not e2.conflict:
                            raise
                        continue  # somebody else created it; evaluate theirs
                spec = existing.get("spec", {}) or {}
                rv = (existing.get("metadata") or {}).get("resourceVersion")
                if not spec.get("holderIdentity") or not spec.get("renewTime"):
                    # relinquished or never renewed: immediate for a
                    # zero-grace claimant, one vacancy window for graced
                    # standbys (see _vacancy_grace above)
                    if self._vacancy_grace <= 0:
                        expired = True
                    else:
                        if rv != observed_rv:
                            observed_rv = rv
                            observed_at = self._clock.monotonic()
                        expired = (
                            self._clock.monotonic() - observed_at
                            >= self._vacancy_grace
                        )
                elif rv != observed_rv:
                    # the record moved: the holder is alive; restart OUR
                    # local staleness window
                    observed_rv, observed_at = rv, self._clock.monotonic()
                    expired = False
                else:
                    expired = (
                        self._clock.monotonic() - observed_at
                        > self._lease_seconds + self._takeover_grace
                    )
                if spec.get("holderIdentity") == self._identity or expired:
                    # preconditioned takeover: the PUT carries the
                    # resourceVersion just read, so if another challenger
                    # won the race this write turns into a 409
                    existing["spec"] = self._spec(
                        acquire_time=micro_time(self._clock.now())
                    )
                    self._apply_annotations(existing)
                    try:
                        updated = await self._api.replace(self._path(), existing)
                    except ApiError as e:
                        if not e.conflict:
                            raise
                        continue
                    self._note_write(updated)
                    self._start_renewal()
                    return
            except asyncio.CancelledError:
                raise
            except ApiError as e:
                if e.status in (401, 403):
                    # deterministic misconfiguration — retried for parity
                    # with controller-runtime, but LOUD: the operator must
                    # see why this replica never becomes leader
                    log.error(
                        "election blocked by the API server (%s): check the "
                        "controller's RBAC on leases in namespace %r and its "
                        "credentials; retrying",
                        e, self._namespace,
                    )
                else:
                    log.warning("election attempt failed (%s); retrying", e)
                await self._clock.sleep(self._lease_seconds / 3)
                continue
            except Exception as e:
                # includes credential-plugin hiccups (STS throttling, a
                # slow gcloud): a standby has nothing to lose by retrying,
                # and a deterministic breakage just keeps logging loudly
                log.warning("election attempt failed (%s); retrying", e)
                await self._clock.sleep(1.0)
                continue
            await self._clock.sleep(self._lease_seconds / 3)
        # released/stopped while standing by: falling through as if the
        # lease was won would let the caller reconcile without leadership
        raise asyncio.CancelledError("elector stopped before acquiring the lease")

    def _start_renewal(self) -> None:
        self._acquired = True
        self.lost.clear()
        self._renew_task = asyncio.create_task(self._renew_loop())
        # safety net: if the renew task ever dies with an unexpected
        # exception, leadership is no longer being maintained — that IS
        # lost leadership, never a silent no-op
        self._renew_task.add_done_callback(self._renew_done)

    def _renew_done(self, task) -> None:
        if task.cancelled() or self._stop:
            return
        exc = task.exception()
        if exc is not None:
            log.error("renewal task died (%s); leadership lost", exc)
            self.lost.set()

    async def _renew_loop(self) -> None:
        """Re-write renewTime every lease_seconds/3. Transient failures
        are retried only until the renew deadline (2/3 of the lease,
        controller-runtime's renewDeadline<leaseDuration ratio): the
        holder steps down strictly BEFORE any challenger's takeover
        window opens, so two active leaders are impossible. A holder
        change observed mid-renewal also declares leadership lost."""
        renew_deadline = self._lease_seconds * 2.0 / 3.0
        # after a failed attempt, retry on a SHORT cadence (controller-
        # runtime's RetryPeriod idea): sleeping a full lease/3 between
        # failures would burn the whole renew budget on a single blip
        retry_period = min(2.0, self._lease_seconds / 6.0)
        last_renew = self._clock.monotonic()
        delay = self._lease_seconds / 3
        while not self._stop:
            await self._clock.sleep(delay)
            if self._stop:
                return
            # every request's HTTP time is capped by what's left of the
            # renew deadline (recomputed per request — GET and PUT share
            # one budget): a black-holed connection must not let a stale
            # leader keep reconciling into a challenger's takeover window
            # (KubeApi's default 30 s > the 10 s deadline)
            def remaining() -> float:
                return renew_deadline - (self._clock.monotonic() - last_renew)

            if remaining() <= 0:
                log.error("renew deadline exceeded; leadership lost")
                self.lost.set()
                return
            try:
                existing = await self._api.request(
                    "GET", self._path(), timeout=remaining()
                )
                spec = existing.get("spec", {}) or {}
                if spec.get("holderIdentity") != self._identity:
                    log.error(
                        "lease %s/%s taken over by %r; leadership lost",
                        self._namespace, self._name, spec.get("holderIdentity"),
                    )
                    self.lost.set()
                    return
                if remaining() <= 0:
                    log.error("renew deadline exceeded; leadership lost")
                    self.lost.set()
                    return
                spec["renewTime"] = micro_time(self._clock.now())
                existing["spec"] = spec
                self._apply_annotations(existing)
                updated = await self._api.request(
                    "PUT", self._path(), body=existing, timeout=remaining()
                )
                self._note_write(updated)
                last_renew = self._clock.monotonic()
                delay = self._lease_seconds / 3
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if isinstance(e, ApiError) and e.conflict:
                    # a resourceVersion conflict mid-renew means another
                    # holder replaced the lease between our GET and PUT:
                    # leadership is ALREADY gone. Demote immediately —
                    # retrying the renew would fight the new holder for
                    # up to the whole renew deadline while this replica
                    # keeps reconciling (split-brain window).
                    log.error(
                        "lease %s/%s renewal hit a resourceVersion conflict "
                        "(another holder took over); leadership lost",
                        self._namespace, self._name,
                    )
                    self.lost.set()
                    return
                # ANY other failure (aiohttp's ServerDisconnectedError is
                # not an OSError) is transient only until the renew deadline
                if self._clock.monotonic() - last_renew >= renew_deadline:
                    log.error("lease renewal failing (%s); leadership lost", e)
                    self.lost.set()
                    return
                log.warning("lease renewal attempt failed (%s); retrying", e)
                delay = retry_period

    def demote(self) -> None:
        """Externally-driven demotion (the shard fence's verdict): stop
        renewing and declare leadership lost, without relinquishing —
        the lease already belongs to someone else. One owner for the
        transition: the renew loop's three self-demote paths and this
        entry point share the same stop-renewing-then-signal shape, so
        a fenced elector can never keep renewing behind its
        replacement's back (two renew loops 409-dueling forever)."""
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        self.lost.set()

    def release(self) -> None:
        """Stop renewing and relinquish the lease so a standby takes
        over immediately instead of waiting out the duration. Callers
        that can await should prefer :meth:`release_async` — the
        fire-and-forget task spawned here loses the handoff race if the
        event loop (or the shared API session) shuts down right after."""
        self._stop = True
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        # best-effort async relinquish; fine if the loop is shutting
        # down. The strong reference matters: the loop tracks tasks by
        # weakref only, and an unreferenced task can be GC'd unrun.
        try:
            self._relinquish_task = asyncio.get_running_loop().create_task(
                self._relinquish()
            )
        except RuntimeError:
            pass

    async def release_async(self) -> None:
        """Like :meth:`release`, but the lease is relinquished before
        returning — use during orderly shutdown, before closing the
        underlying API session."""
        self.release()
        if self._relinquish_task is not None:
            await self._relinquish_task

    async def _relinquish(self) -> None:
        if not self._acquired:
            # a standby never held the lease: nothing to hand over, and
            # a doomed GET would only stall shutdown
            return
        try:
            # short timeouts: this runs during shutdown and must finish
            # inside a pod's termination grace period even when the API
            # server is unreachable
            existing = await self._api.request("GET", self._path(), timeout=5)
            spec = existing.get("spec", {}) or {}
            if spec.get("holderIdentity") != self._identity:
                return
            spec["holderIdentity"] = ""
            existing["spec"] = spec
            await self._api.request("PUT", self._path(), body=existing, timeout=5)
        except asyncio.CancelledError:
            raise
        except Exception:
            # genuinely best-effort: network failures here (including
            # aiohttp ClientErrors, which are not OSErrors) must never
            # crash an orderly shutdown — the lease just expires instead
            log.debug("lease relinquish failed; standby waits out the lease")
