"""Planet-scale federation (ISSUE 19).

Units for the cluster registry (capability descriptors, movement-judged
liveness, exactly-one flight bundle per transition), capability-aware
routing (slice / tightest-fit / default spread / structured
``no_capable_cluster``), the federated rollup (two-level merge,
attribution conservation under version skew), the global front door
(global quota, cross-cluster coalescing, the ``forwarded`` column, the
per-(tenant, cluster) conservation ledger), the federation plane +
/statusz block + CLI rendering, and the scripted FakeClock acceptance.
"""

import asyncio

import pytest

from activemonitor_tpu.federation import (
    FEDERATION_TENANT,
    NO_CAPABLE_CLUSTER,
    OUTCOME_FORWARDED,
    STATE_HEALTHY,
    STATE_UNHEALTHY,
    CapabilityRouter,
    ClusterDescriptor,
    ClusterRegistry,
    FederationPlane,
    GlobalFrontDoor,
    Requirement,
    federate_statusz,
    federation_quota,
)
from activemonitor_tpu.federation.globaldoor import (
    REFUSE_CLUSTER_UNATTACHED,
    UNROUTED_CLUSTER,
)
from activemonitor_tpu.federation.registry import (
    KIND_CLUSTER_JOIN,
    KIND_CLUSTER_LEAVE,
    KIND_CLUSTER_RECOVERED,
    KIND_CLUSTER_UNHEALTHY,
)
from activemonitor_tpu.federation.routing import (
    MATCHED_CAPABILITY,
    MATCHED_DEFAULT,
    MATCHED_SLICE,
    _chips_in,
)
from activemonitor_tpu.frontdoor import (
    OUTCOME_JOINED,
    OUTCOME_REFUSED,
    OUTCOME_RUN,
    REFUSE_QUOTA,
    REFUSE_TENANT_CAPACITY,
    AdmissionController,
    FrontDoor,
    TenantQuota,
)
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs.flightrec import FlightRecorder
from activemonitor_tpu.obs.history import ResultHistory
from activemonitor_tpu.obs.slo import (
    MERGE_LEVEL_CLUSTER,
    MERGE_LEVEL_REPLICA,
    merge_blocks,
)
from activemonitor_tpu.utils.clock import FakeClock


def desc(name, device_kind="TPU v5e", chips=16, topology="4x4",
         slices=(), dcn_gbps=0.0, url=""):
    return ClusterDescriptor.build(
        name,
        url=url,
        device_kind=device_kind,
        chips=chips,
        topology=topology,
        slices=slices,
        dcn_gbps=dcn_gbps,
    )


def cluster_payload(generated_at, *, ratio=1.0, runs=10, old_binary=False,
                    checks=()):
    """A minimal replica-shaped /statusz payload. ``old_binary`` drops
    the goodput attribution block — the version-skew shape a pre-
    attribution binary serves."""
    lost = 1.0 - ratio
    fleet = {
        "replicas": 1,
        "checks": len(checks),
        "window_runs": runs,
        "goodput_ratio": ratio,
        "generated_at": generated_at,
        "degraded": False,
        "breaker": {"state": "closed"},
        "status_writes_queued": 0,
        "remedy_tokens": None,
    }
    if not old_binary:
        fleet["goodput"] = {
            "window_runs": runs,
            "lost_runs": {"ici": runs * lost},
            "attribution": {"ici": lost},
            "lost_ratio": lost,
            "top": "ici" if lost > 0 else None,
        }
    return {
        "fleet": fleet,
        "checks": [
            {
                "key": key,
                "namespace": key.split("/")[0],
                "healthcheck": key.split("/")[1],
                "window": {"results": runs},
            }
            for key in checks
        ],
    }


# -- descriptors -------------------------------------------------------


def test_descriptor_derives_capability_card_from_rated_tables():
    d = desc("c1", device_kind="TPU v5p", chips=64, topology="4x4x4")
    assert d.generation == "v5p"
    assert d.capability["bf16_tflops"] == pytest.approx(459.0)
    # the rated dcn tier is the default...
    assert d.dcn_gbps == pytest.approx(25.0)
    # ...and an explicit per-host figure wins over it
    fat = desc("c2", device_kind="TPU v5p", dcn_gbps=100.0)
    assert fat.dcn_gbps == pytest.approx(100.0)
    # unknown hardware: no card, no generation — still a valid member
    weird = desc("c3", device_kind="FPGA x1")
    assert weird.generation == ""
    assert weird.capability == {}


# -- registry: movement-judged liveness --------------------------------


def test_join_and_leave_fire_exactly_one_bundle_each():
    clock = FakeClock()
    flightrec = FlightRecorder(clock)
    registry = ClusterRegistry(clock=clock, flightrec=flightrec)
    registry.join(desc("east"))
    registry.join(desc("west"))
    assert len(flightrec.bundles(kind=KIND_CLUSTER_JOIN)) == 2
    registry.leave("east")
    registry.leave("east")  # already gone: no second bundle
    assert len(flightrec.bundles(kind=KIND_CLUSTER_LEAVE)) == 1
    assert registry.names() == ["west"]


@pytest.mark.asyncio
async def test_liveness_is_observed_movement_not_remote_wallclock():
    """Health is judged by whether the remote's payload MOVES as seen
    on our monotonic clock — a remote stamping absurd future times
    cannot fake liveness by the size of its stamps, and a frozen
    payload goes unhealthy no matter what its stamp claims."""
    clock = FakeClock()
    flightrec = FlightRecorder(clock)
    registry = ClusterRegistry(
        clock=clock, liveness_seconds=90.0, flightrec=flightrec
    )
    registry.join(desc("moving"))
    registry.join(desc("frozen"))
    # "frozen" serves one payload with a HUGE wall-clock stamp, then
    # freezes; "moving" serves small but CHANGING stamps
    assert registry.observe("frozen", cluster_payload(9e12))
    step = 0
    for _ in range(4):
        await clock.advance(30.0)
        step += 1
        assert registry.observe("moving", cluster_payload(100.0 + step))
        # same stamp again: not movement
        assert not registry.observe("frozen", cluster_payload(9e12))
        registry.sweep()
    assert registry.state("moving") == STATE_HEALTHY
    assert registry.state("frozen") == STATE_UNHEALTHY
    # exactly ONE unhealthy bundle despite four sweeps past the window
    assert len(flightrec.bundles(kind=KIND_CLUSTER_UNHEALTHY)) == 1


@pytest.mark.asyncio
async def test_recovery_fires_one_bundle_and_restores_routing():
    clock = FakeClock()
    flightrec = FlightRecorder(clock)
    registry = ClusterRegistry(
        clock=clock, liveness_seconds=90.0, flightrec=flightrec
    )
    registry.join(desc("east"))
    await clock.advance(90.0)
    assert registry.sweep() == [("east", KIND_CLUSTER_UNHEALTHY)]
    assert registry.healthy() == []
    # movement recovers it — exactly one recovered bundle
    assert registry.observe("east", cluster_payload(1.0))
    assert registry.state("east") == STATE_HEALTHY
    assert registry.observe("east", cluster_payload(2.0))
    assert len(flightrec.bundles(kind=KIND_CLUSTER_RECOVERED)) == 1
    assert [d.name for d in registry.healthy()] == ["east"]


@pytest.mark.asyncio
async def test_registry_snapshot_and_metrics_export():
    clock = FakeClock()
    metrics = MetricsCollector()
    registry = ClusterRegistry(
        clock=clock, liveness_seconds=90.0, metrics=metrics
    )
    registry.join(desc("east", device_kind="TPU v5e"))
    registry.join(desc("west", device_kind="TPU v5p", chips=64,
                       topology="4x4x4"))
    registry.observe("east", cluster_payload(10.0))
    await clock.advance(120.0)
    registry.observe("west", cluster_payload(20.0))
    registry.sweep()
    snap = registry.snapshot()
    assert snap["healthy"] == 1 and snap["unhealthy"] == 1
    assert snap["clusters"]["east"]["state"] == STATE_UNHEALTHY
    assert snap["clusters"]["west"]["generation"] == "v5p"
    assert snap["clusters"]["west"]["movement_age_seconds"] == pytest.approx(0.0)
    registry.export_metrics()  # exercises the gauges; families pinned
    # unhealthy clusters keep serving their LAST payload to the rollup
    assert set(registry.payloads()) == {"east", "west"}


# -- capability-aware routing ------------------------------------------


def _registry_with(clock, *descriptors):
    registry = ClusterRegistry(clock=clock, liveness_seconds=90.0)
    for d in descriptors:
        registry.join(d)
    return registry


def test_topology_chip_math():
    assert _chips_in("4x4") == 16
    assert _chips_in("2x2x4") == 16
    assert _chips_in("") == 0
    assert _chips_in("4xbanana") == 0  # malformed must not match big pods
    assert _chips_in("0x4") == 0


def test_slice_ownership_wins_over_capability():
    clock = FakeClock()
    registry = _registry_with(
        clock,
        desc("edge", device_kind="TPU v5e", slices=("train-pod-a",)),
        desc("big", device_kind="TPU v5p", chips=64, topology="4x4x4"),
    )
    router = CapabilityRouter(registry)
    decision = router.route(
        "bench/hc", Requirement(generation="v5e", slice_name="train-pod-a")
    )
    assert decision.routed and decision.cluster == "edge"
    assert decision.matched == MATCHED_SLICE


def test_tightest_capability_fit_keeps_big_pods_free():
    clock = FakeClock()
    registry = _registry_with(
        clock,
        desc("huge", device_kind="TPU v5p", chips=256, topology="8x8x4"),
        desc("small", device_kind="TPU v5p", chips=64, topology="4x4x4"),
    )
    router = CapabilityRouter(registry)
    decision = router.route(
        "bench/hc", Requirement(generation="v5p", topology="4x4x4")
    )
    assert decision.cluster == "small"  # 64 >= 64, tightest fit
    assert decision.matched == MATCHED_CAPABILITY
    # a bigger ask only the huge pod satisfies
    assert router.route(
        "bench/hc", Requirement(generation="v5p", min_chips=128)
    ).cluster == "huge"


def test_no_capable_cluster_is_a_structured_refusal():
    clock = FakeClock()
    registry = _registry_with(
        clock, desc("edge", device_kind="TPU v5e", chips=16)
    )
    router = CapabilityRouter(registry)
    decision = router.route("bench/hc", Requirement(generation="v6e"))
    assert not decision.routed
    assert decision.reason == NO_CAPABLE_CLUSTER
    assert "edge" in decision.why  # names the healthy set it searched
    # an empty federation refuses too, structured, never raising
    empty = CapabilityRouter(ClusterRegistry(clock=clock))
    assert empty.route("bench/hc").reason == NO_CAPABLE_CLUSTER


def test_default_spread_is_deterministic_per_key():
    clock = FakeClock()
    registry = _registry_with(
        clock, desc("a"), desc("b"), desc("c")
    )
    router = CapabilityRouter(registry)
    first = router.route("bench/hc-1")
    assert first.matched == MATCHED_DEFAULT
    # same key, same cluster, every time (global-door coalescing
    # locality depends on this)
    assert all(
        router.route("bench/hc-1").cluster == first.cluster
        for _ in range(8)
    )
    # many keys actually spread over the healthy set
    landed = {router.route(f"bench/hc-{i}").cluster for i in range(64)}
    assert landed == {"a", "b", "c"}


@pytest.mark.asyncio
async def test_unhealthy_slice_owner_falls_through_to_capability():
    """The reroute path: when a slice's owner goes dark its pinned
    checks start matching by capability instead of black-holing."""
    clock = FakeClock()
    registry = _registry_with(
        clock,
        desc("owner", device_kind="TPU v5p", chips=64, topology="4x4x4",
             slices=("train-pod-a",)),
        desc("spare", device_kind="TPU v5p", chips=64, topology="4x4x4"),
    )
    router = CapabilityRouter(registry)
    req = Requirement(generation="v5p", slice_name="train-pod-a")
    assert router.route("bench/hc", req).cluster == "owner"
    await clock.advance(90.0)
    registry.observe("spare", cluster_payload(1.0))
    registry.sweep()
    decision = router.route("bench/hc", req)
    assert decision.cluster == "spare"
    assert decision.matched == MATCHED_CAPABILITY


# -- the merge seam + federated rollup ---------------------------------


def test_merge_blocks_levels_and_replica_counting():
    # replica payloads count 1 each unless they carry a rollup's count
    merged = merge_blocks(
        [cluster_payload(1.0, runs=10), cluster_payload(2.0, runs=30)],
        level=MERGE_LEVEL_REPLICA,
    )
    assert merged["level"] == MERGE_LEVEL_REPLICA
    assert merged["replicas"] == 2
    assert merged["window_runs"] == 40
    # a cluster-level merge over per-cluster ROLLUPS sums their replica
    # counts (two-level merge, not flattening)
    rollup_a = cluster_payload(1.0, runs=10)
    rollup_a["fleet"]["replicas"] = 3
    merged = merge_blocks(
        [rollup_a, cluster_payload(2.0, runs=30)], level=MERGE_LEVEL_CLUSTER
    )
    assert merged["level"] == MERGE_LEVEL_CLUSTER
    assert merged["replicas"] == 4
    # goodput is run-weighted, never a naive mean
    merged = merge_blocks(
        [
            cluster_payload(1.0, ratio=0.9, runs=100),
            cluster_payload(2.0, ratio=0.5, runs=0),
        ],
        level=MERGE_LEVEL_CLUSTER,
    )
    assert merged["goodput_ratio"] == pytest.approx(0.9)


def test_federated_rollup_checks_tagged_and_deduped_by_cluster():
    fed = federate_statusz(
        {
            "east": cluster_payload(1.0, checks=("bench/a", "bench/b")),
            "west": cluster_payload(2.0, checks=("bench/b", "bench/c")),
        }
    )
    assert fed["fleet"]["clusters"] == 2
    assert fed["fleet"]["checks"] == 3  # bench/b deduped, first cluster wins
    by_key = {c["key"]: c["cluster"] for c in fed["checks"]}
    assert by_key == {
        "bench/a": "east", "bench/b": "east", "bench/c": "west"
    }
    assert set(fed["fleet"]["per_cluster"]) == {"east", "west"}


def test_cluster_version_skew_folds_into_unknown_and_conserves():
    """Satellite: an old-binary cluster (no attribution block) must
    fold its whole share into the ``unknown`` bucket WITHOUT breaking
    conservation — sum(attribution) + goodput == 1 to ±1e-9."""
    fed = federate_statusz(
        {
            "new-east": cluster_payload(1.0, ratio=0.9, runs=100),
            "new-west": cluster_payload(2.0, ratio=0.8, runs=50),
            "legacy": cluster_payload(3.0, ratio=0.7, runs=50,
                                      old_binary=True),
        }
    )
    fleet = fed["fleet"]
    # run-weighted: (0.9*100 + 0.8*50 + 0.7*50) / 200
    assert fleet["goodput_ratio"] == pytest.approx(0.825)
    attribution = fleet["goodput"]["attribution"]
    assert sum(attribution.values()) + fleet["goodput_ratio"] == pytest.approx(
        1.0, abs=1e-9
    )
    # legacy's entire lost share (50 runs * 0.3 / 200) is unknown's
    assert attribution["unknown"] == pytest.approx(0.075, abs=1e-9)
    assert fleet["per_cluster"]["legacy"]["skewed"]
    assert not fleet["per_cluster"]["new-east"]["skewed"]


# -- the global front door ---------------------------------------------


def make_global_door(clock, registry, *, quotas=None, default_quota=None,
                     max_tenants=1024, metrics=None):
    router = CapabilityRouter(registry, metrics=metrics)
    admission = AdmissionController(
        quotas,
        default_quota=default_quota,
        clock=clock,
        max_tenants=max_tenants,
    )
    return GlobalFrontDoor(
        registry, router, admission, clock=clock, metrics=metrics
    )


def make_cluster_door(clock, fleet_history=None):
    """A per-cluster door admitting the federation tenant under the
    effectively-unlimited federation quota."""
    history = fleet_history or ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(
            {FEDERATION_TENANT: federation_quota()}, clock=clock
        ),
        clock=clock,
    )
    triggered = []
    door.bind(lambda ns, name: triggered.append(f"{ns}/{name}"))
    return door, history, triggered


@pytest.mark.asyncio
async def test_global_quota_is_paid_once_and_refuses_structured():
    clock = FakeClock()
    registry = _registry_with(clock, desc("only"))
    gdoor = make_global_door(
        clock,
        registry,
        quotas={"t-a": TenantQuota(rate_per_minute=2.0, burst=2.0)},
    )
    door, _history, triggered = make_cluster_door(clock)
    gdoor.attach("only", door)
    a = gdoor.submit("t-a", "bench/x")
    b = gdoor.submit("t-a", "bench/y")
    c = gdoor.submit("t-a", "bench/z")
    assert (a.outcome, b.outcome) == (OUTCOME_RUN, OUTCOME_RUN)
    assert c.outcome == OUTCOME_REFUSED and c.reason == REFUSE_QUOTA
    assert c.cluster == UNROUTED_CLUSTER  # refused before routing
    # the inner door saw only the admitted two, as the federation tenant
    assert triggered == ["bench/x", "bench/y"]
    assert door.admission.refused.get(FEDERATION_TENANT) is None
    conservation = gdoor.conservation()
    assert conservation["ok"]
    assert conservation["tenants"]["t-a"]["refusals"] == {REFUSE_QUOTA: 1}


@pytest.mark.asyncio
async def test_cross_cluster_coalescing_shares_one_run_and_trace_id():
    """N tenants, one check, doors in two clusters: deterministic
    routing lands every submission on ONE cluster's door, whose cache
    fans them in — one probe run, one shared trace id, globally."""
    clock = FakeClock()
    registry = _registry_with(clock, desc("east"), desc("west"))
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    doors = {}
    histories = {}
    triggered = {}
    for name in ("east", "west"):
        doors[name], histories[name], triggered[name] = make_cluster_door(clock)
        gdoor.attach(name, doors[name])
    tickets = [
        gdoor.submit(f"tenant-{i}", "bench/shared") for i in range(5)
    ]
    landed = {t.cluster for t in tickets}
    assert len(landed) == 1  # every tenant's copy routed to ONE cluster
    cluster = landed.pop()
    assert [t.outcome for t in tickets] == [OUTCOME_RUN] + [OUTCOME_JOINED] * 4
    assert len(triggered[cluster]) == 1  # ONE probe run for all five
    histories[cluster].record(
        "bench/shared", ok=True, latency=1.0, workflow="wf", trace_id="tr-1"
    )
    results = await asyncio.gather(*(t.wait() for t in tickets))
    assert all(r is not None and r.trace_id == "tr-1" for r in results)
    assert {t.trace_id for t in tickets} == {"tr-1"}
    conservation = gdoor.conservation()
    assert conservation["ok"]
    assert conservation["submitted"] == 5
    # each tenant's cell sits under the SAME cluster column
    for i in range(5):
        row = conservation["tenants"][f"tenant-{i}"]
        assert set(row["clusters"]) == {cluster}


@pytest.mark.asyncio
async def test_forwarded_books_at_handoff_and_conserves():
    clock = FakeClock()
    registry = _registry_with(
        clock,
        desc("local", slices=("here",)),
        desc("remote", slices=("there",)),
    )
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    door, _history, _triggered = make_cluster_door(clock)
    gdoor.attach("local", door)
    handed = []
    gdoor.attach_forwarder(
        "remote",
        lambda tenant, check, freshness: handed.append((tenant, check))
        or "handle-1",
    )
    near = gdoor.submit("t", "bench/near", requirement=Requirement(slice_name="here"))
    far = gdoor.submit("t", "bench/far", requirement=Requirement(slice_name="there"))
    assert near.outcome == OUTCOME_RUN and near.cluster == "local"
    assert far.outcome == OUTCOME_FORWARDED and far.cluster == "remote"
    assert far.forwarded == "handle-1"
    assert handed == [("t", "bench/far")]
    assert await far.wait() is None  # accounted on the remote from here on
    conservation = gdoor.conservation()
    assert conservation["ok"]
    assert conservation["forwarded"] == 1
    assert conservation["tenants"]["t"]["clusters"]["remote"]["forwarded"] == 1


@pytest.mark.asyncio
async def test_unattached_cluster_is_a_structured_refusal():
    clock = FakeClock()
    registry = _registry_with(clock, desc("ghost"))
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    ticket = gdoor.submit("t", "bench/x")
    assert ticket.outcome == OUTCOME_REFUSED
    assert ticket.reason == REFUSE_CLUSTER_UNATTACHED
    assert ticket.cluster == "ghost"  # names the cluster it routed to
    conservation = gdoor.conservation()
    assert conservation["ok"]  # post-admission refusal: books stay exact
    assert gdoor.admission.refused["t"] == {REFUSE_CLUSTER_UNATTACHED: 1}


@pytest.mark.asyncio
async def test_no_capable_cluster_refusal_reaches_the_tenant_ledger():
    clock = FakeClock()
    registry = _registry_with(clock, desc("edge", device_kind="TPU v5e"))
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    ticket = gdoor.submit(
        "t", "bench/x", requirement=Requirement(generation="v6e")
    )
    assert ticket.outcome == OUTCOME_REFUSED
    assert ticket.reason == NO_CAPABLE_CLUSTER
    assert gdoor.conservation()["ok"]


@pytest.mark.asyncio
async def test_inner_door_refusal_mirrors_into_the_global_books():
    """A cluster door refusing an ADMITTED request (here: unrouted by a
    sharded inner fleet) must book a post-admission refusal globally —
    otherwise admitted > outcomes and conservation breaks."""
    clock = FakeClock()
    registry = _registry_with(clock, desc("only"))
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    door, _history, _triggered = make_cluster_door(clock)
    door.owns = lambda key: False  # another replica owns every key
    gdoor.attach("only", door)
    ticket = gdoor.submit("t", "bench/x")
    assert ticket.outcome == OUTCOME_REFUSED
    assert gdoor.conservation()["ok"]


@pytest.mark.asyncio
async def test_global_door_snapshot_shape():
    clock = FakeClock()
    registry = _registry_with(clock, desc("only"))
    gdoor = make_global_door(
        clock, registry, default_quota=TenantQuota(rate_per_minute=600.0)
    )
    door, _history, _triggered = make_cluster_door(clock)
    gdoor.attach("only", door)
    gdoor.submit("t", "bench/x")
    snap = gdoor.snapshot()
    assert snap["attached"] == ["only"]
    assert snap["conservation_ok"]
    assert snap["requests"]["submitted"] == 1
    assert snap["per_cluster"]["only"]["probe_runs"] == 1
    assert snap["tenants"]["t"]["ok"]


# -- the federation plane ----------------------------------------------

FED_CONFIG = {
    "liveness_seconds": 90,
    "clusters": [
        {
            "name": "us-east1-v5e",
            "url": "http://east.monitor:8081/statusz",
            "device_kind": "TPU v5e",
            "chips": 16,
            "topology": "4x4",
            "slices": ["edge-pod"],
        },
        {
            "name": "us-west1-v5p",
            "url": "http://west.monitor:8081/statusz",
            "device_kind": "TPU v5p",
            "chips": 64,
            "topology": "4x4x4",
            "dcn_gbps": 100,
        },
    ],
}


@pytest.mark.asyncio
async def test_plane_from_config_polls_and_federates():
    clock = FakeClock()
    plane = FederationPlane.from_config(FED_CONFIG, clock=clock)
    assert plane.registry.names() == ["us-east1-v5e", "us-west1-v5p"]
    assert plane.registry.get("us-west1-v5p").dcn_gbps == pytest.approx(100.0)
    stamps = {"n": 0}
    served = {
        "http://east.monitor:8081/statusz": lambda: cluster_payload(
            stamps["n"], ratio=0.9, runs=10
        ),
        "http://west.monitor:8081/statusz": lambda: cluster_payload(
            stamps["n"] + 0.5, ratio=0.8, runs=30
        ),
    }

    async def fetch(url):
        return served[url]()

    plane.fetch = fetch
    stamps["n"] = 1
    assert await plane.poll() == 2
    fed = plane.federated()
    assert fed["fleet"]["clusters"] == 2
    assert fed["fleet"]["goodput_ratio"] == pytest.approx(0.825)
    snap = plane.snapshot()
    assert snap["registry"]["healthy"] == 2
    assert snap["door"] is None
    # a cluster whose fetch starts failing goes dark by the liveness
    # window, not by the error itself
    async def flaky(url):
        if "west" in url:
            raise OSError("conn reset")
        stamps["n"] += 1
        return served[url]()

    plane.fetch = flaky
    for _ in range(4):
        await clock.advance(30.0)
        await plane.poll()
    assert plane.registry.state("us-west1-v5p") == STATE_UNHEALTHY
    assert plane.registry.state("us-east1-v5e") == STATE_HEALTHY


def test_statusz_federation_block_rides_the_fleet_payload():
    from activemonitor_tpu.obs.slo import FleetStatus

    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    payload = fleet.statusz([])
    assert payload["fleet"]["federation"] is None  # unfederated: null
    plane = FederationPlane.from_config(FED_CONFIG, clock=clock)
    fleet.federation = plane
    block = fleet.statusz([])["fleet"]["federation"]
    assert block["registry"]["healthy"] == 2
    assert "door" in block


# -- CLI rendering -----------------------------------------------------


def test_render_clusters_table():
    from activemonitor_tpu.__main__ import render_clusters

    clock = FakeClock()
    registry = _registry_with(
        clock,
        desc("east", device_kind="TPU v5e", slices=("edge",)),
        desc("west", device_kind="TPU v5p", chips=64, topology="4x4x4"),
    )
    plane = FederationPlane(registry, CapabilityRouter(registry))
    text = render_clusters(plane.snapshot())
    assert "FEDERATION  clusters=2  healthy=2  unhealthy=0" in text
    assert "east" in text and "west" in text and "v5p" in text
    # an empty registry renders, never crashes
    empty = render_clusters({"registry": {"clusters": {}}})
    assert "No clusters joined." in empty


def test_render_status_table_federation_lines():
    from activemonitor_tpu.__main__ import render_status_table

    fed = federate_statusz(
        {
            "east": cluster_payload(1.0, ratio=0.9, runs=100,
                                    checks=("bench/a",)),
            "legacy": cluster_payload(2.0, ratio=0.7, runs=50,
                                      old_binary=True),
        }
    )
    text = render_status_table(fed)
    assert "clusters=2" in text
    assert "CLUSTER east" in text
    assert "SKEWED" in text  # the old binary is called out, not hidden


def test_cluster_name_for_url():
    from activemonitor_tpu.__main__ import _cluster_name_for_url

    assert _cluster_name_for_url("http://east.monitor:8081/statusz") == (
        "east.monitor:8081"
    )
    assert _cluster_name_for_url("not a url") == "not a url"


# -- the scripted FakeClock acceptance ---------------------------------


@pytest.mark.asyncio
async def test_federation_acceptance():
    """ISSUE 19's scripted acceptance: three stub clusters (v5e, v5p,
    old binary), a capability-routed check landing on the v5p mesh, N
    tenants across two clusters coalescing to ONE run with a shared
    trace id, the global per-tenant quota refusing the (N+1)th tenant
    with a structured reason, the federated rollup conserving
    attribution to ±1e-9, and a cluster going unhealthy firing exactly
    one flight bundle while its checks reroute."""
    N = 4
    clock = FakeClock()
    flightrec = FlightRecorder(clock)
    metrics = MetricsCollector()
    registry = ClusterRegistry(
        clock=clock, liveness_seconds=90.0, metrics=metrics,
        flightrec=flightrec,
    )
    registry.join(desc("edge-v5e", device_kind="TPU v5e", chips=16,
                       topology="4x4", slices=("edge-pod",)))
    registry.join(desc("pod-v5p", device_kind="TPU v5p", chips=64,
                       topology="4x4x4"))
    registry.join(desc("legacy", device_kind="TPU v4", chips=32,
                       topology="4x4x2"))
    router = CapabilityRouter(registry, metrics=metrics)
    plane = FederationPlane(registry, router)
    gdoor = GlobalFrontDoor(
        registry,
        router,
        AdmissionController(
            default_quota=TenantQuota(rate_per_minute=600.0),
            clock=clock,
            max_tenants=N,  # the global cap the (N+1)th tenant hits
        ),
        clock=clock,
        metrics=metrics,
    )
    plane.door = gdoor
    doors, histories, triggered = {}, {}, {}
    for name in ("edge-v5e", "pod-v5p"):
        doors[name], histories[name], triggered[name] = make_cluster_door(clock)
        gdoor.attach(name, doors[name])

    # 1. the capability-routed check lands on the v5p mesh
    routed = gdoor.submit(
        "tenant-0", "bench/matmul-4x4x4",
        requirement=Requirement(generation="v5p", topology="4x4x4"),
    )
    assert routed.cluster == "pod-v5p"
    assert routed.matched == MATCHED_CAPABILITY
    assert routed.outcome == OUTCOME_RUN
    histories["pod-v5p"].record(
        "bench/matmul-4x4x4", ok=True, latency=1.0, workflow="wf",
        trace_id="tr-matmul",
    )

    # 2. N tenants, doors in two clusters, ONE coalesced run + trace id
    tickets = [
        gdoor.submit(f"tenant-{i}", "bench/shared") for i in range(N)
    ]
    assert len({t.cluster for t in tickets}) == 1
    cluster = tickets[0].cluster
    assert sorted(t.outcome for t in tickets) == (
        [OUTCOME_JOINED] * (N - 1) + [OUTCOME_RUN]
    )
    assert len(triggered[cluster]) == (
        2 if cluster == "pod-v5p" else 1
    )  # the matmul run above also triggered on pod-v5p
    histories[cluster].record(
        "bench/shared", ok=True, latency=1.0, workflow="wf",
        trace_id="tr-shared",
    )
    results = await asyncio.gather(*(t.wait() for t in tickets))
    assert {r.trace_id for r in results} == {"tr-shared"}

    # 3. the (N+1)th tenant is refused with a structured reason
    extra = gdoor.submit("tenant-extra", "bench/shared")
    assert extra.outcome == OUTCOME_REFUSED
    assert extra.reason == REFUSE_TENANT_CAPACITY
    conservation = gdoor.conservation()
    assert conservation["ok"]
    assert conservation["submitted"] == N + 2

    # 4. the federated rollup conserves attribution to ±1e-9 with the
    # old binary folded into unknown
    registry.observe("edge-v5e", cluster_payload(1.0, ratio=0.9, runs=100))
    registry.observe("pod-v5p", cluster_payload(1.5, ratio=0.8, runs=60))
    registry.observe(
        "legacy", cluster_payload(2.0, ratio=0.6, runs=40, old_binary=True)
    )
    fed = plane.federated()
    fleet = fed["fleet"]
    attribution = fleet["goodput"]["attribution"]
    assert sum(attribution.values()) + fleet["goodput_ratio"] == pytest.approx(
        1.0, abs=1e-9
    )
    assert attribution["unknown"] >= 40 * 0.4 / 200 - 1e-9
    assert fleet["per_cluster"]["legacy"]["skewed"]

    # 5. pod-v5p goes dark: exactly one bundle, and its capability-
    # routed checks land elsewhere only if capable — the v5p-pinned
    # check refuses (structured) rather than landing on weaker hardware,
    # while a slice-free default check reroutes to the survivors
    for step in range(4):
        await clock.advance(30.0)
        registry.observe(
            "edge-v5e", cluster_payload(10.0 + step, ratio=0.9, runs=100)
        )
        registry.observe(
            "legacy",
            cluster_payload(20.0 + step, ratio=0.6, runs=40, old_binary=True),
        )
        plane.sweep()
    assert registry.state("pod-v5p") == STATE_UNHEALTHY
    assert len(flightrec.bundles(kind=KIND_CLUSTER_UNHEALTHY)) == 1
    rerouted = gdoor.submit("tenant-0", "bench/shared")
    assert rerouted.cluster != "pod-v5p"
    strict = gdoor.submit(
        "tenant-0", "bench/matmul-4x4x4",
        requirement=Requirement(generation="v5p", topology="4x4x4"),
    )
    assert strict.outcome == OUTCOME_REFUSED
    assert strict.reason == NO_CAPABLE_CLUSTER
    assert gdoor.conservation()["ok"]

    # the /statusz federation block reflects all of it
    snap = plane.snapshot()
    assert snap["registry"]["unhealthy"] == 1
    assert snap["door"]["conservation_ok"]
