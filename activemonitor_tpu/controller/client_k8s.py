"""Kubernetes-backed HealthCheck client — cluster mode.

Watches HealthCheck CRs through the API server exactly as the reference
controller does (reference: cached client + status subresource writes,
healthcheck_controller.go:175,208-215,1445-1462), built on the
framework's own REST layer (:mod:`activemonitor_tpu.kube`) — fully
async, no threads, no dependency on the ``kubernetes`` package.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, List, Optional

from activemonitor_tpu import GROUP, VERSION
from activemonitor_tpu.api.types import HealthCheck
from activemonitor_tpu.controller.client import (
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from activemonitor_tpu.kube import ApiError, KubeApi, api_path

log = logging.getLogger(__name__)

PLURAL = "healthchecks"


class KubernetesHealthCheckClient:
    # outcomes flow to the shared circuit breaker at the KubeApi
    # transport (when wired there) — the reconciler must not record
    # them a second time at its own call sites
    shares_kube_transport = True

    def __init__(self, api: Optional[KubeApi] = None, owns=None):
        self._api = api if api is not None else KubeApi.from_default_config()
        # shard filter (controller/sharding.py): a live
        # ``(namespace, name) -> bool`` ownership predicate. Applied to
        # the RAW items before the pydantic parse — at fleet scale
        # (50k+ checks) parsing only the owned shards' slice is the
        # difference between an O(fleet) and an O(fleet/N) resync.
        # get/apply/update_status stay unfiltered: handoff races read
        # and write across shard boundaries (the write fence guards).
        self._owns = owns

    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]:
        try:
            obj = await self._api.get(api_path(GROUP, VERSION, PLURAL, namespace, name))
        except ApiError as e:
            if e.not_found:
                return None
            raise
        return HealthCheck.from_dict(obj)

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]:
        raw = await self._api.get(api_path(GROUP, VERSION, PLURAL, namespace or ""))
        items = raw.get("items", [])
        if self._owns is not None:
            items = [
                item
                for item in items
                if self._owns(
                    (item.get("metadata") or {}).get("namespace", ""),
                    (item.get("metadata") or {}).get("name", ""),
                )
            ]
        return [HealthCheck.from_dict(item) for item in items]

    async def apply(self, hc: HealthCheck) -> HealthCheck:
        """Create, or update an existing object. The spec is replaced
        wholesale (fields removed from the manifest disappear — a
        deleted ``remedyworkflow`` stops running), while labels and
        annotations are merged additively (keys owned by other tools
        are never deleted; full kubectl-apply three-way semantics would
        need last-applied tracking). Status is a subresource, untouched
        by this write."""
        body = hc.to_dict()
        body.pop("status", None)
        # an empty namespace would target the cluster-wide collection
        # path, which a real API server rejects for namespaced CRs —
        # default it like kubectl does
        namespace = hc.metadata.namespace or "default"
        body.setdefault("metadata", {})["namespace"] = namespace
        obj_path = api_path(GROUP, VERSION, PLURAL, namespace, hc.metadata.name)
        for attempt in range(5):
            if attempt:
                # bounded, backed-off retries: a webhook mutating every
                # write must not turn this loop into an API-server DoS
                await asyncio.sleep(0.05 * 2**attempt)
            try:
                created = await self._api.create(
                    api_path(GROUP, VERSION, PLURAL, namespace), body
                )
                break
            except ApiError as e:
                if not e.conflict:
                    raise
            try:
                existing = await self._api.get(obj_path)
            except ApiError as e:
                if e.not_found:
                    continue  # deleted between the 409 and here: recreate
                raise
            existing["spec"] = body.get("spec", {})
            meta = existing.setdefault("metadata", {})
            for key in ("labels", "annotations"):
                incoming = body.get("metadata", {}).get(key)
                if incoming:
                    merged = dict(meta.get(key) or {})
                    merged.update(incoming)
                    meta[key] = merged
            try:
                # the PUT carries the resourceVersion just read, so a
                # concurrent writer turns this into a 409 and we retry
                created = await self._api.replace(obj_path, existing)
                break
            except ApiError as e:
                if not e.conflict and not e.not_found:
                    raise
        else:
            raise ConflictError(hc.key)
        return HealthCheck.from_dict(created)

    async def update_status(self, hc: HealthCheck) -> HealthCheck:
        # the FULL status, defaults and Nones included — the in-process
        # model is authoritative (the reconciler read-modify-writes it),
        # so every field must be stated explicitly. An exclude-defaults
        # dump under a MERGE patch can never move a field BACK to its
        # default: a cleared Quarantined `state`, a reset remedy's
        # zeroed counters and nulled timestamps (RFC 7386: null deletes
        # the key), an emptied errorMessage — all would silently stick
        # at their last non-default value forever.
        body = {
            "metadata": {"resourceVersion": hc.metadata.resource_version or None},
            "status": hc.status.model_dump(by_alias=True, mode="json"),
        }
        try:
            updated = await self._api.merge_patch(
                api_path(
                    GROUP, VERSION, PLURAL, hc.metadata.namespace, hc.metadata.name,
                    subresource="status",
                ),
                body,
            )
        except ApiError as e:
            if e.conflict:
                raise ConflictError(hc.key) from e
            if e.not_found:
                raise NotFoundError(hc.key) from e
            raise
        return HealthCheck.from_dict(updated)

    async def delete(self, namespace: str, name: str) -> None:
        try:
            await self._api.delete(api_path(GROUP, VERSION, PLURAL, namespace, name))
        except ApiError as e:
            if e.not_found:
                raise NotFoundError(f"{namespace}/{name}") from e
            raise

    def watch(self) -> AsyncIterator[WatchEvent]:
        """All-namespaces watch with automatic reconnect.

        The server sends synthetic ADDED events for the existing state
        when a watch starts without a resourceVersion, and the manager
        boot-resyncs via list() right after — so events cannot fall into
        the registration gap. On stream loss we resume from the last
        seen resourceVersion. On 410 Gone (the gap outlived etcd's
        compaction window) the restart-from-scratch ADDEDs cover
        additions and updates but NOT objects deleted during the gap —
        so we list and synthesize DELETED for every key that vanished,
        otherwise their timers would keep firing spurious runs."""
        path = api_path(GROUP, VERSION, PLURAL)

        async def gen() -> AsyncIterator[WatchEvent]:
            resource_version = ""
            known: set = set()  # (namespace, name) seen alive on this stream
            while True:
                try:
                    async for event in self._api.watch(
                        path, resource_version=resource_version
                    ):
                        obj = event.get("object", {}) or {}
                        meta = obj.get("metadata", {}) or {}
                        if meta.get("resourceVersion"):
                            resource_version = meta["resourceVersion"]
                        if event.get("type") == "BOOKMARK":
                            continue  # rv bookkeeping only, nothing changed
                        key = (meta.get("namespace", ""), meta.get("name", ""))
                        if event.get("type") == "DELETED":
                            known.discard(key)
                        else:
                            known.add(key)
                        # shard filter at YIELD time (ownership is live);
                        # `known` still tracks the whole fleet so a
                        # post-410 re-list stays correct across handoffs
                        if self._owns is not None and not self._owns(*key):
                            continue
                        yield WatchEvent(
                            type=event.get("type", "MODIFIED"),
                            namespace=key[0],
                            name=key[1],
                        )
                except ApiError as e:
                    if e.status == 410:
                        log.info("watch expired (410); re-listing from scratch")
                        resource_version = ""
                        for ns, name in await self._vanished(known):
                            known.discard((ns, name))
                            if self._owns is not None and not self._owns(ns, name):
                                continue
                            yield WatchEvent(type="DELETED", namespace=ns, name=name)
                    else:
                        log.warning("watch broke (%s); re-establishing", e)
                        await asyncio.sleep(1.0)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("watch stream broke; re-establishing")
                    await asyncio.sleep(1.0)

        return gen()

    async def _vanished(self, known: set) -> list:
        """Keys in ``known`` that no longer exist on the server (the
        deletions a 410 gap swallowed). The list is retried with
        backoff — it is the ONLY path that recovers those deletions
        (another 410 may never come), so giving up after one attempt
        would leave deleted checks' schedules firing forever."""
        raw = None
        for attempt in range(6):
            if attempt:
                await asyncio.sleep(min(0.2 * 2**attempt, 5.0))
            try:
                raw = await self._api.get(api_path(GROUP, VERSION, PLURAL))
                break
            except Exception:
                continue
        if raw is None:
            log.error(
                "post-410 re-list failed repeatedly; deletions during the "
                "watch gap will only be noticed on the next 410/restart"
            )
            return []
        current = {
            (
                item.get("metadata", {}).get("namespace", ""),
                item.get("metadata", {}).get("name", ""),
            )
            for item in raw.get("items", [])
        }
        return sorted(known - current)
