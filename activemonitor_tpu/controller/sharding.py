"""Sharded controller fleet — consistent-hash ownership, crash-safe handoff.

ROADMAP item 1: a single manager process owning every HealthCheck
behind active/standby election (controller/leader.py) stalls the whole
fleet on one crash and scales to exactly one process. This module
shards the reconcile fleet horizontally, the Maple direction (PAPERS.md:
partitioned control planes that survive member churn) applied to our
control plane:

- :class:`ShardRouter` — consistent-hash assignment of check keys to N
  shards (md5 ring with virtual nodes, stable across processes and
  Python hash randomization; adding a shard moves ~1/(N+1) of the keys).
- :class:`ShardSet` — one :class:`~activemonitor_tpu.controller.leader.
  KubernetesLeaseElector` per shard, generalizing the single HA lock to
  a shard map: a replica acquires its *home* shard eagerly and stands by
  for every other shard, adopting any whose lease expires (shard death,
  scale-down). Standbys wait one lease of grace past expiry, so a FAST
  restart reclaims the home shard before any peer adopts it; after a
  longer outage a peer adopts first, and the coordinator's home-return
  rule hands the shard back once the restarted replica's presence lease
  is moving again.
- :class:`ShardCoordinator` — the manager/reconciler façade: ownership
  checks for watch/list/queue filtering, resourceVersion fencing for
  status writes (a paused old owner's late write is rejected), depth
  publication via lease annotations riding the renewal write, and
  shard-granular work-stealing when this replica's ``workqueue_depth``
  diverges above the fleet median.

Crash-safe handoff needs no new durable state: the adopting owner
reconciles every check of the dead shard, and the restart-resume path
(reconciler divergence 10) rebuilds each TimerWheel entry from the
durable ``.status`` — current checks re-arm for the remaining interval,
overdue checks fire immediately, and nothing double-fires because the
old owner's timers died with it and its late status writes are fenced.

Everything here runs on the injectable Clock (hack/lint.py bans bare
wall-clock reads in this module, like resilience/ and analysis/).
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import logging
import statistics
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from activemonitor_tpu.controller.leader import ELECTION_ID, KubernetesLeaseElector
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.sharding")

# workqueue depth published on each shard lease (rides the renewal PUT,
# never a separate write — a separate PATCH would race the renew loop's
# GET→PUT and self-inflict the conflict that demotes a holder)
DEPTH_ANNOTATION = "activemonitor.keikoproj.io/workqueue-depth"

# a replica sheds a shard only when its depth exceeds the fleet median
# by at least this many queued keys — small divergence is noise, not
# imbalance worth a handoff
DEFAULT_STEAL_THRESHOLD = 16


class ShardFencedError(Exception):
    """A write was rejected because this replica no longer holds the
    key's shard lease (expired, taken over, or shed). The new owner is
    authoritative; the caller must DROP the write, never queue it."""

    def __init__(self, shard: int, key: str, reason: str = ""):
        super().__init__(
            f"shard {shard} fence rejected write for {key}"
            + (f": {reason}" if reason else "")
        )
        self.shard = shard
        self.key = key


def _point(data: str) -> int:
    """Stable 64-bit ring position (md5, not ``hash()``: every replica
    must map a key to the same shard across processes and restarts)."""
    return int.from_bytes(hashlib.md5(data.encode()).digest()[:8], "big")


class ShardRouter:
    """Consistent-hash ring: check key -> shard id in [0, shards)."""

    def __init__(self, shards: int, vnodes: int = 128):
        if shards < 1:
            raise ValueError(f"need at least one shard, got {shards}")
        self.shards = shards
        ring = sorted(
            (_point(f"shard-{shard}/vnode-{v}"), shard)
            for shard in range(shards)
            for v in range(vnodes)
        )
        self._points = [p for p, _ in ring]
        self._owners = [s for _, s in ring]

    def shard_for(self, key: str) -> int:
        if self.shards == 1:
            return 0
        i = bisect.bisect(self._points, _point(key)) % len(self._points)
        return self._owners[i]


def shard_lease_name(shard: int) -> str:
    """Per-shard Lease object name — the single-lock ELECTION_ID
    generalized to a shard map (one coordination.k8s.io Lease each)."""
    return f"{ELECTION_ID}-shard-{shard:02d}"


def member_lease_name(slot: int) -> str:
    """Per-replica presence Lease (slot = the replica's home shard id).

    Distinct from the shard leases on purpose: a replica that owns NO
    shard right now (fresh restart whose home was adopted by a peer)
    still renews its member lease, so its published workqueue depth
    stays visible to the work-stealing median — otherwise an idle
    standby could never be stolen FOR, and an overloaded survivor would
    keep an adopted shard forever."""
    return f"{ELECTION_ID}-member-{slot:02d}"


class ShardSet:
    """All N shard elections, driven from one replica.

    The home shard is contended immediately; every other shard gets a
    standby loop that sleeps one lease duration before contending, so a
    healthy fleet converges to one shard per replica and an orphaned
    shard is adopted by whichever survivor's standby wins the expired
    lease (the elector's preconditioned takeover keeps that race safe).
    """

    def __init__(
        self,
        api,
        namespace: str,
        shards: int,
        home_shard: int,
        identity: str,
        clock: Optional[Clock] = None,
        lease_seconds: float = 15.0,
        annotations: Optional[Callable[[], dict]] = None,
        on_acquired: Optional[Callable[[int], Awaitable[None]]] = None,
        on_lost: Optional[Callable[[int], Awaitable[None]]] = None,
    ):
        if not (0 <= home_shard < shards):
            raise ValueError(f"shard id {home_shard} outside [0, {shards})")
        self._api = api
        self._namespace = namespace
        self.shards = shards
        self.home_shard = home_shard
        self.identity = identity
        self.clock = clock or Clock()
        self.lease_seconds = float(lease_seconds)
        self._annotations = annotations
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.owned: Dict[int, KubernetesLeaseElector] = {}
        # this replica's presence lease (member_lease_name); None while
        # contending (e.g. a same-slot twin holds it)
        self.member: Optional[KubernetesLeaseElector] = None
        # shards in acquisition order; the tail is the most recently
        # adopted one — the first candidate for work-stealing shed
        self.adopt_order: List[int] = []
        self.first_owned = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self._stopping = False
        # monotonic deadline before which a shed shard is not
        # re-contended (another replica must get a clean shot at it)
        self._cooldown: Dict[int, float] = {}
        self._shedding: set = set()

    def _make_elector(self, shard: int) -> KubernetesLeaseElector:
        return KubernetesLeaseElector(
            api=self._api,
            namespace=self._namespace,
            name=shard_lease_name(shard),
            identity=self.identity,
            lease_seconds=self.lease_seconds,
            clock=self.clock,
            annotations=self._annotations,
            # standby grace, enforced INSIDE the contend loop so it
            # holds in steady state (not just on the first loop entry):
            # non-home standbys wait one extra lease past expiry before
            # takeover, while the shard's home replica contends with no
            # grace — a home replica restarting within the grace window
            # reclaims its shard before any peer adopts it. Relinquished
            # leases (voluntary shed / home-return) get a SHORTER
            # vacancy window (elector's _vacancy_grace): the home
            # replica still takes them immediately, graced standbys a
            # beat later — so a home-return lands home, not on whichever
            # peer polls first.
            takeover_grace=(
                0.0 if shard == self.home_shard else self.lease_seconds
            ),
        )

    async def start(self, wait_first: bool = True) -> None:
        """Spawn one election loop per shard plus the presence loop; by
        default blocks until this replica owns at least one shard (its
        home, on a healthy fleet) so the manager never serves
        shardless."""
        self._tasks.append(
            asyncio.create_task(self._run_member(), name="shard-member")
        )
        for shard in range(self.shards):
            self._tasks.append(
                asyncio.create_task(
                    self._run_shard(shard), name=f"shard-election:{shard}"
                )
            )
        if wait_first:
            await self.first_owned.wait()

    async def _run_member(self) -> None:
        """Hold the replica's presence lease continuously — it exists
        only to carry the depth annotation, so losing it never touches
        shard ownership; the loop just re-contends."""
        while not self._stopping:
            elector = KubernetesLeaseElector(
                api=self._api,
                namespace=self._namespace,
                name=member_lease_name(self.home_shard),
                identity=self.identity,
                lease_seconds=self.lease_seconds,
                clock=self.clock,
                annotations=self._annotations,
            )
            await elector.acquire()
            self.member = elector
            await elector.lost.wait()
            self.member = None

    async def _run_shard(self, shard: int) -> None:
        while not self._stopping:
            wait = self._cooldown.pop(shard, 0.0) - self.clock.monotonic()
            if wait > 0:
                await self.clock.sleep(wait)
            # (the standby grace for non-home shards lives inside the
            # elector's contend loop — takeover_grace in _make_elector —
            # so it applies in steady state, not just at loop entry)
            if self._stopping:
                return
            elector = self._make_elector(shard)
            await elector.acquire()
            self.owned[shard] = elector
            self.adopt_order.append(shard)
            self.first_owned.set()
            log.info(
                "shard %d acquired by %s (%d/%d owned)",
                shard, self.identity, len(self.owned), self.shards,
            )
            if self.on_acquired is not None:
                try:
                    await self.on_acquired(shard)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("on_acquired(%d) callback failed", shard)
            await elector.lost.wait()
            self.owned.pop(shard, None)
            try:
                self.adopt_order.remove(shard)
            except ValueError:
                pass
            shed = shard in self._shedding
            self._shedding.discard(shard)
            log.warning(
                "shard %d %s by %s (%d/%d owned)",
                shard, "shed" if shed else "lost", self.identity,
                len(self.owned), self.shards,
            )
            if self.on_lost is not None:
                try:
                    await self.on_lost(shard)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("on_lost(%d) callback failed", shard)

    async def shed(self, shard: int) -> bool:
        """Voluntarily release an adopted shard (work-stealing): the
        lease is relinquished so an underloaded peer's standby takes it
        within one short vacancy window, and this replica sits out two
        lease durations before contending again. The home shard is
        never shed."""
        elector = self.owned.get(shard)
        if elector is None or shard == self.home_shard:
            return False
        self._shedding.add(shard)
        self._cooldown[shard] = self.clock.monotonic() + self.lease_seconds * 2
        await elector.release_async()
        # wake the _run_shard loop: release() suppresses the elector's
        # own lost signal (orderly stop), so the shed path fires it
        elector.lost.set()
        return True

    async def stop(self) -> None:
        """Orderly shutdown: stop contending, relinquish every owned
        lease so survivors adopt immediately instead of waiting out the
        lease durations."""
        self._stopping = True
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        for elector in list(self.owned.values()):
            await elector.release_async()
        if self.member is not None:
            await self.member.release_async()
            self.member = None
        self.owned.clear()
        self.adopt_order.clear()


class ShardCoordinator:
    """The sharded fleet's face toward the manager and reconciler.

    Bundles the router (who owns a key), the shard set (which leases
    this replica holds), write fencing (reject a paused old owner's
    late status writes), depth publication, and the shard-granular
    work-stealing policy. ``/statusz`` serves :meth:`snapshot`;
    :func:`activemonitor_tpu.obs.slo.rollup_statusz` merges the
    per-replica snapshots into the fleet view.
    """

    def __init__(
        self,
        api,
        namespace: str,
        shards: int,
        shard_id: int,
        identity: str = "",
        clock: Optional[Clock] = None,
        metrics=None,
        lease_seconds: float = 15.0,
        steal_threshold: int = DEFAULT_STEAL_THRESHOLD,
        vnodes: int = 128,
    ):
        import socket
        import uuid

        self.api = api
        self.namespace = namespace
        self.shards = shards
        self.shard_id = shard_id
        self.clock = clock or Clock()
        self.metrics = metrics
        self.lease_seconds = float(lease_seconds)
        self.steal_threshold = steal_threshold
        self.identity = (
            identity or f"{socket.gethostname()}-s{shard_id}-{uuid.uuid4().hex[:8]}"
        )
        self.router = ShardRouter(shards, vnodes=vnodes)
        self._depth = 0
        self._check_counts: Dict[int, int] = {}
        self._shed_pending: set = set()
        # shards mid-voluntary-handoff: owns_key() reports them unowned
        # so no NEW work starts while the pre-shed gate scans and the
        # lease is released (closing the dequeue-during-shed race), but
        # in-flight writes still land — we hold the lease until the
        # release, and owns_for_write()/admit_write ignore draining
        self.draining: set = set()
        # member-lease liveness by LOCALLY-OBSERVED resourceVersion
        # movement (slot -> (rv, monotonic first seen at this rv)) —
        # the same skew-immune discipline the elector's expiry uses;
        # trusting the holder's renewTime wall-clock stamp would wedge
        # home-return behind clock skew
        self._member_seen: Dict[int, Tuple[str, float]] = {}
        # member rv observed on the first sweep after adopting a shard:
        # the corpse's final renewal must not read as presence — only
        # MOVEMENT from this baseline proves the home replica is back
        self._member_baseline: Dict[int, str] = {}
        self.fenced_writes = 0
        # wired by the Manager before start(): adoption resync / handoff
        # cleanup. The coordinator's own hooks keep the metrics honest
        # even when no manager is attached (unit tests).
        self.on_acquired: Optional[Callable[[int], Awaitable[None]]] = None
        self.on_lost: Optional[Callable[[int], Awaitable[None]]] = None
        # awaited before a VOLUNTARY shed; returning False aborts it.
        # The manager uses this to drain the shard's queued status
        # writes first — a shed must hand the new owner durable truth,
        # not strand recorded runs in this process's replay queue
        # (crash handoffs have no such luxury: durable status is all
        # the corpse leaves behind, and the fence blocks its late
        # corrections).
        self.pre_shed: Optional[Callable[[int], Awaitable[bool]]] = None
        self.set = ShardSet(
            api,
            namespace,
            shards,
            shard_id,
            self.identity,
            clock=self.clock,
            lease_seconds=lease_seconds,
            annotations=self._lease_annotations,
            on_acquired=self._acquired,
            on_lost=self._lost,
        )

    # -- ownership -------------------------------------------------------
    def shard_for(self, key: str) -> int:
        return self.router.shard_for(key)

    def owns_key(self, key: str) -> bool:
        """May NEW work for this key start here? False for unowned AND
        for draining shards (a voluntary handoff in progress must not
        admit fresh dequeues/timer fires it would immediately strand)."""
        shard = self.router.shard_for(key)
        return shard in self.set.owned and shard not in self.draining

    def owns_for_write(self, key: str) -> bool:
        """May a status write for this key be attempted? Unlike
        :meth:`owns_key` this ignores ``draining`` — the lease is held
        until the release lands, and an in-flight run finishing during
        the pre-shed scan must record its result, not get dropped."""
        elector = self.set.owned.get(self.router.shard_for(key))
        return elector is not None and not elector.lost.is_set()

    def owns_event(self, namespace: str, name: str) -> bool:
        """Watch/list filter predicate (namespace, name) — the shape
        the shard-aware clients take."""
        return self.owns_key(f"{namespace}/{name}")

    def owned_shards(self) -> List[int]:
        return sorted(self.set.owned)

    # -- lifecycle -------------------------------------------------------
    async def start(self, wait_first: bool = True) -> None:
        await self.set.start(wait_first=wait_first)

    async def stop(self) -> None:
        await self.set.stop()
        if self.metrics is not None:
            for shard in range(self.shards):
                self.metrics.set_shard_owned(shard, False)

    async def _acquired(self, shard: int) -> None:
        if self.metrics is not None:
            self.metrics.set_shard_owned(shard, True)
            self.metrics.record_shard_handoff(shard, "acquired")
        if self.on_acquired is not None:
            await self.on_acquired(shard)

    async def _lost(self, shard: int) -> None:
        self._check_counts.pop(shard, None)
        self._member_baseline.pop(shard, None)
        shed = shard in self._shed_pending
        self._shed_pending.discard(shard)
        if self.metrics is not None:
            self.metrics.set_shard_owned(shard, False)
            self.metrics.clear_shard_checks(shard)
            self.metrics.record_shard_handoff(shard, "shed" if shed else "lost")
        if self.on_lost is not None:
            await self.on_lost(shard)

    # -- write fencing ---------------------------------------------------
    async def admit_write(self, key: str) -> None:
        """Gate a status write on still owning the key's shard.

        Fast path: our last successful lease write is younger than the
        renew deadline (2/3 lease), so no challenger's takeover window
        can have opened — admit without I/O. Stale path (a paused
        process, a wedged renew loop): re-read the shard's lease and
        check ``spec.holderIdentity`` is still us; anyone else holding
        it means a takeover happened while we were paused, so the shard
        is released locally and the write is rejected. The fence is
        still resourceVersion-based end to end — takeover PUTs are
        rv-fenced at the elector, and ``fence_rv``/``last_write`` (the
        rv recorded at our last successful write) is what arms this
        stale path — but the verification itself compares identity, not
        rv (see inline comment: an rv compare would false-positive
        against our own racing renew loop). Transient GET failures
        propagate to the caller's normal retry/queue machinery rather
        than silently dropping the write."""
        shard = self.router.shard_for(key)
        elector = self.set.owned.get(shard)
        if elector is None or elector.lost.is_set():
            raise ShardFencedError(shard, key, "shard not owned")
        fresh_window = self.lease_seconds * 2.0 / 3.0
        if self.clock.monotonic() - elector.last_write <= fresh_window:
            return
        lease = await self.api.get(elector.path)
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        if holder != self.identity:
            # taken over while we were paused: drop the shard NOW — the
            # _run_shard loop cleans up and goes back to standing by.
            # The identity IS the verdict: nobody else ever writes OUR
            # uuid-suffixed identity, and takeover PUTs are themselves
            # resourceVersion-fenced at the elector. Comparing the rv
            # against our recorded fence token here would false-positive
            # against our OWN renew loop racing this GET (the stale path
            # runs exactly while that loop is retrying near its
            # deadline) and drop a healthy shard.
            elector.demote()
            raise ShardFencedError(
                shard, key, f"lease held by {holder!r}"
            )
        # deliberately NOT refreshing last_write here: a read-only GET
        # proves we held the lease at verification time but does not
        # renew it — challengers' takeover clocks keep running from the
        # last real WRITE, so extending the no-I/O fast path from a read
        # would re-open exactly the paused-owner window this fence
        # closes. Every stale-path write pays the GET until the renew
        # loop lands a real renewal (only _note_write advances the token).

    def note_fenced(self, key: str) -> None:
        """Account one rejected write (metric + counter for /statusz)."""
        self.fenced_writes += 1
        if self.metrics is not None:
            self.metrics.record_fenced_write(self.router.shard_for(key))

    # -- depth publication + work stealing -------------------------------
    def _lease_annotations(self) -> dict:
        return {DEPTH_ANNOTATION: str(self._depth)}

    def publish_depth(self, depth: int) -> None:
        """Record this replica's workqueue depth; it rides every owned
        shard's next lease renewal as an annotation."""
        self._depth = int(depth)

    async def _lease_table(self, prefix: str) -> Dict[int, Tuple[str, int, str]]:
        """id -> (holder identity, published depth, resourceVersion)
        for leases named ``<prefix><NN>`` (one LIST of the namespace's
        leases)."""
        from activemonitor_tpu.kube import api_path

        raw = await self.api.get(
            api_path(
                KubernetesLeaseElector.LEASE_GROUP,
                KubernetesLeaseElector.LEASE_VERSION,
                KubernetesLeaseElector.LEASE_PLURAL,
                self.namespace,
            )
        )
        out: Dict[int, Tuple[str, int, str]] = {}
        for item in raw.get("items", []):
            meta = item.get("metadata") or {}
            name = meta.get("name", "")
            if not name.startswith(prefix):
                continue
            try:
                ident = int(name[len(prefix):])
            except ValueError:
                continue
            holder = (item.get("spec") or {}).get("holderIdentity") or ""
            try:
                depth = int((meta.get("annotations") or {}).get(DEPTH_ANNOTATION, 0))
            except (TypeError, ValueError):
                depth = 0
            out[ident] = (holder, depth, str(meta.get("resourceVersion") or ""))
        return out

    async def fleet_depths(self) -> Dict[int, Tuple[str, int]]:
        """shard -> (holder identity, last published depth), read from
        the shard Leases."""
        table = await self._lease_table(f"{ELECTION_ID}-shard-")
        return {k: (holder, depth) for k, (holder, depth, _rv) in table.items()}

    def _member_alive(self, slot: int, rv: str) -> bool:
        """Liveness by locally-observed rv movement, never by the
        holder's renewTime wall clock (a skewed-clock peer must not
        look dead — same discipline as the elector's expiry): the lease
        is alive while its rv keeps moving; static for two lease
        durations on OUR monotonic clock means the holder is gone. A
        crashed replica's member lease keeps its holderIdentity forever
        (nothing re-contends a presence slot except a same-slot twin),
        so without this a ghost's stale depth would skew the
        work-stealing median indefinitely."""
        now = self.clock.monotonic()
        seen = self._member_seen.get(slot)
        if seen is None or seen[0] != rv:
            self._member_seen[slot] = (rv, now)
            return True  # moved (or first sighting): start the window
        return now - seen[1] <= self.lease_seconds * 2.0

    def _alive_members(
        self, members: Dict[int, Tuple[str, int, str]]
    ) -> Dict[int, Tuple[str, int, str]]:
        return {
            slot: entry
            for slot, entry in members.items()
            if entry[0] and self._member_alive(slot, entry[2])
        }

    async def member_depths(self) -> Dict[str, int]:
        """replica identity -> published depth, read from the LIVE
        member (presence) leases — these include replicas that
        currently own no shard at all, which is exactly who
        work-stealing sheds for."""
        members = await self._lease_table(f"{ELECTION_ID}-member-")
        return {
            holder: depth
            for holder, depth, _rv in self._alive_members(members).values()
        }

    async def rebalance(self, my_depth: int) -> Optional[int]:
        """The periodic placement policy, two rules in priority order:

        1. **Home return.** An adopted shard whose HOME replica's
           member (presence) lease is fresh again is handed back — the
           replica restarted, and without this it could never reacquire
           its shard on a balanced fleet (its eager acquire only beats
           EXPIRED leases), wedging ``Manager.start`` → ``/readyz``
           and every rolling update behind it.
        2. **Work-stealing.** When this replica's queue depth diverges
           above the fleet median (over every live member's published
           depth) by more than the threshold AND it owns more than one
           shard, shed the most recently adopted non-home shard for an
           underloaded peer.

        Returns the shed shard id, or None. Both rules move whole
        shards on purpose — moving individual keys would break the
        consistent-hash routing every replica relies on, and they share
        ONE member-lease LIST per sweep."""
        self.publish_depth(my_depth)
        if not any(s != self.shard_id for s in self.set.adopt_order):
            return None  # nothing adopted: nothing returnable or sheddable
        members = await self._lease_table(f"{ELECTION_ID}-member-")
        alive = self._alive_members(members)
        returned = await self._return_home_shard(members, alive)
        if returned is not None:
            return returned
        if len(self.set.owned) <= 1:
            # never STEAL-shed the last owned shard — but this guard must
            # not sit above home-return: a replica holding ONLY an
            # adopted shard (home shard fenced away while its peer was
            # dead) would otherwise never hand it back, and the restarted
            # home replica would wedge in Manager.start forever
            return None
        per_member = {
            holder: depth for holder, depth, _rv in alive.values()
        }
        per_member[self.identity] = my_depth
        if len(per_member) < 2:
            return None  # nobody to steal for
        median = statistics.median(per_member.values())
        if my_depth - median < self.steal_threshold:
            return None
        candidates = [s for s in self.set.adopt_order if s != self.shard_id]
        if not candidates:
            return None
        shard = candidates[-1]
        if not await self._shed(shard):
            return None
        log.warning(
            "workqueue depth %d diverged above fleet median %.0f; "
            "shed shard %d for an underloaded peer",
            my_depth, median, shard,
        )
        return shard

    async def _shed(self, shard: int) -> bool:
        """A voluntary handoff, quiesced: the shard drains FIRST (new
        dequeues/timer fires see it unowned) so no fresh work can slip
        in between the pre-shed in-flight scan and the lease release —
        the run it started would finish after the handoff and lose its
        status record at the fence. In-flight writes still land
        (``owns_for_write`` ignores draining). The ``pre_shed`` gate
        then defers while anything is still in flight; an aborted shed
        un-drains and retries next sweep."""
        self.draining.add(shard)
        try:
            if self.pre_shed is not None and not await self.pre_shed(shard):
                log.warning(
                    "shard %d shed deferred: its in-flight work / queued "
                    "status writes have not drained yet", shard,
                )
                return False
            self._shed_pending.add(shard)
            if await self.set.shed(shard):
                return True
            self._shed_pending.discard(shard)
            return False
        finally:
            self.draining.discard(shard)

    async def _return_home_shard(self, members, alive) -> Optional[int]:
        """Hand an adopted shard back once its home replica is ALIVE
        again AND its member lease has moved past the baseline recorded
        on our first sweep after adoption — the dead incarnation's last
        renewal must never read as presence, or we would return the
        shard to a corpse and orphan it for another expiry round. The
        freed lease is relinquished; the home replica's zero-grace
        acquire takes it immediately while every other standby sits out
        the elector's vacancy window, so the return deterministically
        lands home."""
        adopted = [s for s in self.set.adopt_order if s != self.shard_id]
        for shard in adopted:
            rv_now = (members.get(shard) or ("", 0, ""))[2]
            baseline = self._member_baseline.get(shard)
            if baseline is None:
                self._member_baseline[shard] = rv_now
                continue
            entry = alive.get(shard)
            if entry is None:
                continue  # home replica still absent
            holder, _depth, rv = entry
            if holder == self.identity or rv == baseline:
                continue  # us, or no movement since we adopted
            if not await self._shed(shard):
                continue
            log.info(
                "shard %d's home replica %s is back; returned the shard",
                shard, holder,
            )
            return shard
        return None

    # -- statusz ---------------------------------------------------------
    def update_check_counts(self, checks) -> None:
        """Per-shard ownership counts over the given (owned) check list
        — the numbers the fleet /statusz rollup sums against the check
        total. Refreshed by the manager's rollup loop and every statusz
        build, never on the reconcile path."""
        counts: Dict[int, int] = {shard: 0 for shard in self.set.owned}
        for hc in checks:
            shard = self.router.shard_for(hc.key)
            if shard in counts:
                counts[shard] += 1
        self._check_counts = counts
        if self.metrics is not None:
            for shard, count in counts.items():
                self.metrics.set_shard_checks(shard, count)

    def snapshot(self) -> dict:
        """The /statusz ``fleet.sharding`` block."""
        return {
            "shards": self.shards,
            "shard_id": self.shard_id,
            "identity": self.identity,
            "owned": self.owned_shards(),
            "checks_per_shard": {
                str(shard): count
                for shard, count in sorted(self._check_counts.items())
            },
            "workqueue_depth": self._depth,
            "fenced_writes": self.fenced_writes,
        }
