"""Shared harness for cluster-mode tests.

Each coroutine test runs in its own event loop (see conftest.py), so the
stub API server must be started *inside* the test body — an async
context manager, not a fixture.
"""

from contextlib import asynccontextmanager

from activemonitor_tpu.kube import KubeApi, KubeConfig
from activemonitor_tpu.kube.stub import StubApiServer


@asynccontextmanager
async def stub_env(token: str = ""):
    """An in-process API server plus a client pointed at it."""
    server = StubApiServer(token=token)
    await server.start()
    api = KubeApi(KubeConfig(server=server.url, token=token))
    try:
        yield server, api
    finally:
        await api.close()
        await server.stop()
