"""Roofline classification: cost-model evidence under every fraction.

A probe reporting "0.6 of rated" is ambiguous: a memory-bound kernel at
0.6 of the chip's FLAT compute peak may be sitting exactly on its true
ceiling (healthy), while a compute-bound kernel at 0.6 of the same peak
is sick. The ML Productivity Goodput work (PAPERS.md, arXiv:2502.06982)
attributes lost goodput against *hardware ceilings*, and ReFrame
(arXiv:2404.10536) demands an analytic baseline under every scenario —
both need the roofline model under the measurement. This module is that
model:

- **arithmetic intensity** = FLOPs executed / HBM bytes moved
  (FLOPs/byte), taken from XLA's compile-time cost analysis
  (``utils/compat.compile_cost_analysis``) on TPU, or from the probe's
  own analytic estimate off-TPU / on old JAX — the latter explicitly
  labeled ``cost_source: model`` and never compared against a TPU bar.
- **ridge point** = rated peak FLOP/s / rated HBM byte/s
  (``probes/rated.ridge_point``): intensity below it ⇒ memory-bound
  (ceiling = intensity x bandwidth), above ⇒ compute-bound (ceiling =
  flat peak). Collective probes get the ICI roofline instead: their
  ceiling is the schedule's rated bus bandwidth, bound ``comm``.
- **roofline fraction** = achieved / *ceiling* — the
  fraction-of-what-this-kernel-could-ever-do number the flat
  fraction-of-rated gauges cannot express.

Probe side, :func:`capture` bundles the whole pipeline (cost capture →
classification → ``*-arithmetic-intensity`` / ``*-roofline-fraction``
ProbeMetrics → the stdout contract's ``roofline`` block → per-phase
device-memory snapshot); controller side the block rides the result
history into /statusz, ``am-tpu roofline``, goodput attribution ("0.41
of memory-bound ceiling") and flight bundles.

Clock discipline like every obs/ module: no wall-clock reads
(``hack/lint.py`` bans them here) — measured seconds arrive as
arguments, classification is pure math, and nothing raises into the
probe or recording path that feeds it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

from activemonitor_tpu.probes.base import ProbeMetric
from activemonitor_tpu.probes.rated import RatedSpec, rated_for, ridge_point

BOUND_COMPUTE = "compute"
BOUND_MEMORY = "memory"
BOUND_COMM = "comm"
BOUNDS = (BOUND_COMPUTE, BOUND_MEMORY, BOUND_COMM)

# where the numbers under the verdict came from: XLA's compile-time
# cost analysis, or the probe's own analytic estimate (interpret
# mode / CPU / a JAX without the API) — model-sourced verdicts are
# informational and never gate against a TPU bar
COST_SOURCE_XLA = "xla"
COST_SOURCE_MODEL = "model"

# contract metric-name suffixes (docs/probes.md): every integrated
# probe exports `<prefix>-arithmetic-intensity` and
# `<prefix>-roofline-fraction` next to its existing gauges
INTENSITY_SUFFIX = "-arithmetic-intensity"
FRACTION_SUFFIX = "-roofline-fraction"

# fields of one contract `roofline` block entry (pinned beside the
# statusz schema contract): parsers gate on this, and the collector
# refuses entries without the load-bearing trio
VERDICT_FIELDS = (
    "bound",
    "intensity",
    "fraction",
    "ceiling_flops",
    "achieved_flops",
    "ridge",
    "cost_source",
    "flops",
    "hbm_bytes",
)


@dataclass(frozen=True)
class RooflineVerdict:
    """One kernel's position against its device roofline."""

    bound: str  # compute | memory | comm
    intensity: float  # FLOPs per HBM byte (comm: FLOPs per wire byte)
    fraction: float  # achieved / ceiling (the headline number)
    ceiling_flops: float  # FLOP/s this kernel could ever reach here
    achieved_flops: float  # FLOP/s it actually reached
    ridge: float  # the device ridge point used (FLOPs/byte)
    cost_source: str  # xla | model
    flops: float  # FLOPs per op (cost model)
    hbm_bytes: float  # HBM bytes per op (cost model)

    def to_dict(self) -> dict:
        return {
            "bound": self.bound,
            "intensity": self.intensity,
            "fraction": self.fraction,
            "ceiling_flops": self.ceiling_flops,
            "achieved_flops": self.achieved_flops,
            "ridge": self.ridge,
            "cost_source": self.cost_source,
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
        }


def classify(
    *,
    flops: float,
    hbm_bytes: float,
    seconds: float,
    spec: RatedSpec,
    cost_source: str = COST_SOURCE_MODEL,
) -> Optional[RooflineVerdict]:
    """Place one measured op on the device roofline. Pure math: the
    cost model (``flops``/``hbm_bytes`` per op) and the measured
    ``seconds`` per op come in as arguments. None when the inputs
    cannot support a verdict (a no-op cost model or a zero time)."""
    if flops <= 0 or hbm_bytes <= 0 or seconds <= 0:
        return None
    peak_flops = spec.bf16_tflops * 1e12
    hbm_bytes_per_s = spec.hbm_gbps * 1e9
    if peak_flops <= 0 or hbm_bytes_per_s <= 0:
        return None
    ridge = ridge_point(spec)
    intensity = flops / hbm_bytes
    # the classic roofline: below the ridge the ceiling is the
    # bandwidth line, above it the flat peak. The comparison is made
    # against ridge_point() — NOT the equivalent memory_ceiling <
    # peak_flops inequality — so the validated
    # ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE override really moves
    # the bound pivot (its whole purpose: silicon whose effective ridge
    # diverges from the paper numbers); without an override the two
    # formulations are identical.
    if intensity < ridge:
        # clamped to the flat peak: with an overridden ridge ABOVE the
        # derived one, I×B can exceed P — a physically impossible
        # ceiling that would deflate a healthy chip's fraction below
        # the rated floor (the docs define ceiling(I) = min(P, I×B))
        bound, ceiling = BOUND_MEMORY, min(peak_flops, intensity * hbm_bytes_per_s)
    else:
        bound, ceiling = BOUND_COMPUTE, peak_flops
    achieved = flops / seconds
    return RooflineVerdict(
        bound=bound,
        intensity=intensity,
        fraction=achieved / ceiling,
        ceiling_flops=ceiling,
        achieved_flops=achieved,
        ridge=ridge,
        cost_source=cost_source,
        flops=flops,
        hbm_bytes=hbm_bytes,
    )


def classify_comm(
    *,
    busbw_gbps: float,
    rated_busbw_gbps: float,
    payload_bytes: float = 0.0,
    flops: float = 0.0,
    cost_source: str = COST_SOURCE_MODEL,
) -> Optional[RooflineVerdict]:
    """Collective probes live on the ICI roofline, not the HBM one:
    their ceiling is the schedule's rated bus bandwidth (the very
    denominator the ``*-fraction-of-rated`` gauges divide by), the
    bound is ``comm`` by construction, and intensity is the (near-zero)
    FLOPs per wire byte — an all-reduce does one add per byte, which is
    the roofline argument for why it can never be compute-bound."""
    if busbw_gbps <= 0 or rated_busbw_gbps <= 0:
        return None
    intensity = (flops / payload_bytes) if payload_bytes > 0 else 0.0
    return RooflineVerdict(
        bound=BOUND_COMM,
        intensity=intensity,
        fraction=busbw_gbps / rated_busbw_gbps,
        ceiling_flops=rated_busbw_gbps * 1e9,  # byte/s ceiling, comm land
        achieved_flops=busbw_gbps * 1e9,
        ridge=0.0,
        cost_source=cost_source,
        flops=flops,
        hbm_bytes=payload_bytes,
    )


# ---------------------------------------------------------------------
# probe-side capture (the only jax-touching corner, imports kept lazy)
# ---------------------------------------------------------------------


@dataclass
class Capture:
    """What :func:`capture` hands a probe: contract metrics to append,
    the ``roofline`` block entry keyed by the probe's metric prefix,
    details to merge — or a structured skip reason (never both)."""

    prefix: str
    metrics: list
    block: Dict[str, dict]
    details: Dict[str, dict]
    skip_reason: str = ""

    @property
    def skipped(self) -> bool:
        return bool(self.skip_reason)


def skip_capture(prefix: str, reason: str) -> Capture:
    """A capture that could not produce a verdict records WHY in the
    details (the quick-mode/old-JAX/interpret contract: a missing
    roofline field must be a structured skip, not a silent omission).
    Public: probes use it when THEY know there is no roofline to stand
    on (e.g. int8 on a generation without an int8 MXU mode) — passing
    no spec instead would let :func:`capture`'s device fallback judge
    the kernel against the wrong roofline."""
    return Capture(
        prefix=prefix,
        metrics=[],
        block={},
        details={"roofline": {prefix: {"skipped": reason}}},
        skip_reason=reason,
    )


_skip = skip_capture


def memory_snapshot(device=None) -> Optional[dict]:
    """Per-phase device-memory snapshot: peak HBM vs limit plus live
    buffer bytes from the PJRT runtime, or None where the runtime does
    not expose ``memory_stats`` (interpret mode, tunneled devices)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    snapshot = {
        "hbm_peak_bytes": float(stats.get("peak_bytes_in_use", 0) or 0),
        "hbm_live_bytes": float(stats.get("bytes_in_use", 0) or 0),
        "hbm_limit_bytes": float(stats.get("bytes_limit", 0) or 0),
    }
    return snapshot if any(snapshot.values()) else None


def capture(
    prefix: str,
    *,
    seconds: float,
    fn=None,
    args: Sequence = (),
    xla_cost: Optional[dict] = None,
    model_flops: Optional[float] = None,
    model_bytes: Optional[float] = None,
    spec: Optional[RatedSpec] = None,
    device=None,
    enabled: bool = True,
) -> Capture:
    """The whole probe-side pipeline for one measured op.

    On TPU the cost model comes from XLA's compile-time analysis —
    ``xla_cost`` when the probe already holds a normalized analysis of
    the very executable it timed (``utils/compat.compiled_cost_analysis``
    on an AOT-compiled object; no second compile), else a fresh
    lower+compile of ``fn(*args)`` — labeled ``cost_source: xla``, with
    the probe's analytic ``model_flops``/``model_bytes`` as the old-JAX
    fallback. Off-TPU the analytic model is used directly
    (interpret-mode lowerings cost nothing like the real kernels, so
    their XLA numbers would be evidence-shaped noise) and the verdict
    carries ``cost_source: model``. A fraction/bound verdict
    additionally needs a rated spec — absent one (unknown silicon, CPU)
    the intensity is still exported and the skip is recorded
    structurally in the details.
    """
    if not enabled:
        return _skip(prefix, "disabled (--no-roofline)")
    try:
        return _capture(
            prefix,
            seconds=seconds,
            fn=fn,
            args=args,
            xla_cost=xla_cost,
            model_flops=model_flops,
            model_bytes=model_bytes,
            spec=spec,
            device=device,
        )
    except Exception as e:  # a roofline bug must never fail the probe
        return _skip(prefix, f"capture failed: {e!r}"[:200])


def _capture(
    prefix: str,
    *,
    seconds: float,
    fn,
    args: Sequence,
    xla_cost: Optional[dict],
    model_flops: Optional[float],
    model_bytes: Optional[float],
    spec: Optional[RatedSpec],
    device,
) -> Capture:
    import jax

    if device is None:
        device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    cost = None
    cost_source = COST_SOURCE_MODEL
    if on_tpu and xla_cost is not None:
        cost = dict(xla_cost)
        cost_source = COST_SOURCE_XLA
    elif on_tpu and fn is not None:
        from activemonitor_tpu.utils.compat import compile_cost_analysis

        cost = compile_cost_analysis(fn, *args)
        if cost is not None:
            cost_source = COST_SOURCE_XLA
    if cost is None:
        if model_flops is None or model_bytes is None:
            reason = (
                "cost analysis unavailable"
                if on_tpu
                else f"interpret mode on {device.platform}"
            ) + " and the probe supplied no analytic model"
            return _skip(prefix, reason)
        cost = {"flops": float(model_flops), "bytes_accessed": float(model_bytes)}
    flops = cost["flops"]
    hbm_bytes = cost["bytes_accessed"]
    if flops <= 0 or hbm_bytes <= 0 or seconds <= 0:
        return _skip(
            prefix,
            f"degenerate cost model (flops={flops}, bytes={hbm_bytes}, "
            f"seconds={seconds})",
        )
    source_word = "XLA" if cost_source == COST_SOURCE_XLA else "analytic"
    metrics = [
        ProbeMetric(
            prefix + INTENSITY_SUFFIX,
            flops / hbm_bytes,
            help="Arithmetic intensity (FLOPs per HBM byte) from the "
            f"{source_word} cost model",
        )
    ]
    if spec is None and on_tpu:
        spec = rated_for(device.device_kind)
    if spec is None:
        # intensity without a verdict: there is no rated roofline to
        # stand this measurement against (never a TPU-bar comparison)
        entry = {"skipped": f"no rated roofline for {device.device_kind!r}"}
        return Capture(
            prefix=prefix,
            metrics=metrics,
            block={},
            details={"roofline": {prefix: entry}},
        )
    verdict = classify(
        flops=flops,
        hbm_bytes=hbm_bytes,
        seconds=seconds,
        spec=spec,
        cost_source=cost_source,
    )
    if verdict is None:
        return _skip(prefix, "classification rejected the cost model")
    metrics.append(
        ProbeMetric(
            prefix + FRACTION_SUFFIX,
            verdict.fraction,
            help=f"Achieved / {verdict.bound}-bound roofline ceiling "
            f"({source_word} cost model)",
        )
    )
    entry = verdict.to_dict()
    snapshot = memory_snapshot(device)
    if snapshot is not None:
        entry.update(snapshot)
    return Capture(
        prefix=prefix,
        metrics=metrics,
        block={prefix: entry},
        details={"roofline": {prefix: entry}},
    )


def comm_capture(
    prefix: str,
    *,
    busbw_gbps: float,
    rated_busbw_gbps: Optional[float],
    payload_bytes: float = 0.0,
    flops: float = 0.0,
    device=None,
    enabled: bool = True,
) -> Capture:
    """:func:`capture`'s sibling for collective probes (ICI roofline).
    The probes already measured busbw and already know their rated
    schedule ceiling; this folds both into the same verdict/contract
    shape the compute/memory captures produce."""
    if not enabled:
        return _skip(prefix, "disabled (--no-roofline)")
    try:
        if rated_busbw_gbps is None or rated_busbw_gbps <= 0:
            return _skip(prefix, "no rated ICI ceiling for this hardware")
        verdict = classify_comm(
            busbw_gbps=busbw_gbps,
            rated_busbw_gbps=rated_busbw_gbps,
            payload_bytes=payload_bytes,
            flops=flops,
            cost_source=COST_SOURCE_MODEL,
        )
        if verdict is None:
            return _skip(prefix, "degenerate bandwidth measurement")
        metrics = [
            ProbeMetric(
                prefix + INTENSITY_SUFFIX,
                verdict.intensity,
                help="FLOPs per wire byte (collectives are comm-bound "
                "by construction)",
            ),
            ProbeMetric(
                prefix + FRACTION_SUFFIX,
                verdict.fraction,
                help="Achieved busbw / rated ICI roofline ceiling",
            ),
        ]
        entry = verdict.to_dict()
        snapshot = memory_snapshot(device)
        if snapshot is not None:
            entry.update(snapshot)
        return Capture(
            prefix=prefix,
            metrics=metrics,
            block={prefix: entry},
            details={"roofline": {prefix: entry}},
        )
    except Exception as e:
        return _skip(prefix, f"capture failed: {e!r}"[:200])


def apply(result, *captures) -> None:
    """Fold captures into a :class:`ProbeResult` in place: metrics
    appended, block entries merged into ``result.roofline`` (the stdout
    contract), details merged under ``details["roofline"]`` (verdicts
    AND structured skips — the silent-omission ban)."""
    for cap in captures:
        result.metrics.extend(cap.metrics)
        result.roofline.update(cap.block)
        merged = result.details.setdefault("roofline", {})
        merged.update(cap.details.get("roofline", {}))


# ---------------------------------------------------------------------
# controller-side reading (contract block → /statusz, CLI, attribution)
# ---------------------------------------------------------------------


def valid_entry(entry) -> bool:
    """A contract ``roofline`` block entry the controller will trust:
    the load-bearing trio present, numeric AND finite (JSON happily
    round-trips NaN/Infinity, which would poison the worst-fraction
    min(), the gauges, and strict-JSON /statusz consumers), the bound
    in vocabulary. Anything else is version drift and must be dropped,
    not guessed at."""
    if not isinstance(entry, dict):
        return False
    if entry.get("bound") not in BOUNDS:
        return False
    for field_name in ("intensity", "fraction"):
        value = entry.get(field_name)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return False
        if not math.isfinite(value):
            return False
    return True


def verdict_line(entry: dict) -> str:
    """The one-phrase evidence citation attribution/why lines carry:
    ``0.41 of memory-bound ceiling (xla cost model)``."""
    return "{:.2g} of {}-bound ceiling ({} cost model)".format(
        entry.get("fraction", 0.0),
        entry.get("bound", "?"),
        entry.get("cost_source", "?"),
    )


def entry_for_metric(
    roofline: Optional[Dict[str, dict]], metric: str
) -> Optional[dict]:
    """The roofline block entry whose prefix underlies ``metric``
    (longest prefix wins: ``mxu-int8-fraction-of-rated`` must match the
    ``mxu-int8`` entry, not ``mxu``), or None."""
    if not roofline:
        return None
    best = None
    for prefix, entry in roofline.items():
        if metric == prefix or metric.startswith(prefix + "-"):
            if valid_entry(entry) and (best is None or len(prefix) > len(best[0])):
                best = (prefix, entry)
    return best[1] if best else None


def summarize_result(result) -> Optional[dict]:
    """One :class:`CheckResult`'s roofline snapshot for /statusz /
    ``am-tpu roofline`` / flight bundles — None when the run carried no
    (valid) block. Invalid entries are filtered here so every surface
    downstream sees only trusted verdicts."""
    block = {
        prefix: entry
        for prefix, entry in (getattr(result, "roofline", None) or {}).items()
        if valid_entry(entry)
    }
    if not block:
        return None
    worst = min(block.items(), key=lambda kv: kv[1]["fraction"])
    return {
        "ts": result.ts.isoformat(),
        "trace_id": result.trace_id,
        "metrics": block,
        "worst": worst[0],
        "worst_fraction": worst[1]["fraction"],
        "worst_bound": worst[1]["bound"],
    }


def latest_snapshot(results: Sequence) -> Optional[dict]:
    """The newest run that shipped a roofline block (runs without one —
    quick mode, old probes — do not blank the evidence)."""
    for result in reversed(list(results)):
        snapshot = summarize_result(result)
        if snapshot is not None:
            return snapshot
    return None
