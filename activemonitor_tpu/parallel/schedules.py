"""Collective algorithm zoo — explicit ppermute schedules for
all-reduce / all-gather (ROADMAP item 2, the Demystifying-NCCL family).

``parallel/collectives.py`` times the XLA-built-in collectives (psum /
all_gather) plus raw ring hops; this module implements the classical
alternative *schedules* as explicit ``ppermute`` compositions so each
regime of the latency-vs-bandwidth tradeoff has a measurable
representative:

- **ring reduce-scatter + all-gather** (``all_reduce_rsag``) — the
  NCCL ring decomposition: 2(n−1) rounds of (shard/n)-sized chunks.
  Bandwidth-optimal (per-device wire volume 2(n−1)/n × S, the
  theoretical minimum), latency-poor (rounds grow linearly with n).
- **recursive doubling/halving** (``all_reduce_recdouble``) — log2(n)
  full-payload pairwise exchanges. Latency-optimal (fewest rounds),
  bandwidth-poor (log2(n) × S wire volume). Power-of-two native; other
  sizes fold the remainder ranks in/out with one extra round each way.
- **binomial tree reduce + broadcast** (``all_reduce_tree``) —
  2·ceil(log2 n) rounds, each a one-direction full-payload hop; the
  logical tree NCCL uses for small payloads on high-diameter rings.
- **ring all-gather** (``all_gather_ring``) and **recursive-doubling
  all-gather** (``all_gather_recdouble``) — the same two regimes for
  the gather family (recdouble falls back to the ring off power-of-two
  sizes, where block-doubling has no clean pairing).

Every schedule is shape-polymorphic (rsag pads odd rows internally),
numerically equivalent to the ``jax.lax.psum`` / ``all_gather``
reference (tests/test_schedules.py: allclose across meshes n∈{2,3,4,8},
bitwise where the schedule only moves data), and traced through the
``_hop`` choke point so the PR-5 hop-budget contract applies: each
schedule sends exactly its theoretical round count (``theoretical_hops``)
— asserted by tests, not asserted in comments.

Timed wrappers (``*_bandwidth``) reuse the chain-delta scaffold and
``CollectiveResult``/busbw accounting from parallel/collectives.py, so
zoo numbers are directly comparable against the XLA baselines; the
per-schedule *rated ceilings* (wire volume ≠ busbw convention) live in
probes/collectives._rated_busbw.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel.collectives import CollectiveResult, _bench
from activemonitor_tpu.utils.compat import axis_size
from jax.sharding import Mesh


# Schedule tokens, in the spelling the probes/autotuner/docs share.
# "xla" is the psum/all_gather builtin the zoo is raced against.
ALL_REDUCE_SCHEDULES = ("xla", "rsag", "recdouble", "tree")
ALL_GATHER_SCHEDULES = ("xla", "ring", "recdouble")

# Test hook (the ops/ring_attention.py pattern): when set to a list,
# every ppermute round a schedule issues appends (schedule_tag, round).
# Schedules unroll python loops, so one traced application logs each
# round individually and the log length IS the hop count.
_HOP_LOG = None


def _hop(x, axis_name, perm, tag, step):
    """One ppermute round, routed through a single site so the traced
    hop counter sees every transfer a schedule issues."""
    if _HOP_LOG is not None:
        _HOP_LOG.append((tag, step))
    return jax.lax.ppermute(x, axis_name, perm)


def _resolve_n(axis_name, n=None) -> int:
    return int(n) if n is not None else axis_size(axis_name)


def theoretical_hops(schedule: str, n: int, collective: str = "allreduce") -> int:
    """Rounds (ppermute calls) schedule issues on an n-device axis —
    the contract the hop-budget tests pin.

    The public token "recdouble" names a different algorithm per
    family (ALL_REDUCE_SCHEDULES vs ALL_GATHER_SCHEDULES), so pass
    ``collective="allgather"`` for the gather variant — its non-pow2
    fallback is the ring (n−1 hops), not the fold/unfold."""
    if collective == "allgather":
        schedule = {"recdouble": "ag-recdouble"}.get(schedule, schedule)
    if n <= 1:
        return 0
    p = 1 << (n.bit_length() - 1)  # largest power of two ≤ n
    r = n - p
    if schedule == "rsag":
        return 2 * (n - 1)
    if schedule == "recdouble":
        return int(math.log2(p)) + (2 if r else 0)
    if schedule == "tree":
        return 2 * math.ceil(math.log2(n))
    if schedule == "ring":  # all-gather ring
        return n - 1
    if schedule == "ag-recdouble":
        # falls back to the ring off power-of-two sizes
        return int(math.log2(n)) if r == 0 else n - 1
    raise ValueError(f"unknown schedule {schedule!r}")


# ---------------------------------------------------------------------------
# all-reduce schedules (per-shard x → per-shard sum over axis)
# ---------------------------------------------------------------------------


def all_reduce_rsag(x, axis_name: str, n: int | None = None):
    """Ring reduce-scatter + all-gather (the NCCL ring decomposition).

    Phase 1 rotates (shard/n)-chunks clockwise n−1 times, accumulating
    so device i ends holding the fully-reduced chunk (i+1) mod n; phase
    2 rotates the reduced chunks n−1 more times to rebuild the full
    sum everywhere. 2(n−1) rounds of S/n bytes — the bandwidth-optimal
    2(n−1)/n × S wire volume. Rows that don't divide n are zero-padded
    for the rotation and trimmed after (zeros are psum-neutral).
    """
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    rows = x.shape[0]
    pad = (-rows) % n
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    chunk = x.shape[0] // n
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def take(j):
        return jax.lax.dynamic_slice_in_dim(x, j * chunk, chunk, axis=0)

    # reduce-scatter: after round s the arriving partial is of chunk
    # (idx − s − 1) mod n; add the local copy and pass it on
    buf = take(idx)
    for s in range(n - 1):
        buf = _hop(buf, axis_name, perm, "rsag-rs", s)
        buf = buf + take((idx - s - 1) % n)
    # all-gather: own reduced chunk is (idx + 1) mod n; each further
    # round delivers chunk (idx − s) mod n from the left neighbor
    out = jnp.zeros_like(x)
    out = jax.lax.dynamic_update_slice_in_dim(
        out, buf, ((idx + 1) % n) * chunk, axis=0
    )
    cur = buf
    for s in range(n - 1):
        cur = _hop(cur, axis_name, perm, "rsag-ag", s)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, cur, ((idx - s) % n) * chunk, axis=0
        )
    return out[:rows] if pad else out


def all_reduce_recdouble(x, axis_name: str, n: int | None = None):
    """Recursive doubling: log2(n) full-payload pairwise exchanges
    (partner = idx XOR 2^s), latency-optimal. Off power-of-two sizes
    the r = n − 2^⌊log2 n⌋ remainder ranks fold their vector into rank
    (idx − p) first and receive the finished sum back last — one extra
    round each way, the standard MPI_Allreduce fixup."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    p = 1 << (n.bit_length() - 1)
    r = n - p
    idx = jax.lax.axis_index(axis_name)
    step = 0
    if r:
        # fold: ranks p+j send into j (non-destinations receive zeros)
        fold = [(p + j, j) for j in range(r)]
        x = x + _hop(x, axis_name, fold, "recdouble-fold", step)
        step += 1
    bit = 1
    while bit < p:
        pairs = [(i, i ^ bit) for i in range(p)]
        x = x + _hop(x, axis_name, pairs, "recdouble-xchg", step)
        bit <<= 1
        step += 1
    if r:
        # unfold: ranks j broadcast the finished sum back to p+j
        unfold = [(j, p + j) for j in range(r)]
        got = _hop(x, axis_name, unfold, "recdouble-unfold", step)
        x = jnp.where(idx >= p, got, x)
    return x


def all_reduce_tree(x, axis_name: str, n: int | None = None):
    """Binomial-tree reduce to rank 0, then binomial broadcast back:
    2·ceil(log2 n) one-direction full-payload rounds. Works for any n
    (ranks whose partner would fall off the end just sit the round
    out); the latency/bandwidth middle ground NCCL's tree algorithm
    occupies."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    rounds = math.ceil(math.log2(n))
    idx = jax.lax.axis_index(axis_name)
    # reduce: at round s, ranks ≡ 2^s (mod 2^{s+1}) send down to
    # idx − 2^s and retire; non-receivers add zeros
    for s in range(rounds):
        stride = 1 << s
        pairs = [
            (i, i - stride) for i in range(n) if i % (2 * stride) == stride
        ]
        x = x + _hop(x, axis_name, pairs, "tree-reduce", s)
    # broadcast: mirror image, receivers REPLACE their (stale) vector
    for s in reversed(range(rounds)):
        stride = 1 << s
        pairs = [
            (i, i + stride)
            for i in range(n)
            if i % (2 * stride) == 0 and i + stride < n
        ]
        got = _hop(x, axis_name, pairs, "tree-bcast", s)
        x = jnp.where(idx % (2 * stride) == stride, got, x)
    return x


# ---------------------------------------------------------------------------
# all-gather schedules (per-shard x[rows,...] → concatenated [n*rows,...])
# ---------------------------------------------------------------------------


def all_gather_ring(x, axis_name: str, n: int | None = None):
    """Ring all-gather: rotate shards clockwise n−1 times, placing each
    arrival at its owner's slot — tiled output ([n·rows, ...], device
    order), bitwise-identical to ``lax.all_gather(..., tiled=True)``."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    rows = x.shape[0]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((n * rows,) + x.shape[1:], x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x, idx * rows, axis=0)
    cur = x
    for s in range(n - 1):
        cur = _hop(cur, axis_name, perm, "ag-ring", s)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, cur, ((idx - s - 1) % n) * rows, axis=0
        )
    return out


def all_gather_recdouble(x, axis_name: str, n: int | None = None):
    """Recursive-doubling all-gather: log2(n) exchanges, the gathered
    block doubling each round (partner = idx XOR 2^s; the half owning
    the lower ranks prepends what it receives). Power-of-two only —
    other sizes fall back to the ring schedule, where the ISSUE-pinned
    hop contract records n−1 ring hops instead."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    if n & (n - 1):
        return all_gather_ring(x, axis_name, n)
    idx = jax.lax.axis_index(axis_name)
    g = x
    bit = 1
    step = 0
    while bit < n:
        pairs = [(i, i ^ bit) for i in range(n)]
        got = _hop(g, axis_name, pairs, "ag-recdouble", step)
        # partner above me: my block comes first; partner below: second
        g = jnp.where(
            (idx & bit) == 0,
            jnp.concatenate([g, got], axis=0),
            jnp.concatenate([got, g], axis=0),
        )
        bit <<= 1
        step += 1
    return g


# ---------------------------------------------------------------------------
# timed wrappers — CollectiveResult/busbw accounting shared with the
# XLA baselines (parallel/collectives._bench)
# ---------------------------------------------------------------------------


def _allreduce_bench(name: str, schedule_fn):
    def bench(
        mesh: Mesh,
        size_mb: float = 64.0,
        dtype=jnp.bfloat16,
        iters: int = 5,
        axis: str = "",
    ) -> CollectiveResult:
        def make_body(n, ax):
            inv_n = jnp.asarray(1.0 / n, dtype)
            return lambda x: schedule_fn(x, ax, n) * inv_n  # mean: stable chain

        return _bench(
            name, mesh, axis, size_mb, dtype, iters, make_body,
            rows_multiple_of_n=True,  # time the rotation, not the padding
            busbw_factor=lambda n: 2 * (n - 1) / n,
        )

    return bench


all_reduce_rsag_bandwidth = _allreduce_bench("all_reduce_rsag", all_reduce_rsag)
all_reduce_recdouble_bandwidth = _allreduce_bench(
    "all_reduce_recdouble", all_reduce_recdouble
)
all_reduce_tree_bandwidth = _allreduce_bench("all_reduce_tree", all_reduce_tree)


def _allgather_bench(name: str, schedule_fn):
    def bench(
        mesh: Mesh,
        size_mb: float = 64.0,
        dtype=jnp.bfloat16,
        iters: int = 5,
        axis: str = "",
    ) -> CollectiveResult:
        def make_body(n, ax):
            inv_n = jnp.asarray(1.0 / n, dtype)

            def body(x):
                g = schedule_fn(x, ax, n)  # [n*rows, cols]
                return jnp.sum(g.reshape((n,) + x.shape), axis=0) * inv_n

            return body

        n = mesh.shape[axis or mesh.axis_names[0]]
        return _bench(
            name, mesh, axis, size_mb, dtype, iters, make_body,
            payload_mult=float(n),  # NCCL all-gather: total gathered data
            busbw_factor=lambda n: (n - 1) / n,
        )

    return bench


all_gather_ring_bandwidth = _allgather_bench("all_gather_ring", all_gather_ring)
all_gather_recdouble_bandwidth = _allgather_bench(
    "all_gather_recdouble", all_gather_recdouble
)
