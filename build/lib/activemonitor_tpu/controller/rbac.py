"""Per-check RBAC provisioning.

Every HealthCheck gets its own ServiceAccount plus a least-privilege
(Cluster)Role and binding, scoped by ``spec.level``; remedy workflows
get a separate, write-capable identity that is created per run and
deleted after (reference: healthcheck_controller.go:302-474 and the
CRUD helpers :1128-1443).

Semantics preserved:

- read-only defaults for checks vs write defaults for remedies
  (reference: :85-120), overridable per-spec via rbacRules (:124-129)
- SA-name collision between check and remedy auto-renames the remedy SA
  to ``<sa>-remedy`` (:316-319)
- deletes are guarded by the managed-by label so user-owned objects are
  never removed (:1169,:1242 etc.)
- missing level / missing remedy SA / nil remedy resource are errors
  (:327-329,:409-412,:312-315)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

from activemonitor_tpu.api.types import (
    HealthCheck,
    PolicyRule,
    LEVEL_CLUSTER,
    LEVEL_NAMESPACE,
    WORKFLOW_TYPE_REMEDY,
)
from activemonitor_tpu.kube import ApiError, api_path, core_path

# labels (reference: healthcheck_controller.go:67-68)
MANAGED_BY_LABEL_KEY = "workflows.argoproj.io/managed-by"
MANAGED_BY_VALUE = "active-monitor"

# reference: healthcheck_controller.go:85-101
DEFAULT_HEALTHCHECK_RULES = [
    PolicyRule(
        api_groups=[""],
        resources=[
            "pods", "nodes", "events", "services", "configmaps",
            "namespaces", "endpoints",
        ],
        verbs=["get", "list", "watch"],
    ),
    PolicyRule(
        api_groups=["apps"],
        resources=["deployments", "replicasets", "statefulsets", "daemonsets"],
        verbs=["get", "list", "watch"],
    ),
    PolicyRule(
        api_groups=["argoproj.io"],
        resources=["workflows"],
        verbs=["get", "list", "watch"],
    ),
    # divergence from the reference defaults (which predate Argo 3.4):
    # the Argo executor sidecar reports step results via
    # workflowtaskresults, so probe pods without this grant fail to
    # report on modern Argo. Write access is scoped to exactly that
    # reporting resource; everything else stays read-only.
    PolicyRule(
        api_groups=["argoproj.io"],
        resources=["workflowtaskresults"],
        verbs=["create", "patch"],
    ),
]

# reference: healthcheck_controller.go:104-120
DEFAULT_REMEDY_RULES = [
    PolicyRule(
        api_groups=[""],
        resources=["pods", "events", "services", "configmaps", "endpoints"],
        verbs=["get", "list", "watch", "create", "update", "patch", "delete"],
    ),
    PolicyRule(
        api_groups=["apps"],
        resources=["deployments", "replicasets", "statefulsets"],
        verbs=["get", "list", "watch", "create", "update", "patch", "delete"],
    ),
    PolicyRule(
        api_groups=["argoproj.io"],
        resources=["workflows"],
        verbs=["get", "list", "watch", "create", "update", "patch", "delete"],
    ),
]


def resolve_rbac_rules(
    custom: List[PolicyRule], defaults: List[PolicyRule]
) -> List[PolicyRule]:
    """Custom rules win when provided (reference: healthcheck_controller.go:124-129)."""
    return custom if custom else defaults


class RBACError(RuntimeError):
    pass


@dataclass
class RBACObject:
    kind: str  # ServiceAccount | ClusterRole | ClusterRoleBinding | Role | RoleBinding
    name: str
    namespace: str = ""  # empty for cluster-scoped
    rules: List[PolicyRule] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    subject: str = ""  # SA name for bindings
    role_ref: str = ""  # role name for bindings


class RBACBackend(Protocol):
    """Storage for RBAC objects (Kubernetes API in cluster mode,
    in-memory store elsewhere/tests, like the reference unit tests'
    fake clientset, healthcheck_controller_unit_test.go:312)."""

    async def get(self, kind: str, namespace: str, name: str) -> Optional[RBACObject]: ...

    async def create(self, obj: RBACObject) -> RBACObject: ...

    async def delete(self, kind: str, namespace: str, name: str) -> None: ...


class InMemoryRBACBackend:
    def __init__(self):
        self.objects: Dict[tuple, RBACObject] = {}

    @staticmethod
    def _key(kind: str, namespace: str, name: str) -> tuple:
        return (kind, namespace, name)

    async def get(self, kind: str, namespace: str, name: str) -> Optional[RBACObject]:
        return self.objects.get(self._key(kind, namespace, name))

    async def create(self, obj: RBACObject) -> RBACObject:
        self.objects[self._key(obj.kind, obj.namespace, obj.name)] = obj
        return obj

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        self.objects.pop(self._key(kind, namespace, name), None)


class KubernetesRBACBackend:
    """Real cluster state: ServiceAccounts, (Cluster)Roles and bindings
    created through the API server, like the reference's typed-clientset
    helpers (reference: healthcheck_controller.go:1128-1443). The
    :class:`RBACObject` ↔ manifest mapping lives here so the
    provisioner stays backend-agnostic."""

    RBAC_GROUP = "rbac.authorization.k8s.io"
    RBAC_VERSION = "v1"
    _PLURALS = {
        "ClusterRole": "clusterroles",
        "ClusterRoleBinding": "clusterrolebindings",
        "Role": "roles",
        "RoleBinding": "rolebindings",
    }

    def __init__(self, api):
        self._api = api

    def _path(self, kind: str, namespace: str, name: str = "") -> str:
        if kind == "ServiceAccount":
            return core_path("serviceaccounts", namespace, name)
        plural = self._PLURALS[kind]
        # Cluster* kinds are cluster-scoped regardless of the namespace arg
        scoped_ns = "" if kind.startswith("Cluster") else namespace
        return api_path(self.RBAC_GROUP, self.RBAC_VERSION, plural, scoped_ns, name)

    # -- RBACObject <-> manifest ---------------------------------------
    def _to_manifest(self, obj: RBACObject) -> dict:
        meta = {"name": obj.name, "labels": dict(obj.labels)}
        if obj.namespace and not obj.kind.startswith("Cluster"):
            meta["namespace"] = obj.namespace
        manifest: dict = {"metadata": meta}
        if obj.kind == "ServiceAccount":
            manifest["apiVersion"] = "v1"
            manifest["kind"] = "ServiceAccount"
        elif obj.kind in ("ClusterRole", "Role"):
            manifest["apiVersion"] = f"{self.RBAC_GROUP}/{self.RBAC_VERSION}"
            manifest["kind"] = obj.kind
            manifest["rules"] = [
                {
                    "apiGroups": r.api_groups,
                    "resources": r.resources,
                    "verbs": r.verbs,
                }
                for r in obj.rules
            ]
        elif obj.kind in ("ClusterRoleBinding", "RoleBinding"):
            manifest["apiVersion"] = f"{self.RBAC_GROUP}/{self.RBAC_VERSION}"
            manifest["kind"] = obj.kind
            sa_namespace, _, sa_name = obj.subject.partition("/")
            manifest["subjects"] = [
                {
                    "kind": "ServiceAccount",
                    "name": sa_name,
                    "namespace": sa_namespace,
                }
            ]
            manifest["roleRef"] = {
                "apiGroup": self.RBAC_GROUP,
                "kind": "ClusterRole" if obj.kind == "ClusterRoleBinding" else "Role",
                "name": obj.role_ref,
            }
        else:
            raise RBACError(f"unknown RBAC kind {obj.kind!r}")
        return manifest

    @staticmethod
    def _from_manifest(kind: str, namespace: str, manifest: dict) -> RBACObject:
        meta = manifest.get("metadata", {})
        subject = ""
        if manifest.get("subjects"):
            s = manifest["subjects"][0]
            subject = f"{s.get('namespace', '')}/{s.get('name', '')}"
        return RBACObject(
            kind=kind,
            name=meta.get("name", ""),
            namespace="" if kind.startswith("Cluster") else namespace,
            rules=[
                PolicyRule(
                    api_groups=r.get("apiGroups", []),
                    resources=r.get("resources", []),
                    verbs=r.get("verbs", []),
                )
                for r in manifest.get("rules", [])
            ],
            labels=meta.get("labels", {}) or {},
            subject=subject,
            role_ref=(manifest.get("roleRef") or {}).get("name", ""),
        )

    # -- backend protocol ----------------------------------------------
    async def get(self, kind: str, namespace: str, name: str) -> Optional[RBACObject]:
        try:
            manifest = await self._api.get(self._path(kind, namespace, name))
        except ApiError as e:
            if e.not_found:
                return None
            raise
        return self._from_manifest(kind, namespace, manifest)

    async def create(self, obj: RBACObject) -> RBACObject:
        try:
            await self._api.create(
                self._path(obj.kind, obj.namespace), self._to_manifest(obj)
            )
        except ApiError as e:
            # lost race with a concurrent creator: the object exists,
            # which is all _ensure() wants (reference idempotent create,
            # healthcheck_controller.go:1129-1135)
            if not e.conflict:
                raise
        return obj

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            await self._api.delete(self._path(kind, namespace, name))
        except ApiError as e:
            if not e.not_found:
                raise


class RBACProvisioner:
    def __init__(self, backend: RBACBackend):
        self._backend = backend

    # -- create path ---------------------------------------------------
    async def create_rbac_for_workflow(
        self, hc: HealthCheck, workflow_type: str
    ) -> None:
        """Provision SA + role + binding for a check or remedy run
        (reference: healthcheck_controller.go:302-415)."""
        level = hc.spec.level
        wf = hc.spec.workflow
        if wf.resource is None:
            raise RBACError("workflow resource is nil")
        hc_sa = wf.resource.service_account
        wf_namespace = wf.resource.namespace

        remedy_sa = ""
        remedy_namespace = ""
        if not hc.spec.remedy_workflow.is_empty():
            remedy = hc.spec.remedy_workflow
            if remedy.resource is None:
                raise RBACError("RemedyWorkflow is set but Resource is nil")
            if not remedy.resource.service_account:
                raise RBACError("ServiceAccount for the RemedyWorkflow is not specified")
            # collision rename (reference: :316-319) — mutates the spec
            # in memory exactly like the reference does
            if remedy.resource.service_account == hc_sa:
                remedy.resource.service_account = hc_sa + "-remedy"
            remedy_sa = remedy.resource.service_account
            remedy_namespace = remedy.resource.namespace

        if workflow_type == WORKFLOW_TYPE_REMEDY:
            sa, namespace = remedy_sa, remedy_namespace
            rules = resolve_rbac_rules(
                hc.spec.remedy_workflow.rbac_rules, DEFAULT_REMEDY_RULES
            )
        else:
            sa, namespace = hc_sa, wf_namespace
            rules = resolve_rbac_rules(hc.spec.workflow.rbac_rules, DEFAULT_HEALTHCHECK_RULES)

        await self._ensure(
            RBACObject(
                kind="ServiceAccount",
                name=sa,
                namespace=namespace,
                labels={MANAGED_BY_LABEL_KEY: MANAGED_BY_VALUE},
            )
        )

        if level == LEVEL_CLUSTER:
            role_name = f"{sa}-cluster-role"
            await self._ensure(
                RBACObject(
                    kind="ClusterRole",
                    name=role_name,
                    rules=rules,
                    labels={MANAGED_BY_LABEL_KEY: MANAGED_BY_VALUE},
                )
            )
            await self._ensure(
                RBACObject(
                    kind="ClusterRoleBinding",
                    name=f"{sa}-cluster-role-binding",
                    subject=f"{namespace}/{sa}",
                    role_ref=role_name,
                    labels={MANAGED_BY_LABEL_KEY: MANAGED_BY_VALUE},
                )
            )
        elif level == LEVEL_NAMESPACE:
            role_name = f"{sa}-ns-role"
            await self._ensure(
                RBACObject(
                    kind="Role",
                    name=role_name,
                    namespace=namespace,
                    rules=rules,
                    labels={MANAGED_BY_LABEL_KEY: MANAGED_BY_VALUE},
                )
            )
            await self._ensure(
                RBACObject(
                    kind="RoleBinding",
                    name=f"{sa}-ns-role-binding",
                    namespace=namespace,
                    subject=f"{namespace}/{sa}",
                    role_ref=role_name,
                    labels={MANAGED_BY_LABEL_KEY: MANAGED_BY_VALUE},
                )
            )
        else:
            raise RBACError("level is not set")

    async def _ensure(self, obj: RBACObject) -> None:
        """Idempotent create: an existing object is reused untouched
        (reference: healthcheck_controller.go:1129-1135)."""
        existing = await self._backend.get(obj.kind, obj.namespace, obj.name)
        if existing is None:
            await self._backend.create(obj)

    # -- delete path (remedy RBAC is ephemeral) ------------------------
    async def delete_rbac_for_workflow(self, hc: HealthCheck) -> None:
        """Delete the remedy's SA/role/binding after its run
        (reference: healthcheck_controller.go:417-474). Objects without
        our managed-by label are left alone."""
        remedy = hc.spec.remedy_workflow
        if remedy.resource is None:
            return  # nothing to clean up (reference: :418-421)
        level = hc.spec.level
        sa = remedy.resource.service_account
        namespace = remedy.resource.namespace

        await self._delete_if_managed("ServiceAccount", namespace, sa)
        if level == LEVEL_CLUSTER:
            await self._delete_if_managed("ClusterRole", "", f"{sa}-cluster-role")
            await self._delete_if_managed(
                "ClusterRoleBinding", "", f"{sa}-cluster-role-binding"
            )
        elif level == LEVEL_NAMESPACE:
            await self._delete_if_managed("Role", namespace, f"{sa}-ns-role")
            await self._delete_if_managed(
                "RoleBinding", namespace, f"{sa}-ns-role-binding"
            )
        else:
            raise RBACError("level is not set")

    async def _delete_if_managed(self, kind: str, namespace: str, name: str) -> None:
        obj = await self._backend.get(kind, namespace, name)
        if obj is None:
            return
        if obj.labels.get(MANAGED_BY_LABEL_KEY) != MANAGED_BY_VALUE:
            return  # not ours — leave it (reference delete guard)
        await self._backend.delete(kind, namespace, name)
