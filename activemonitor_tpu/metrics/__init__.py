"""Prometheus metrics collectors."""

from activemonitor_tpu.metrics.collector import (
    CONTROLLER_NAME,
    LABEL_HC,
    LABEL_WF,
    MetricsCollector,
    RECONCILE_ERROR,
    RECONCILE_REQUEUE_AFTER,
    RECONCILE_SUCCESS,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
    WORKQUEUE_NAME,
)

__all__ = [
    "CONTROLLER_NAME",
    "LABEL_HC",
    "LABEL_WF",
    "MetricsCollector",
    "RECONCILE_ERROR",
    "RECONCILE_REQUEUE_AFTER",
    "RECONCILE_SUCCESS",
    "WORKFLOW_LABEL_HEALTHCHECK",
    "WORKFLOW_LABEL_REMEDY",
    "WORKQUEUE_NAME",
]
