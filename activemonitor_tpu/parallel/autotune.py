"""Message-size autotuner over the collective algorithm zoo.

The zoo (parallel/schedules.py) gives 2–3 schedules per collective,
each winning a distinct latency-vs-bandwidth regime (Demystifying
NCCL); this module picks the winner per **(collective, axis size,
payload bucket, dtype)** from *measured* busbw — the PR-5 discipline:
the decision table is evidence, serialized into the sweep probe's
details and the bench artifact, never an asserted preference.

Layers:

- ``record()`` / ``lookup()`` — the in-process decision table. Keys
  bucket payload bytes by powers of two (one decision per octave, so a
  64 MB tuning point serves 48..96 MB gradients), and carry a
  **topology tier** ("ici" — the default, every flat decision — or
  "dcn"): the same payload wants different schedules on a fast
  intra-slice link than on the slow cross-slice one.
- ``tune()`` — run every schedule across a payload grid on a live mesh
  and record winners. The measurement function is injectable so unit
  tests script fake timings and watch the decision flip across the
  crossover without hardware.
- ``tune_hierarchical()`` / ``latency_threshold()`` — tune BOTH tiers
  of a ("dcn", "ici") mesh, race the hierarchical bandwidth vs latency
  compositions (and the flat single-level psum), and record the
  payload threshold below which the latency path wins — the
  LL-protocol-style small-message crossover.
- ``crossover_points()`` — where the winner changes along a swept
  grid (the per-topology crossovers the sweep probe reports).
- ``all_reduce()`` / ``all_gather()`` — the tuned surface for
  shard_map bodies: ``schedule="auto"`` consults the table at trace
  time (decisions bake into the jitted computation; retune → retrace).
  Passing a TUPLE of axis names ("dcn", "ici") dispatches the
  hierarchical composition with per-tier winners (:func:`hier_plan`),
  falling back to the flat path on degenerate single-slice meshes.

No wall clocks here: the table stores busbw handed in by callers, so
fake-timing tests stay deterministic.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel import schedules as zoo
from activemonitor_tpu.utils.compat import axis_size


@dataclass(frozen=True)
class TuneKey:
    collective: str  # "allreduce" | "allgather"
    axis_n: int  # devices along the reduced mesh axis
    bucket: int  # floor(log2(payload bytes))
    dtype: str  # canonical dtype name ("bfloat16", "float32", ...)
    # topology tier the decision was measured on: "ici" (flat/default
    # — every pre-hierarchy cell) or "dcn" (the slow cross-slice tier)
    tier: str = "ici"


@dataclass
class Decision:
    schedule: str  # winning schedule token ("xla", "rsag", ...)
    busbw_gbps: float
    runner_up: str = ""
    margin: float = 1.0  # winner busbw / runner-up busbw (≥ 1)
    per_schedule: Dict[str, float] = field(default_factory=dict)


_TABLE: Dict[TuneKey, Decision] = {}

# tuned latency-path thresholds for the hierarchical compositions:
# payloads strictly below the threshold ride the latency path. Keyed
# like the decision table minus the bucket (the threshold IS the
# bucket boundary); untuned topologies ride the LL-style default.
_LATENCY_THRESHOLDS: Dict["HierTuneKey", int] = {}

# untuned default for the small-message crossover (the NCCL LL regime
# sits in the tens of KB on fast links; a measured threshold from
# tune_hierarchical always replaces this)
DEFAULT_LATENCY_THRESHOLD_BYTES = 64 * 1024


@dataclass(frozen=True)
class HierTuneKey:
    collective: str
    n_dcn: int
    n_ici: int
    dtype: str


def payload_bucket(payload_bytes: int) -> int:
    """Power-of-two octave of the payload: one decision per doubling."""
    return max(0, int(math.floor(math.log2(max(1, payload_bytes)))))


def clear() -> None:
    _TABLE.clear()
    _LATENCY_THRESHOLDS.clear()


def record(
    collective: str,
    axis_n: int,
    payload_bytes: int,
    dtype,
    busbw_by_schedule: Dict[str, float],
    tier: str = "ici",
) -> Decision:
    """Fold one measurement point into the table and return the
    decision. ``busbw_by_schedule`` maps schedule token → busbw GB/s
    (the NCCL-convention number, comparable across schedules)."""
    if not busbw_by_schedule:
        raise ValueError("no schedules measured")
    ranked = sorted(
        busbw_by_schedule.items(), key=lambda kv: kv[1], reverse=True
    )
    winner, best = ranked[0]
    runner_up, second = ranked[1] if len(ranked) > 1 else ("", 0.0)
    decision = Decision(
        schedule=winner,
        busbw_gbps=best,
        runner_up=runner_up,
        margin=(best / second) if second > 0 else 1.0,
        per_schedule=dict(busbw_by_schedule),
    )
    key = TuneKey(
        collective, int(axis_n), payload_bucket(payload_bytes),
        jnp.dtype(dtype).name, str(tier),
    )
    _TABLE[key] = decision
    return decision


def lookup(
    collective: str,
    axis_n: int,
    payload_bytes: int,
    dtype,
    max_distance: int = 2,
    tier: str = "ici",
) -> Optional[str]:
    """Winning schedule for the exact bucket, else the nearest tuned
    bucket within ``max_distance`` octaves for the same (collective,
    axis, dtype, tier) — a 48 MB gradient should ride the 64 MB
    decision, but a 4 KB scalar-ish payload must NOT ride a 64 MB cell
    from the wrong side of the crossover; past the distance bound the
    caller falls back to the XLA builtin. Tiers never cross-serve: a
    fast-ICI decision says nothing about the slow DCN link."""
    name = jnp.dtype(dtype).name
    bucket = payload_bucket(payload_bytes)
    exact = _TABLE.get(TuneKey(collective, int(axis_n), bucket, name, tier))
    if exact is not None:
        return exact.schedule
    near = [
        k
        for k in _TABLE
        if k.collective == collective and k.axis_n == int(axis_n)
        and k.dtype == name and k.tier == tier
        and abs(k.bucket - bucket) <= max_distance
    ]
    if not near:
        return None
    # equidistant octaves tie-break toward the smaller payload's
    # decision (the latency-safe side of the crossover)
    best = min(near, key=lambda k: (abs(k.bucket - bucket), k.bucket))
    return _TABLE[best].schedule


def record_latency_threshold(
    collective: str, n_dcn: int, n_ici: int, dtype, threshold_bytes: int
) -> None:
    """Record the tuned small-message threshold for a two-tier
    topology: payloads strictly below it ride the latency composition."""
    if threshold_bytes < 0:
        raise ValueError(
            f"threshold must be >= 0 bytes, got {threshold_bytes}"
        )
    _LATENCY_THRESHOLDS[
        HierTuneKey(collective, int(n_dcn), int(n_ici), jnp.dtype(dtype).name)
    ] = int(threshold_bytes)


def latency_threshold(collective: str, n_dcn: int, n_ici: int, dtype) -> int:
    """The tuned latency-path threshold for this topology, or the
    LL-style default when nothing is tuned."""
    return _LATENCY_THRESHOLDS.get(
        HierTuneKey(collective, int(n_dcn), int(n_ici), jnp.dtype(dtype).name),
        DEFAULT_LATENCY_THRESHOLD_BYTES,
    )


def table_as_dict(keys: Optional[Sequence[TuneKey]] = None) -> dict:
    """JSON-serializable snapshot — the evidence block the sweep probe
    and bench.py stamp into their artifacts. ``keys`` restricts the
    snapshot (e.g. to the cells ONE tune() run measured, so a
    long-lived process never stamps stale cells from earlier tunes as
    this run's evidence)."""
    selected = _TABLE if keys is None else {
        k: _TABLE[k] for k in keys if k in _TABLE
    }
    out: dict = {}
    for key, d in sorted(
        selected.items(),
        key=lambda kv: (
            kv[0].collective, kv[0].tier, kv[0].axis_n, kv[0].bucket,
        ),
    ):
        # the flat/"ici" spelling predates tiers: only non-default
        # tiers grow a suffix, so pre-hierarchy readers keep parsing
        tier_suffix = "" if key.tier == "ici" else f"@{key.tier}"
        out[
            f"{key.collective}/n{key.axis_n}/2^{key.bucket}B/"
            f"{key.dtype}{tier_suffix}"
        ] = {
            "schedule": d.schedule,
            "busbw_gbps": round(d.busbw_gbps, 3),
            "runner_up": d.runner_up,
            "margin": round(d.margin, 3),
            "per_schedule_busbw_gbps": {
                s: round(v, 3) for s, v in d.per_schedule.items()
            },
        }
    return out


def crossover_points(
    points: Iterable[Tuple[float, str]],
) -> List[dict]:
    """Where the winner flips along a swept payload grid.

    ``points``: (payload_mb, winning schedule), any order. Returns one
    entry per flip with the bracketing payloads — "rsag takes over from
    tree between 4 and 16 MB" is the per-topology crossover the NCCL
    paper catalogs."""
    ordered = sorted(points)
    flips = []
    for (lo_mb, lo_s), (hi_mb, hi_s) in zip(ordered, ordered[1:]):
        if lo_s != hi_s:
            flips.append(
                {
                    "below_mb": lo_mb,
                    "above_mb": hi_mb,
                    "from": lo_s,
                    "to": hi_s,
                }
            )
    return flips


# measurement functions per (collective, schedule token); injectable in
# tune() so fake-timing tests can script regime flips
def _default_benches() -> Dict[Tuple[str, str], Callable]:
    from activemonitor_tpu.parallel import collectives as xla

    return {
        ("allreduce", "xla"): xla.all_reduce_bandwidth,
        ("allreduce", "rsag"): zoo.all_reduce_rsag_bandwidth,
        ("allreduce", "recdouble"): zoo.all_reduce_recdouble_bandwidth,
        ("allreduce", "tree"): zoo.all_reduce_tree_bandwidth,
        ("allgather", "xla"): xla.all_gather_bandwidth,
        ("allgather", "ring"): zoo.all_gather_ring_bandwidth,
        ("allgather", "recdouble"): zoo.all_gather_recdouble_bandwidth,
    }


# log-spaced payload grid ≈ 4 KB → 256 MB — the regimes the NCCL
# paper's crossovers live in, now reaching DOWN into the LL/latency
# regime (the old 256 KB floor meant the octave table bottomed out
# above the small-message crossover, so the latency path could never
# be measured into a decision). Single source of truth: the sweep
# probe re-exports this; edit it here.
DEFAULT_SWEEP_SIZES_MB = (0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 16.0, 64.0, 256.0)


@dataclass
class TuneRun:
    """One tune() invocation: raw busbw per (collective, size,
    schedule) plus the exact table keys it recorded — the slice of the
    global table that is THIS run's evidence."""

    results: Dict[str, Dict[float, Dict[str, float]]]
    keys: List[TuneKey]


def tune(
    mesh,
    axis: str = "",
    collectives: Sequence[str] = ("allreduce",),
    sizes_mb: Sequence[float] = DEFAULT_SWEEP_SIZES_MB,
    dtype=jnp.bfloat16,
    iters: int = 3,
    bench: Optional[Callable] = None,
    tier: str = "ici",
) -> TuneRun:
    """Measure every schedule at every payload size and record winners.

    ``bench(collective, schedule, mesh, axis, size_mb, dtype, iters)``
    must return an object with ``busbw_gbps`` and ``payload_bytes``
    (CollectiveResult shape) — tests inject a fake to script timings.
    The decision table is updated as a side effect (under ``tier``,
    "ici" for every flat tune); the returned ``TuneRun.keys`` identify
    exactly the cells this run wrote."""
    schedules_for = {
        "allreduce": zoo.ALL_REDUCE_SCHEDULES,
        "allgather": zoo.ALL_GATHER_SCHEDULES,
    }
    unknown = [c for c in collectives if c not in schedules_for]
    if unknown:
        raise ValueError(
            f"unknown collectives {unknown}; pick from "
            f"{tuple(schedules_for)}"
        )
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    benches = _default_benches()

    def run_one(collective, schedule, size_mb):
        if bench is not None:
            return bench(collective, schedule, mesh, axis, size_mb, dtype, iters)
        return benches[(collective, schedule)](
            mesh, size_mb=size_mb, dtype=dtype, iters=iters, axis=axis
        )

    raw: dict = {}
    keys: List[TuneKey] = []
    for collective in collectives:
        raw[collective] = {}
        for size_mb in sizes_mb:
            busbw: Dict[str, float] = {}
            payload = int(size_mb * 1e6)
            for schedule in schedules_for[collective]:
                result = run_one(collective, schedule, size_mb)
                busbw[schedule] = result.busbw_gbps
                payload = result.payload_bytes
            record(collective, n, payload, dtype, busbw, tier=tier)
            keys.append(
                TuneKey(
                    collective, int(n), payload_bucket(payload),
                    jnp.dtype(dtype).name, str(tier),
                )
            )
            raw[collective][size_mb] = busbw
    return TuneRun(results=raw, keys=keys)


@dataclass
class HierTuneRun:
    """One tune_hierarchical() invocation: the per-tier flat tunes,
    the bandwidth/latency/flat composition race, and the recorded
    latency-path threshold — the evidence bench.py stamps as
    ``hierarchical_autotune``."""

    tier_runs: Dict[str, TuneRun]  # "dcn" / "ici"
    variant_results: Dict[float, Dict[str, float]]  # size_mb → busbw
    threshold_bytes: int
    threshold_source: str  # "crossover" | "latency-everywhere" | ...
    keys: List[TuneKey]


def tune_hierarchical(
    mesh,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    sizes_mb: Sequence[float] = DEFAULT_SWEEP_SIZES_MB,
    dtype=jnp.bfloat16,
    iters: int = 3,
    bench: Optional[Callable] = None,
    hier_bench: Optional[Callable] = None,
) -> HierTuneRun:
    """Tune a two-tier ("dcn", "ici") mesh end to end.

    1. Flat-tunes EACH tier of the mesh separately (``tune`` over the
       dcn axis records under ``tier="dcn"``, the ici axis under
       ``tier="ici"``) so :func:`hier_plan` has per-tier winners.
    2. Races the hierarchical bandwidth vs latency compositions (and
       the flat single-level psum baseline) across the payload grid
       and records the threshold below which the latency path wins —
       the LL-protocol small-message crossover,
       :func:`latency_threshold`.

    ``bench`` is the flat per-tier injectable (``tune`` contract);
    ``hier_bench(variant, mesh, dcn_axis, ici_axis, size_mb, dtype,
    iters)`` returns a CollectiveResult-shaped object for the composed
    paths ("bandwidth" | "latency" | "flat") — tests script both to
    prove the decision flip without hardware."""
    from activemonitor_tpu.parallel import schedules as zoo

    n_dcn = mesh.shape[dcn_axis]
    n_ici = mesh.shape[ici_axis]
    tier_runs: Dict[str, TuneRun] = {}
    keys: List[TuneKey] = []
    for tier, axis, n in (("dcn", dcn_axis, n_dcn), ("ici", ici_axis, n_ici)):
        if n < 2:
            continue  # nothing to race on a singleton tier
        run = tune(
            mesh, axis=axis, collectives=("allreduce",), sizes_mb=sizes_mb,
            dtype=dtype, iters=iters, bench=bench, tier=tier,
        )
        tier_runs[tier] = run
        keys.extend(run.keys)

    def run_hier(variant, size_mb):
        if hier_bench is not None:
            return hier_bench(
                variant, mesh, dcn_axis, ici_axis, size_mb, dtype, iters
            )
        return zoo.hier_all_reduce_bandwidth(
            mesh, size_mb=size_mb, dtype=dtype, iters=iters,
            dcn_axis=dcn_axis, ici_axis=ici_axis, variant=variant,
        )

    variant_results: Dict[float, Dict[str, float]] = {}
    payload_of: Dict[float, int] = {}
    for size_mb in sizes_mb:
        row: Dict[str, float] = {}
        for variant in ("bandwidth", "latency", "flat"):
            result = run_hier(variant, size_mb)
            row[variant] = result.busbw_gbps
            payload_of[size_mb] = result.payload_bytes
        variant_results[size_mb] = row

    # the threshold: payloads below the smallest measured payload where
    # the bandwidth composition catches the latency one ride the
    # latency path. Latency winning the whole grid pushes the
    # threshold past the largest payload; bandwidth winning everywhere
    # (including the floor) leaves only the unmeasured region below
    # the floor on the latency side — the α-dominated regime the floor
    # can't see, where fewer rounds is the safe default.
    ordered = sorted(variant_results)
    threshold = None
    source = "crossover"
    for size_mb in ordered:
        row = variant_results[size_mb]
        if row["bandwidth"] >= row["latency"]:
            threshold = payload_of[size_mb]
            if size_mb == ordered[0]:
                source = "bandwidth-everywhere"
            break
    if threshold is None:
        threshold = 2 * payload_of[ordered[-1]]
        source = "latency-everywhere"
    record_latency_threshold("allreduce", n_dcn, n_ici, dtype, threshold)
    return HierTuneRun(
        tier_runs=tier_runs,
        variant_results=variant_results,
        threshold_bytes=int(threshold),
        threshold_source=source,
        keys=keys,
    )


# ---------------------------------------------------------------------------
# the tuned surface — called INSIDE shard_map bodies
# ---------------------------------------------------------------------------

_ALL_REDUCE_IMPL = {
    "rsag": zoo.all_reduce_rsag,
    "recdouble": zoo.all_reduce_recdouble,
    "tree": zoo.all_reduce_tree,
}

_ALL_GATHER_IMPL = {
    "ring": zoo.all_gather_ring,
    "recdouble": zoo.all_gather_recdouble,
}

# schedule tokens the hierarchical surface accepts: "auto" consults
# threshold + per-tier tables, "xla" is the joint psum/all_gather
# builtin, the variants force one composition
HIER_SCHEDULES = ("auto", "xla", "bandwidth", "latency")


def _tier_sizes(axes, n):
    """Per-axis sizes for a tuple-axis call: ``n`` may be a matching
    tuple of sizes or None (resolved from the trace's axis frames)."""
    if n is None:
        return tuple(axis_size(a) for a in axes)
    if isinstance(n, (tuple, list)):
        if len(n) != len(axes):
            raise ValueError(
                f"n {tuple(n)} does not match axes {tuple(axes)}"
            )
        return tuple(int(v) for v in n)
    raise ValueError(
        f"a tuple-axis collective needs a tuple n per axis, got {n!r}"
    )


def _normalize_axes(axis_name, n):
    """Shared tuple-axis handling for the tuned surfaces: returns
    ``(axis_name, n, tiers)`` where ``tiers`` is None on the flat path
    (a bare axis, or a 1-tuple unwrapped to one) and the per-tier
    ``(n_dcn, n_ici)`` sizes for a 2-tuple. 3+ tiers are an error."""
    if not isinstance(axis_name, (tuple, list)):
        return axis_name, n, None
    axes = tuple(axis_name)
    if len(axes) == 1:
        if n is not None and isinstance(n, (tuple, list)):
            n = n[0]
        return axes[0], n, None
    if len(axes) == 2:
        return axes, None, _tier_sizes(axes, n)
    raise ValueError(
        f"hierarchical dispatch takes exactly two tiers, got {axes}"
    )


def hier_plan(
    collective: str,
    n_dcn: int,
    n_ici: int,
    payload_bytes: int,
    dtype,
    schedule: str = "auto",
) -> dict:
    """The per-tier decision for one hierarchical dispatch — which
    path (latency vs bandwidth vs flat fallback) and which schedule
    each tier rides, with the threshold that decided it. This dict IS
    the evidence surface: the training-step probe exports it in its
    stdout contract and bench.py stamps it into the artifact."""
    if schedule not in HIER_SCHEDULES:
        raise ValueError(
            f"unknown hierarchical schedule {schedule!r}; pick from "
            f"{HIER_SCHEDULES}"
        )
    base = {"n_dcn": int(n_dcn), "n_ici": int(n_ici),
            "payload_bytes": int(payload_bytes)}
    if n_dcn <= 1:
        return {
            **base,
            "path": "flat",
            "reason": "degenerate single-slice mesh (dcn=1): flat ici path",
        }
    threshold = latency_threshold(collective, n_dcn, n_ici, dtype)
    if schedule == "auto":
        variant = "latency" if payload_bytes < threshold else "bandwidth"
    elif schedule == "xla":
        return {**base, "path": "hierarchical", "variant": "xla",
                "threshold_bytes": threshold}
    else:
        variant = schedule
    if variant == "latency":
        ici_schedule = (
            lookup(collective, n_ici, payload_bytes, dtype, tier="ici")
            or "recdouble"
        )
        dcn_schedule = (
            lookup(collective, n_dcn, payload_bytes, dtype, tier="dcn")
            or "recdouble"
        )
    else:
        # the bandwidth composition's ICI phases are the rs/ag ring by
        # construction; only the scattered DCN exchange has a choice
        ici_schedule = "rsag"
        chunk = max(1, int(payload_bytes) // max(1, n_ici))
        dcn_schedule = (
            lookup(collective, n_dcn, chunk, dtype, tier="dcn")
            or "recdouble"
        )
    return {
        **base,
        "path": "hierarchical",
        "variant": variant,
        "ici_schedule": ici_schedule,
        "dcn_schedule": dcn_schedule,
        "threshold_bytes": threshold,
    }


def hier_plan_label(plan: dict) -> str:
    """One canonical spelling of a :func:`hier_plan` decision for
    evidence surfaces (probe details, matrix schedule stamps) — built
    here so the probe stdout spelling and the bench/matrix artifact
    spelling cannot drift apart."""
    if plan.get("path") == "flat":
        return "hier-flat(dcn=1)"
    if plan.get("variant") == "xla":
        return "hier/xla"
    return (
        f"hier/{plan['variant']}"
        f"(dcn={plan['dcn_schedule']},ici={plan['ici_schedule']})"
    )


def hier_all_reduce(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    schedule: str = "auto",
    n_dcn: int | None = None,
    n_ici: int | None = None,
):
    """The tuned two-tier all-reduce surface, for shard_map bodies
    manual over both tiers. ``"auto"`` consults the tuned latency
    threshold (below → the latency composition, above → the bandwidth
    one) and the per-tier decision tables; degenerate single-slice
    meshes fall back to the FLAT tuned surface over the ici axis —
    bitwise the flat schedule, with the reason in :func:`hier_plan`."""
    n_dcn = int(n_dcn) if n_dcn is not None else axis_size(dcn_axis)
    n_ici = int(n_ici) if n_ici is not None else axis_size(ici_axis)
    if schedule not in HIER_SCHEDULES:
        raise ValueError(
            f"unknown hierarchical schedule {schedule!r}; pick from "
            f"{HIER_SCHEDULES}"
        )
    if n_dcn <= 1:
        # degenerate single-slice: the flat tuned surface IS the
        # composition (hier_plan records the reason)
        return all_reduce(
            x, ici_axis, schedule="xla" if schedule == "xla" else "auto",
            n=n_ici,
        )
    if x.ndim == 0 or schedule == "xla":
        # nothing to chunk on a scalar; "xla" is the joint builtin
        return jax.lax.psum(x, (dcn_axis, ici_axis))
    payload = x.size * jnp.dtype(x.dtype).itemsize
    plan = hier_plan("allreduce", n_dcn, n_ici, payload, x.dtype, schedule)
    if plan["variant"] == "latency":
        return zoo.hier_all_reduce_latency(
            x, dcn_axis, ici_axis, n_dcn, n_ici,
            ici_schedule=plan["ici_schedule"],
            dcn_schedule=plan["dcn_schedule"],
        )
    return zoo.hier_all_reduce(
        x, dcn_axis, ici_axis, n_dcn, n_ici,
        dcn_schedule=plan["dcn_schedule"],
    )


def hier_all_gather(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    schedule: str = "auto",
    n_dcn: int | None = None,
    n_ici: int | None = None,
):
    """The tuned two-tier all-gather surface: per-tier winners from
    the tier-keyed "allgather" tables (default: the ring), dcn-major
    tiled output like ``lax.all_gather(x, (dcn, ici), tiled=True)``.
    Unlike all-reduce, the gather has no latency/bandwidth composition
    variants (both tiers always gather once), so only "auto"/"xla"
    are accepted — a forced variant must error, not silently auto."""
    n_dcn = int(n_dcn) if n_dcn is not None else axis_size(dcn_axis)
    n_ici = int(n_ici) if n_ici is not None else axis_size(ici_axis)
    if schedule not in ("auto", "xla"):
        raise ValueError(
            f"unknown hierarchical all-gather schedule {schedule!r}; "
            "the two-tier gather takes auto/xla (it has no "
            "latency/bandwidth variants)"
        )
    if n_dcn <= 1:
        return all_gather(
            x, ici_axis, schedule="xla" if schedule == "xla" else "auto",
            n=n_ici,
        )
    if x.ndim == 0 or schedule == "xla":
        return jax.lax.all_gather(x, (dcn_axis, ici_axis), tiled=True)
    itemsize = jnp.dtype(x.dtype).itemsize
    # allgather tables key on gathered-total payload per tier
    ici_schedule = (
        lookup("allgather", n_ici, x.size * itemsize * n_ici, x.dtype,
               tier="ici")
        or "ring"
    )
    dcn_schedule = (
        lookup("allgather", n_dcn, x.size * itemsize * n_ici * n_dcn,
               x.dtype, tier="dcn")
        or "ring"
    )
    return zoo.hier_all_gather(
        x, dcn_axis, ici_axis, n_dcn, n_ici,
        ici_schedule=ici_schedule, dcn_schedule=dcn_schedule,
    )


def all_reduce(x, axis_name, schedule: str = "auto", n=None):
    """psum with a schedule knob, for shard_map bodies. ``"auto"``
    consults the decision table (trace-time: the choice bakes into the
    jitted computation) and falls back to the XLA builtin when nothing
    is tuned within 2 octaves of this (axis size, payload, dtype) —
    or when the input has no leading axis to chunk (scalars always
    ride the builtin).

    ``axis_name`` may be a TUPLE of two axis names (slow outer tier
    first — the ("dcn", "ici") pair a two-tier mesh carries): the
    reduction then dispatches the hierarchical composition through
    :func:`hier_all_reduce` with per-tier tuned winners (``n``: a
    matching tuple of sizes, or None)."""
    axis_name, n, tiers = _normalize_axes(axis_name, n)
    if tiers is not None:
        return hier_all_reduce(
            x, axis_name[0], axis_name[1], schedule=schedule,
            n_dcn=tiers[0], n_ici=tiers[1],
        )
    n = int(n) if n is not None else axis_size(axis_name)
    if schedule == "auto":
        if x.ndim == 0:
            schedule = "xla"  # nothing to chunk/rotate on a scalar
        else:
            payload = x.size * jnp.dtype(x.dtype).itemsize
            schedule = lookup("allreduce", n, payload, x.dtype) or "xla"
    if schedule == "xla":
        return jax.lax.psum(x, axis_name)
    try:
        impl = _ALL_REDUCE_IMPL[schedule]
    except KeyError:
        raise ValueError(
            f"unknown all-reduce schedule {schedule!r}; pick from "
            f"{('auto',) + zoo.ALL_REDUCE_SCHEDULES}"
        ) from None
    return impl(x, axis_name, n)


def all_gather(x, axis_name, schedule: str = "auto", n=None):
    """Tiled all-gather with a schedule knob (output [n·rows, ...] in
    device order, like ``lax.all_gather(..., tiled=True)``). A TUPLE
    ``axis_name`` (slow tier first) dispatches the hierarchical gather
    through :func:`hier_all_gather`, like :func:`all_reduce`."""
    axis_name, n, tiers = _normalize_axes(axis_name, n)
    if tiers is not None:
        return hier_all_gather(
            x, axis_name[0], axis_name[1], schedule=schedule,
            n_dcn=tiers[0], n_ici=tiers[1],
        )
    n = int(n) if n is not None else axis_size(axis_name)
    if schedule == "auto":
        if x.ndim == 0:
            schedule = "xla"  # no leading axis to tile
        else:
            payload = x.size * jnp.dtype(x.dtype).itemsize * n
            schedule = lookup("allgather", n, payload, x.dtype) or "xla"
    if schedule == "xla":
        return jax.lax.all_gather(x, axis_name, tiled=True)
    try:
        impl = _ALL_GATHER_IMPL[schedule]
    except KeyError:
        raise ValueError(
            f"unknown all-gather schedule {schedule!r}; pick from "
            f"{('auto',) + zoo.ALL_GATHER_SCHEDULES}"
        ) from None
    return impl(x, axis_name, n)
