"""Sharded training-step probe — the flagship end-to-end payload.

Verifies the whole TPU software stack in one shot: a data×tensor
parallel train step (loss + grad + AdamW update) on the probe
transformer, jitted over a 2D mesh with megatron shardings, executed
and timed. Catches compiler regressions, sharding/layout breakage, and
underperforming chips in a way single-op probes can't.

The step builder here is also the framework's reference recipe for
distributed training-shaped workloads: params and optimizer state live
sharded (NamedSharding over the mesh), gradients psum over "data"
implicitly via jit, tensor-parallel matmuls psum over "model" — all
collectives inserted by XLA from the sharding annotations, the
scaling-book recipe rather than hand-written communication.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    init_params,
    loss_fn,
    param_count,
    param_specs,
    tiny_config,
)
from activemonitor_tpu.parallel.mesh import make_2d_mesh
from activemonitor_tpu.parallel.partition import (
    match_partition_rules,
    named_tree_map,
    resolve_tiers,
    shard_map,
)
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import (
    CHAIN_GROWTH,
    CHAIN_RETRIES,
    needs_longer_chain,
)

log = logging.getLogger("activemonitor.probes")

# grad_sync tokens build_sharded_train_step accepts: "implicit" keeps
# the XLA-inserted reduction; everything else is an explicit shard_map
# sync through parallel/autotune.all_reduce with that schedule knob
# ("auto" = consult the tuned decision table per gradient leaf).
GRAD_SYNC_SCHEDULES = ("implicit", "auto", "xla", "rsag", "recdouble", "tree")


def resolve_grad_sync(
    mesh: Mesh, attention: str, grad_sync: str, accum_steps: int = 1
):
    """``("explicit", "")`` when the tuned-dispatch gradient sync can
    run, ``("hierarchical", "")`` when the mesh is a two-tier
    ("dcn", "ici") data-parallel mesh and the sync should ride the
    hierarchical composition, else ``("implicit", why)``.

    The explicit sync shard_maps the loss+grad computation over the
    ``"data"`` axis and reduces through ``autotune.all_reduce`` — the
    PR-8 decision table running in the training hot path. It needs a
    nontrivial data axis, every OTHER mesh axis trivial (the sync body
    is fully manual; a live tp/sp axis would need the partial-manual
    lowering the legacy runtime lacks), and dense attention (flash/ring
    run their own shard_map, which cannot nest inside the sync body).

    The hierarchical sync applies the same gates to a mesh that
    carries the tier pair INSTEAD of a "data" axis (the resolution
    rides ``parallel/partition.resolve_tiers``, so the probe's call
    sites never change): batch shards over ("dcn", "ici"), gradients
    reduce through ``autotune.all_reduce(("dcn", "ici"))`` — intra-
    slice reduce-scatter over ICI, cross-slice exchange over DCN,
    all-gather back, or the latency path below the tuned threshold.
    Only "auto"/"xla" are meaningful there (a flat zoo token names a
    single-tier schedule). Anything else falls back to the implicit
    XLA-inserted reduction, with the reason recorded in the probe
    details — a gate, never a crash."""
    if grad_sync not in GRAD_SYNC_SCHEDULES:
        raise ValueError(
            f"grad_sync must be one of {GRAD_SYNC_SCHEDULES}, got "
            f"{grad_sync!r}"
        )
    if grad_sync == "implicit":
        return "implicit", "requested"
    shape = dict(mesh.shape)
    if "data" not in shape and "dcn" in shape and "ici" in shape:
        # two-tier data parallelism: the hierarchical sync (multi-
        # process allowed — cross-slice DCN traffic is the point)
        if grad_sync not in ("auto", "xla"):
            return "implicit", (
                f"flat schedule {grad_sync!r} on a two-tier mesh "
                "(hierarchical sync takes auto/xla)"
            )
        others = [
            a for a in mesh.axis_names
            if a not in ("dcn", "ici") and shape[a] > 1
        ]
        if others:
            return "implicit", f"non-tier axes {others} stay compiler-managed"
        if attention != "dense":
            return "implicit", f"attention={attention!r} runs its own shard_map"
        if accum_steps > 1:
            return "implicit", "accum_steps keeps the global-batch contract"
        if shape["dcn"] * shape["ici"] < 2:
            return "implicit", "single-device mesh: nothing to reduce"
        return "hierarchical", ""
    if jax.process_count() > 1:
        # DCN-spanning meshes keep the XLA-inserted reduction: the
        # tuned ICI schedules are wrong for cross-host links anyway,
        # and the two-process train-step contract predates this path
        return "implicit", "multi-process mesh"
    if mesh.shape.get("data", 1) < 2:
        return "implicit", "no data axis to reduce over"
    others = [
        a for a in mesh.axis_names if a != "data" and mesh.shape[a] > 1
    ]
    if others:
        return "implicit", f"non-data axes {others} stay compiler-managed"
    if attention != "dense":
        return "implicit", f"attention={attention!r} runs its own shard_map"
    if accum_steps > 1:
        # inside the sync body the microbatch split would divide the
        # LOCAL shard, silently rewriting the global-batch % accum_steps
        # contract callers already hold — keep the implicit reduction
        return "implicit", "accum_steps keeps the global-batch contract"
    return "explicit", ""


def _leaf_payloads(cfg: ProbeModelConfig, dtype) -> dict:
    """name → gradient payload bytes over the abstract param tree."""
    abstract = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    itemsize = jnp.dtype(dtype).itemsize
    payloads: dict = {}
    named_tree_map(
        lambda name, leaf: payloads.__setitem__(
            name, int(math.prod(leaf.shape)) * itemsize
        ),
        abstract,
    )
    return payloads


def grad_sync_plan(cfg: ProbeModelConfig, mesh: Mesh, dtype=jnp.float32) -> dict:
    """The per-leaf tuned-dispatch plan for the explicit gradient sync:
    which schedule ``autotune.all_reduce(schedule="auto")`` resolves
    for every gradient leaf's payload octave on this mesh's data axis.
    The headline ``schedule`` is the largest leaf's (the payload that
    dominates sync wall time) — the value the probe exports in its
    stdout contract and bench.py stamps into the artifact."""
    from activemonitor_tpu.parallel import autotune

    n = mesh.shape.get("data", 1)
    payloads = _leaf_payloads(cfg, dtype)
    plan = {
        name: (autotune.lookup("allreduce", n, payload, dtype) or "xla", payload)
        for name, payload in payloads.items()
    }
    largest = max(plan, key=lambda name: plan[name][1])
    by_schedule: dict = {}
    for schedule, _payload in plan.values():
        by_schedule[schedule] = by_schedule.get(schedule, 0) + 1
    return {
        "axis_n": n,
        "schedule": plan[largest][0],
        "largest_leaf": largest,
        "largest_leaf_bytes": plan[largest][1],
        "by_schedule": by_schedule,
    }


def hier_sync_plan(
    cfg: ProbeModelConfig, mesh: Mesh, dtype=jnp.float32,
    schedule: str = "auto",
) -> dict:
    """The per-TIER decision for the hierarchical gradient sync on a
    two-tier ("dcn", "ici") mesh: which path the dominant gradient
    leaf rides (latency below the tuned threshold, bandwidth above)
    and which schedule each tier resolved — the per-tier evidence the
    probe exports in its stdout contract (``details["hier_sync"]``)."""
    from activemonitor_tpu.parallel import autotune

    data_axes, reason = resolve_tiers(mesh, "data")
    payloads = _leaf_payloads(cfg, dtype)
    largest = max(payloads, key=payloads.get)
    if len(data_axes) < 2:
        return {
            "path": "flat",
            "reason": reason,
            "largest_leaf": largest,
            "largest_leaf_bytes": payloads[largest],
        }
    plan = autotune.hier_plan(
        "allreduce", mesh.shape["dcn"], mesh.shape["ici"],
        payloads[largest], dtype,
        schedule if schedule in autotune.HIER_SCHEDULES else "auto",
    )
    plan["largest_leaf"] = largest
    plan["largest_leaf_bytes"] = payloads[largest]
    return plan


def build_sharded_train_step(
    cfg: ProbeModelConfig,
    mesh: Mesh,
    learning_rate: float = 1e-3,
    attention: str = "dense",
    zero1: bool = False,
    remat: bool = False,
    accum_steps: int = 1,
    init_state: bool = True,
    grad_sync: str = "auto",
):
    """Returns (step_fn, params, opt_state, data_sharding).

    ``init_state=False`` returns ``(step_fn, None, None, data_sh)``
    without allocating anything — shardings come from abstract shapes.
    The resume path pairs it with :func:`train_state_templates` +
    :func:`restore_train_state`, so an HBM-tight job never materializes
    a throwaway random init on restart.

    step_fn(params, opt_state, tokens) -> (params, opt_state, loss) is
    jitted with explicit in/out shardings; XLA inserts all collectives.

    The three standard memory levers compose freely with every
    attention variant and mesh shape:

    - ``zero1`` — ZeRO-1: AdamW's mu/nu additionally shard over the
      "data" axis (on each leaf's leading dim where it divides and is
      not already model-sharded — ln scales and embeddings included).
      Identical math: XLA turns the sharding annotations into the
      reduce-scatter/all-gather dance, the scaling-book way, so
      optimizer memory drops ~dp× with no hand-written collectives.
    - ``remat`` — rematerialize block activations in the backward
      (``jax.checkpoint``): FLOPs for HBM.
    - ``accum_steps`` — gradient accumulation over that many
      microbatches via ``lax.scan`` (batch must divide): the step
      consumes the same global batch in accum_steps forward/backward
      passes and applies ONE averaged update.

    ``grad_sync`` picks how gradients reduce over the "data" axis:
    ``"implicit"`` keeps XLA's sharding-derived reduction; any
    ``autotune`` schedule token (default ``"auto"``) syncs explicitly
    through ``autotune.all_reduce`` inside a shard_map over "data" —
    the tuned decision table dispatched in the training hot path.
    Meshes/configs the explicit path cannot serve fall back to
    implicit (:func:`resolve_grad_sync` has the gate) rather than
    crash.
    """
    from activemonitor_tpu.parallel.distributed import distribute_tree

    optimizer = optax.adamw(learning_rate)
    # the batch axis resolves through the partition tier rule: a mesh
    # carrying ("dcn", "ici") instead of "data" shards the batch over
    # BOTH tiers (dcn-major) with zero call-site changes
    data_axes, _tier_reason = resolve_tiers(mesh, "data")
    tiered = "data" not in mesh.shape
    data_entry = data_axes[0] if len(data_axes) == 1 else data_axes
    data_sh = NamedSharding(mesh, P(data_entry, None))

    # shardings derive from ABSTRACT shapes — nothing allocated yet
    abstract_params = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    if tiered:
        # two-tier data parallelism: params/optimizer replicate (the
        # megatron param specs name a "model" axis these meshes don't
        # carry; tensor parallelism inside a slice is a composed-mesh
        # follow-up, not this path)
        if zero1:
            raise ValueError(
                "zero1 needs a 'data' mesh axis; two-tier ('dcn', 'ici') "
                "meshes keep optimizer state replicated"
            )
        replicated = NamedSharding(mesh, P())
        param_sh = jax.tree.map(lambda _: replicated, abstract_params)
        state_sh = param_sh
    else:
        param_sh, state_sh, replicated = _state_shardings(
            cfg, mesh, zero1, abstract_params
        )
    abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
    opt_sh = _opt_shardings(abstract_opt, param_sh, replicated, state_sh=state_sh)
    if init_state:
        # every process computes the same init (same key), then
        # contributes its shards — single-chip and DCN-spanning meshes
        # alike; the optimizer state is born ON its shardings (zero1:
        # the dp-extended layout) — eager init would choke on
        # multi-process global params anyway
        params = distribute_tree(init_params(jax.random.key(0), cfg), param_sh)
        opt_state = jax.jit(optimizer.init, out_shardings=opt_sh)(params)
    else:
        params = opt_state = None

    if attention == "flash":
        from activemonitor_tpu.models.probe_model import flash_attention_fn

        attention_fn = flash_attention_fn(cfg, mesh)
    elif attention == "ring":
        from activemonitor_tpu.models.probe_model import ring_attention_fn

        attention_fn = ring_attention_fn(cfg, mesh)
    elif attention == "dense":
        attention_fn = None
    else:
        raise ValueError(
            f"attention must be dense, flash or ring, got {attention!r}"
        )
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def loss_of(params, tokens):
        return loss_fn(params, tokens, cfg, attention_fn, remat=remat)

    def compute_grads(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(loss_of)(params, tokens)
        batch = tokens.shape[0]
        if batch % accum_steps:
            raise ValueError(
                f"batch {batch} not divisible into {accum_steps} microbatches"
            )
        micro = tokens.reshape(accum_steps, batch // accum_steps, -1)

        def body(carry, mb):
            loss_sum, grad_sum = carry
            value, grads = jax.value_and_grad(loss_of)(params, mb)
            return (
                loss_sum + value,
                jax.tree.map(jnp.add, grad_sum, grads),
            ), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro
        )
        return loss_sum / accum_steps, jax.tree.map(
            lambda g: g / accum_steps, grad_sum
        )

    sync_mode, _sync_reason = resolve_grad_sync(mesh, attention, grad_sync, accum_steps)
    if sync_mode in ("explicit", "hierarchical"):
        # the one-sharding-surface sync: each data shard computes grads
        # on its local microbatch, then the reduction rides the tuned
        # collective surface (schedule="auto" consults the PR-8
        # decision table per leaf payload; untuned leaves take the XLA
        # psum). Mean-of-shard-means equals the global mean — shard
        # sizes are equal by the batch % data check in jit's sharding.
        # On a two-tier mesh the SAME call dispatches hierarchically:
        # axis is the ("dcn", "ici") pair, so autotune.all_reduce
        # routes through the latency/bandwidth compositions per leaf
        # payload (parallel/autotune.hier_plan).
        sync_axes = ("data",) if sync_mode == "explicit" else data_axes
        axis_token = sync_axes[0] if len(sync_axes) == 1 else sync_axes
        sync_ns = tuple(mesh.shape[a] for a in sync_axes)
        sync_n = sync_ns[0] if len(sync_ns) == 1 else sync_ns
        n_sync = math.prod(sync_ns)

        def local_grads(params, tokens):
            from activemonitor_tpu.parallel import autotune

            loss, grads = compute_grads(params, tokens)
            grads = jax.tree.map(
                lambda g: autotune.all_reduce(
                    g, axis_token, schedule=grad_sync, n=sync_n
                )
                / n_sync,
                grads,
            )
            return jax.lax.psum(loss, axis_token) / n_sync, grads

        synced_grads = shard_map(
            local_grads,
            mesh=mesh,
            # params replicate over the (trivial-other-axes) mesh; only
            # the token batch is manual-sharded
            in_specs=(P(), P(data_entry, None)),
            out_specs=(P(), P()),
            check_vma=False,
        )

    def step(params, opt_state, tokens):
        if sync_mode in ("explicit", "hierarchical"):
            loss, grads = synced_grads(params, tokens)
        else:
            loss, grads = compute_grads(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step_fn = jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, data_sh),
        out_shardings=(param_sh, opt_sh, replicated),
        donate_argnums=(0, 1),
    )
    return step_fn, params, opt_state, data_sh


def _state_shardings(cfg: ProbeModelConfig, mesh: Mesh, zero1: bool, params_like):
    """(param_sh, state_sh, replicated) sharding trees for a training
    state on ``mesh``. ``params_like`` may be concrete arrays or
    ShapeDtypeStructs — only shapes are read (the ZeRO-1 divisibility
    rule), so the abstract template path allocates nothing."""
    specs = param_specs(cfg)
    param_sh = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    replicated = NamedSharding(mesh, P())
    state_sh = param_sh
    if zero1:
        state_sh = jax.tree.map(
            lambda leaf, spec: _zero1_sharding(leaf, spec, mesh),
            params_like,
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
    return param_sh, state_sh, replicated


def _zero1_sharding(leaf, spec: P, mesh: Mesh) -> NamedSharding:
    """ZeRO-1 sharding for one optimizer-state leaf: add the "data"
    axis on the leading dim when that dim is free (not already sharded)
    and divisible; otherwise keep the parameter's own sharding. Partial
    by construction — a leaf that can't shard cleanly stays replicated
    over dp rather than forcing a pad."""
    dims = tuple(spec) + (None,) * (leaf.ndim - len(tuple(spec)))
    dp = mesh.shape.get("data", 1)
    if (
        dp > 1
        and leaf.ndim > 0
        and dims[0] is None
        and leaf.shape[0] % dp == 0
    ):
        return NamedSharding(mesh, P("data", *dims[1:]))
    return NamedSharding(mesh, P(*dims))


def composed_param_rules(pp_axis: str = "pp", tp_axis: str = "model"):
    """Partition rules for the composed (dp×tp×pp) parameter tree: the
    embedding replicates, the stacked layer block takes the
    ops/pipeline ``stacked_layer_rules`` layout (pp-major, megatron tp
    inside), and everything else (final norm) falls through to
    replicated. One rules tuple = the whole composed layout as data."""
    from activemonitor_tpu.ops.pipeline import stacked_layer_rules

    return (("^embed$", P(None, None)),) + tuple(
        (f"^layers/.*{pattern}", spec)
        for pattern, spec in stacked_layer_rules(pp_axis, tp_axis)
    )


def build_composed_train_step(
    cfg: ProbeModelConfig,
    mesh: Mesh,
    learning_rate: float = 1e-3,
    num_microbatches: int = 0,
):
    """dp×tp×pp composed train step on ONE mesh — the ≥3-axis recipe.

    ``mesh`` must carry axes ``data``, ``model`` and ``pp``
    (size-1 axes are fine). The layer stack is stacked
    (ops/pipeline.stack_layer_params) and sharded pp-major with
    megatron tp inside each layer (stacked_layer_specs); the forward
    pipelines microbatches over "pp" with the shard_map manual ONLY
    over that axis, so each stage's layer compute keeps its dp×tp
    shardings and XLA still inserts the "model" psums and "data"
    gradient reductions. Requires cfg.n_layers % mesh.shape['pp'] == 0.

    Returns (step_fn, params, opt_state, data_sharding) like
    :func:`build_sharded_train_step`.
    """
    from activemonitor_tpu.models.probe_model import _rmsnorm
    from activemonitor_tpu.ops.pipeline import (
        pipeline_forward_blocks,
        stack_layer_params,
    )

    for needed in ("data", "model", "pp"):
        if needed not in mesh.shape:
            raise ValueError(f"composed mesh needs a '{needed}' axis, has {dict(mesh.shape)}")
    if cfg.n_layers % mesh.shape["pp"]:
        raise ValueError(
            f"{cfg.n_layers} layers do not split over {mesh.shape['pp']} pp stages"
        )

    optimizer = optax.adamw(learning_rate)
    raw = init_params(jax.random.key(0), cfg)
    stacked = {
        "embed": raw["embed"],
        "layers": stack_layer_params(raw["layers"]),
        "final_ln": raw["final_ln"],
    }
    # the composed pp×tp layout, resolved from rules over the ACTUAL
    # tree — GQA configs get their wq/wkv split sharded without a
    # second hand-written spec dict
    specs = match_partition_rules(
        composed_param_rules("pp", "model"), stacked, mesh=mesh
    )
    param_sh = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    data_sh = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P())

    params = jax.device_put(stacked, param_sh)
    opt_state = optimizer.init(params)
    opt_sh = _opt_shardings(opt_state, param_sh, replicated)

    def loss(params, tokens):
        dt = cfg.dtype
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        x = params["embed"].astype(dt)[inputs]  # [B, S, D]
        x = pipeline_forward_blocks(
            params["layers"], x, cfg, mesh, "pp",
            num_microbatches=num_microbatches, composed=True,
        )
        x = _rmsnorm(x, params["final_ln"]["scale"])
        logits = jnp.einsum(
            "bsd,vd->bsv", x, params["embed"].astype(dt)
        ).astype(jnp.float32)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)
        return jnp.mean(nll)

    def step(params, opt_state, tokens):
        loss_value, grads = jax.value_and_grad(loss)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_value

    step_fn = jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, data_sh),
        out_shardings=(param_sh, opt_sh, replicated),
        donate_argnums=(0, 1),
    )
    return step_fn, params, opt_state, data_sh


def restore_targets(tree):
    """Map a (concrete OR abstract) pytree to orbax restore targets:
    ShapeDtypeStructs carrying each leaf's sharding. Shared by
    :func:`restore_train_state` and the checkpoint probe so
    restore-target construction cannot drift."""

    def target(leaf):
        if hasattr(leaf, "sharding") and leaf.sharding is not None:
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
        return leaf

    return jax.tree.map(target, tree)


def train_state_templates(
    cfg: ProbeModelConfig,
    mesh: Mesh,
    learning_rate: float = 1e-3,
    zero1: bool = False,
):
    """ABSTRACT (params, opt_state) templates — ShapeDtypeStructs
    carrying the exact shardings :func:`build_sharded_train_step` would
    produce, built via ``jax.eval_shape`` so NOTHING is materialized.
    This is what resume should pass to :func:`restore_train_state`: a
    zero1/remat job that is HBM-tight in steady state must not allocate
    a throwaway random init (plus optimizer state) just to describe the
    restore layout."""
    optimizer = optax.adamw(learning_rate)
    abstract_params = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
    param_sh, state_sh, replicated = _state_shardings(
        cfg, mesh, zero1, abstract_params
    )
    opt_sh = _opt_shardings(abstract_opt, param_sh, replicated, state_sh=state_sh)

    def attach(sds, sharding):
        return jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sharding)

    return (
        jax.tree.map(attach, abstract_params, param_sh),
        jax.tree.map(attach, abstract_opt, opt_sh),
    )


def save_train_state(directory: str, params, opt_state, step: int,
                     keep: int = 2) -> None:
    """Persist the sharded training state (params + optimizer state)
    under a STEP-NUMBERED checkpoint: orbax's CheckpointManager keeps
    the previous checkpoint until the new one commits, so a preemption
    mid-save (the whole gather + serialize window) still leaves a valid
    state to resume from — durable means crash-durable, not
    happy-path-durable. ``keep`` bounds retained checkpoints."""
    import orbax.checkpoint as ocp

    with ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(max_to_keep=keep)
    ) as manager:
        manager.save(
            step, args=ocp.args.StandardSave({"params": params, "opt": opt_state})
        )
        manager.wait_until_finished()


def restore_train_state(directory: str, params_like, opt_state_like,
                        step: int | None = None):
    """Restore (params, opt_state, step) onto the layouts of the given
    templates — :func:`train_state_templates` abstractions (preferred:
    nothing gets materialized twice) or concrete trees from
    :func:`build_sharded_train_step`. Because the targets carry their
    own NamedShardings, orbax reshards on load: a checkpoint written
    from a dp=2×tp=4 run (with or without ZeRO-1 optimizer layouts)
    restores cleanly onto dp=4×tp=2, ZeRO-1 on or off — values
    identical, layout the new mesh's. Elastic resume is a restore-time
    property, not a save-time decision. ``step`` None restores the
    newest RESTORABLE checkpoint: a step directory poisoned by a crash
    (present but empty/truncated — orbax's tmp-dir rename prevents
    most of these, not a filesystem dying mid-rename) is skipped with
    a warning and restore falls back to the next older step, so one
    bad directory cannot brick resume while durable state exists. An
    EXPLICIT ``step`` raises as-is — the caller asked for exactly
    that state and silently substituting another would be worse."""
    import orbax.checkpoint as ocp
    from etils import epath  # orbax dependency; URI-safe (gs://, s3://)

    targets = restore_targets({"params": params_like, "opt": opt_state_like})
    root = epath.Path(directory)

    def scan_steps() -> list:
        if not root.is_dir():
            return []
        return sorted(
            (
                int(p.name)
                for p in root.iterdir()
                if p.is_dir() and p.name.isdigit()
            ),
            reverse=True,
        )

    def direct(ckptr, s: int):
        # the degraded path hardcodes CheckpointManager's current item
        # layout (<dir>/<step>/default); it only runs AFTER the
        # layout-agnostic manager restore failed, so an orbax layout
        # change degrades this fallback, never the healthy path
        item = root / str(s) / "default"
        restored = ckptr.restore(
            item if item.exists() else root / str(s), targets
        )
        return restored["params"], restored["opt"], s

    with ocp.CheckpointManager(directory) as manager:
        # the COMMITTED step set gates every direct restore below: the
        # degraded path bypasses orbax's commit protocol, and on
        # marker-committed storage (gs://) a readable-but-uncommitted
        # step dir holds torn state the manager correctly refuses
        try:
            committed = set(manager.all_steps())
        except Exception:
            committed = None  # manager metadata itself unreadable
        if step is not None:
            try:
                restored = manager.restore(
                    step, args=ocp.args.StandardRestore(targets)
                )
                return restored["params"], restored["opt"], step
            except Exception:
                # the manager infers structure from the WHOLE directory,
                # so a poisoned SIBLING step can break it for a healthy
                # requested step — one direct attempt tells them apart;
                # a genuinely-bad or UNCOMMITTED requested step raises
                if committed is not None and step not in committed:
                    raise
                with ocp.StandardCheckpointer() as ckptr:
                    return direct(ckptr, step)
        latest = manager.latest_step()
        if latest is not None:
            try:
                restored = manager.restore(
                    latest, args=ocp.args.StandardRestore(targets)
                )
                return restored["params"], restored["opt"], latest
            except Exception as e:
                log.warning(
                    "latest checkpoint step %s under %s is unrestorable "
                    "(%s); scanning older steps directly",
                    latest, directory, e,
                )
    steps = (
        sorted(committed, reverse=True)
        if committed is not None
        else scan_steps()
    )
    if not steps:
        raise FileNotFoundError(
            f"no committed checkpoint under {directory!r}"
        )
    # degraded path: per-step restores are immune to a poisoned sibling
    # (a crash between mkdir and data, a filesystem dying mid-rename)
    last_exc: Exception | None = None
    with ocp.StandardCheckpointer() as ckptr:
        for candidate in steps:
            try:
                return direct(ckptr, candidate)
            except Exception as e:
                last_exc = e
                log.warning(
                    "checkpoint step %s under %s is unrestorable (%s); "
                    "trying the next older step",
                    candidate, directory, e,
                )
    # every step failed: a systemic problem (wrong templates, storage
    # outage), NOT an empty directory — surface the real error rather
    # than a FileNotFoundError a resume harness would read as
    # "cold start, reinitialize"
    raise last_exc  # type: ignore[misc]


def _opt_shardings(opt_state, param_sh, replicated, state_sh=None):
    """Shardings for the optax state: AdamW's mu/nu mirror the param
    tree (so they take ``state_sh`` — the param shardings by default,
    the dp-extended ZeRO-1 shardings when enabled); every other leaf
    (step counts, hyperparam scalars) replicates."""
    if state_sh is None:
        state_sh = param_sh
    param_structure = jax.tree.structure(param_sh)

    def map_subtree(subtree):
        if jax.tree.structure(subtree) == param_structure:
            return state_sh
        return jax.tree.map(lambda _: replicated, subtree)

    if isinstance(opt_state, tuple):
        mapped = []
        for element in opt_state:
            if hasattr(element, "mu") and hasattr(element, "nu"):
                mapped.append(type(element)(count=replicated, mu=state_sh, nu=state_sh))
            else:
                mapped.append(jax.tree.map(lambda _: replicated, element))
        return tuple(mapped)
    return map_subtree(opt_state)


def run(
    tiny: bool = False,
    batch_per_device: int = 8,
    seq: int = 128,
    steps: int = 3,
    mesh: Optional[Mesh] = None,
    attention: str = "dense",
    mfu_threshold: Optional[float] = None,
    zero1: bool = False,
    remat: bool = False,
    accum_steps: int = 1,
    roofline: bool = True,
    grad_sync: str = "auto",
    tune_sync: bool = False,
) -> ProbeResult:
    """``mfu_threshold`` turns the MFU gauge into a VERDICT: when set
    and a rated spec exists for the hardware, achieved MFU below the
    threshold fails the probe (BASELINE.md single-chip bar,
    rated.TRAIN_MFU_BAR) — an underperforming chip fails its
    HealthCheck instead of merely exporting a low gauge.

    ``grad_sync`` routes the gradient reduction through the tuned
    collective surface when the mesh allows (build_sharded_train_step);
    the applied mode, the chosen schedule, and — when a tuned schedule
    actually differs from the builtin — the measured
    ``training-step-allreduce-sched`` speedup land in the stdout
    contract. ``tune_sync=True`` first runs a targeted autotune of the
    data axis at the gradient payload, so "auto" has a measured cell to
    dispatch from (otherwise it falls back to the XLA psum)."""
    cfg = tiny_config() if tiny else ProbeModelConfig()
    seq = min(seq, cfg.max_seq_len - 1)
    if mesh is None and attention == "ring":
        # ring attention needs an "sp" axis; default to dp×sp with the
        # smallest useful ring (the per-axis sweep probe covers wider)
        import jax as _jax

        from activemonitor_tpu.parallel.mesh import make_mesh

        n = len(_jax.devices())
        sp = 2 if n % 2 == 0 else 1
        mesh = make_mesh(("data", "model", "sp"), (n // sp, 1, sp))
    mesh = mesh or make_2d_mesh()
    # the batch axis resolves through the partition tier rule: "data"
    # when the mesh carries it, the ("dcn", "ici") pair on a two-tier
    # mesh (hierarchical sync), "ici" on a degenerate single slice
    data_axes, _tier_reason = resolve_tiers(mesh, "data")
    n_data = math.prod(mesh.shape[a] for a in data_axes)
    batch = batch_per_device * n_data

    from activemonitor_tpu.parallel.distributed import distribute

    sync_mode, sync_reason = resolve_grad_sync(mesh, attention, grad_sync, accum_steps)
    if tune_sync and sync_mode == "explicit" and jax.process_count() == 1:
        # targeted tune: every all-reduce schedule raced at THIS mesh's
        # data-axis size and the dominant gradient payload, so the
        # decision the step dispatches below is measured, not assumed
        from activemonitor_tpu.parallel import autotune

        largest_mb = grad_sync_plan(cfg, mesh)["largest_leaf_bytes"] / 1e6
        autotune.tune(
            mesh, axis="data", collectives=("allreduce",),
            sizes_mb=(max(0.25, largest_mb),), dtype=jnp.float32, iters=2,
        )
    if (
        tune_sync and sync_mode == "hierarchical"
        and jax.process_count() == 1 and len(data_axes) > 1
    ):
        # two-tier targeted tune: per-tier winners AND the latency-path
        # threshold, both at the dominant gradient payload (plus one
        # small-message point so the threshold brackets a crossover)
        from activemonitor_tpu.parallel import autotune

        largest_mb = max(_leaf_payloads(cfg, jnp.float32).values()) / 1e6
        autotune.tune_hierarchical(
            mesh, sizes_mb=(0.004, max(0.016, largest_mb)),
            dtype=jnp.float32, iters=2,
        )

    step_fn, params, opt_state, data_sh = build_sharded_train_step(
        cfg, mesh, attention=attention, zero1=zero1, remat=remat,
        accum_steps=accum_steps, grad_sync=grad_sync,
    )
    tokens = distribute(
        jax.random.randint(jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size),
        data_sh,
    )

    # cold step (compile), forced through a host readback. The compile
    # goes through the AOT path when it can, for two reasons: the
    # roofline capture below reads cost_analysis() off the VERY
    # executable the timing measures (a second lower+compile of the
    # battery's most expensive program would roughly double the probe's
    # compile bill), and the timed loop then calls the same compiled
    # object the traced path would have cached anyway.
    t0 = time.perf_counter()
    step_callable = step_fn
    xla_cost = None
    try:
        compiled_step = step_fn.lower(params, opt_state, tokens).compile()
    except Exception:
        compiled_step = None  # legacy lowering quirk: traced jit path
    if compiled_step is not None:
        from activemonitor_tpu.utils.compat import compiled_cost_analysis

        step_callable = compiled_step
        xla_cost = compiled_cost_analysis(compiled_step)
    params, opt_state, loss = step_callable(params, opt_state, tokens)
    losses = [float(loss)]
    compile_seconds = time.perf_counter() - t0

    # steady-state step time via the chain-difference method: constant
    # dispatch/tunnel overhead cancels (see utils/timing.py)
    def timed_chain(k):
        nonlocal params, opt_state
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            params, opt_state, loss = step_callable(params, opt_state, tokens)
        value = float(loss)
        return time.perf_counter() - t0, value

    k_small, k_big = max(1, steps // 2), max(2, steps * 2)
    t_small, _ = timed_chain(k_small)
    t_big, last_loss = timed_chain(k_big)
    # lengthen the chain when the delta is inside the noise floor
    # (tiny models on fast hardware) — same policy as chain_delta_seconds;
    # the longer chain's timing becomes the next baseline (no re-run).
    # MULTI-PROCESS: the retry decision is wall-clock local, and a step
    # contains collectives — processes disagreeing on how many steps to
    # run would deadlock the mesh, so the adaptive loop only runs when
    # this process owns every device
    adaptive = jax.process_count() == 1
    for _ in range(CHAIN_RETRIES if adaptive else 0):
        if not needs_longer_chain(t_small, t_big):
            break
        k_small, t_small = k_big, t_big
        k_big = k_big * CHAIN_GROWTH
        t_big, last_loss = timed_chain(k_big)
    step_seconds = max((t_big - t_small) / (k_big - k_small), 1e-9)
    losses.append(last_loss)

    tokens_per_step = batch * seq
    # train FLOPs ≈ 3 × forward (fwd + bwd ≈ 2× fwd)
    model_flops = 3 * cfg.flops_per_token() * tokens_per_step
    achieved_tflops = model_flops / step_seconds / 1e12
    # rated peak, platform gate and denominator all follow the mesh the
    # step actually ran on, not the process-default device set
    mesh_device = mesh.devices.flat[0]
    rated = rated_for(mesh_device.device_kind)
    details = {
        "mesh": dict(mesh.shape),
        "attention": attention,
        "zero1": zero1,
        "remat": remat,
        "accum_steps": accum_steps,
        "params": param_count(cfg),
        "batch": batch,
        "seq": seq,
        "compile_seconds": round(compile_seconds, 2),
        "step_seconds": round(step_seconds, 5),
        "tokens_per_second": round(tokens_per_step / step_seconds),
        "loss_first": losses[0],
        "loss_last": losses[-1],
    }
    metrics = [
        ProbeMetric(
            "train-step-seconds", step_seconds, help="Per-step time (min-based chain-delta estimate)"
        ),
        ProbeMetric(
            "train-tokens-per-second",
            tokens_per_step / step_seconds,
            help="Training throughput of the probe transformer",
        ),
        ProbeMetric(
            "train-model-tflops", achieved_tflops,
            help="Achieved model FLOP/s (3x fwd convention), TFLOP/s",
        ),
    ]
    # tuned-dispatch evidence: which schedule the gradient sync rode,
    # and — when a tuned schedule actually differs from the builtin —
    # the measured step-time speedup against an explicit-"xla" twin of
    # the same step (isolating schedule choice, not sync plumbing)
    if sync_mode == "explicit":
        details["grad_sync"] = "explicit"
        sync_plan = grad_sync_plan(cfg, mesh)
        chosen = sync_plan["schedule"] if grad_sync == "auto" else grad_sync
        details["allreduce_schedule"] = chosen
        details["allreduce_plan"] = sync_plan
        allreduce_speedup = 1.0
        if chosen != "xla" and adaptive:
            xla_step, xla_params, xla_opt, _ = build_sharded_train_step(
                cfg, mesh, attention=attention, zero1=zero1, remat=remat,
                accum_steps=accum_steps, grad_sync="xla",
            )

            def xla_chain(k):
                nonlocal xla_params, xla_opt
                t0 = time.perf_counter()
                value = None
                for _ in range(k):
                    xla_params, xla_opt, value = xla_step(
                        xla_params, xla_opt, tokens
                    )
                float(value)
                return time.perf_counter() - t0

            xla_chain(1)  # compile + warm
            tb_small = xla_chain(k_small)
            tb_big = xla_chain(k_big)
            builtin_seconds = max(
                (tb_big - tb_small) / (k_big - k_small), 1e-9
            )
            allreduce_speedup = builtin_seconds / step_seconds
        metrics.append(
            ProbeMetric(
                "training-step-allreduce-sched",
                allreduce_speedup,
                help="Tuned grad-sync schedule speedup vs the XLA "
                "builtin sync (builtin step time / tuned step time; "
                "1.0 = builtin dispatched)",
            )
        )
        details["allreduce_sched_speedup"] = round(allreduce_speedup, 4)
    elif sync_mode == "hierarchical":
        # the per-tier evidence: which path the dominant gradient leaf
        # rode (latency vs bandwidth vs degenerate-flat, with the tuned
        # threshold that decided it) and the schedule each tier
        # resolved — exported in the stdout contract both as the
        # details block and as a numeric gauge (1 = latency path)
        from activemonitor_tpu.parallel.autotune import hier_plan_label

        details["grad_sync"] = "hierarchical"
        plan = hier_sync_plan(cfg, mesh, schedule=grad_sync)
        details["hier_sync"] = plan
        details["allreduce_schedule"] = hier_plan_label(plan)
        metrics.append(
            ProbeMetric(
                "training-step-hier-sync",
                1.0 if plan.get("variant") == "latency" else 0.0,
                help="Hierarchical grad sync dispatched: "
                f"{details['allreduce_schedule']} "
                "(1 = latency path, 0 = bandwidth/flat)",
            )
        )
    else:
        details["grad_sync"] = f"implicit({sync_reason})"
        details["allreduce_schedule"] = "xla(implicit)"
    # rated_for() is None off-TPU, so no platform check needed — and
    # tests can exercise the gate by stubbing rated_for
    mfu = None
    if rated is not None:
        mfu = achieved_tflops / (rated.bf16_tflops * mesh.devices.size)
        metrics.append(
            ProbeMetric("train-mfu", mfu, help="Model FLOPs utilization vs rated peak")
        )
        details["mfu"] = round(mfu, 4)
    # verdict: the step must run and produce a finite, decreasing-or-flat loss
    ok = bool(all(jnp.isfinite(jnp.asarray(losses))))
    if mfu_threshold is not None:
        details["mfu_threshold"] = mfu_threshold
        if mfu is None:
            # can't measure against a bar we can't compute — report,
            # don't guess a verdict
            details["mfu_gate"] = "skipped(no rated spec for this hardware)"
        elif mfu < mfu_threshold:
            details["mfu_gate"] = f"FAILED ({mfu:.3f} < {mfu_threshold})"
            ok = False
        else:
            details["mfu_gate"] = "passed"
    result = ProbeResult(
        ok=bool(ok),
        summary=(
            f"train step {step_seconds * 1e3:.1f}ms, "
            f"{tokens_per_step / step_seconds:,.0f} tok/s, loss {losses[-1]:.3f}"
        ),
        metrics=metrics,
        details=details,
    )
    # roofline evidence under the MFU (obs/roofline.py): the XLA cost
    # was read off the COMPILED step executable itself — the very
    # program the timing measured, no second compile — so on TPU the
    # intensity reflects what the compiler actually scheduled
    # (remat/zero1/accum change it), with the 3x-fwd analytic model
    # plus one parameter+optimizer streaming pass as the
    # interpret-mode/legacy fallback. Small probe models are often
    # memory-bound: a LOW MFU with a healthy memory-bound roofline
    # fraction is an overhead-bound probe shape, not a sick chip —
    # exactly the ambiguity this verdict exists to resolve.
    from activemonitor_tpu.obs import roofline as roofline_model

    n_devices = mesh.devices.size
    param_bytes = param_count(cfg) * 4  # f32 master weights
    roofline_model.apply(
        result,
        roofline_model.capture(
            "train",
            seconds=step_seconds,
            xla_cost=xla_cost,
            model_flops=model_flops / n_devices,
            # per device: activations ~ 3 passes over token embeddings
            # per layer, plus params + AdamW mu/nu read and written
            model_bytes=float(3 * param_bytes / n_devices)
            + float(
                3 * cfg.n_layers * tokens_per_step * cfg.d_model * 2 / n_devices
            ),
            device=mesh_device,
            enabled=roofline,
        ),
    )
    return result
