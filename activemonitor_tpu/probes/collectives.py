"""Collectives-sweep probe — the full XLA collective set over ICI.

The ici-allreduce probe answers the north-star question; this probe
characterizes the whole communication surface the parallelism code
relies on: all-reduce (dp gradient sync), all-gather (tp/weight
gather), reduce-scatter (ZeRO/psum_scatter), all-to-all (ep dispatch,
ops/moe.py) and single-hop ppermute (ring attention, ops/ring_attention
.py; pipeline, ops/pipeline.py). A degradation only one pattern hits —
e.g. a routing fault that halves the bisection but leaves neighbor
links intact — shows up here before it shows up as slow training.

Exports, per collective C in {allreduce, allgather, reducescatter,
alltoall, ringhop} (prefix ``collective-``, distinct from the
north-star probe's ``ici-`` gauges so a merged battery contract never
carries duplicate names):

- ``collective-<C>-busbw-gbps`` — NCCL busbw convention
- ``collective-<C>-fraction-of-rated`` — busbw / rated ceiling (TPU)

Rated ceilings assume the same bidirectional-ring model as probes/ici:
2 x unidir link bw for the ring collectives, 1 x for a single hop —
except all-to-all, which is bisection-bound on a ring: each half
exchanges n*S/4 bytes per direction across the cut's 2 links, capping
busbw at 8*B*(n-1)/n^2.

Verdict: every collective's fraction must clear ``threshold`` (rated
hardware, >1 device); otherwise informational-pass, like the other
bandwidth probes. No reference counterpart (the reference has no
communication backend at all, SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from activemonitor_tpu.parallel.collectives import (
    CollectiveResult,
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for

ALL_CASES = ("allreduce", "allgather", "reducescatter", "alltoall", "ringhop")

_BENCH: Dict[str, Callable] = {
    "allreduce": all_reduce_bandwidth,
    "allgather": all_gather_bandwidth,
    "reducescatter": reduce_scatter_bandwidth,
    "alltoall": all_to_all_bandwidth,
    "ringhop": ppermute_ring_bandwidth,
}


def _rated_busbw(name: str, unidir_gbps: float, n: int) -> float:
    """Achievable-busbw ceiling on a bidirectional ring of n devices
    with per-direction link bandwidth ``unidir_gbps`` (see module doc)."""
    if name == "ringhop":
        return unidir_gbps
    if name == "alltoall":
        return 8 * unidir_gbps * (n - 1) / n**2
    return 2 * unidir_gbps


def run(
    size_mb: float = 64.0,
    iters: int = 5,
    threshold: float = 0.8,
    cases: Optional[Sequence[str]] = None,
) -> ProbeResult:
    cases = tuple(cases) if cases else ALL_CASES
    unknown = [c for c in cases if c not in _BENCH]
    if unknown:
        raise ValueError(f"unknown collectives {unknown}; pick from {ALL_CASES}")
    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return ProbeResult(
            ok=True,
            summary=f"collectives sweep skipped: {n} device(s), nothing to move",
            metrics=[],
            details={"devices": n, "skipped": True},
        )

    mesh = make_1d_mesh()
    results: List[Tuple[str, CollectiveResult]] = [
        (name, _BENCH[name](mesh, size_mb=size_mb, iters=iters)) for name in cases
    ]
    rated = rated_for(devices[0].device_kind)
    on_tpu = devices[0].platform == "tpu"

    metrics: List[ProbeMetric] = []
    details: Dict = {"devices": n, "device_kind": devices[0].device_kind}
    fractions: Dict[str, float] = {}
    for name, result in results:
        metrics.append(
            ProbeMetric(
                f"collective-{name}-busbw-gbps",
                result.busbw_gbps,
                help=f"Measured {result.name} bus bandwidth (NCCL convention), GB/s",
            )
        )
        details[f"{name}_busbw_gbps"] = round(result.busbw_gbps, 2)
        if rated is not None and on_tpu:
            rated_busbw = _rated_busbw(name, rated.ici_unidir_gbps, n)
            fraction = result.busbw_gbps / rated_busbw
            fractions[name] = fraction
            metrics.append(
                ProbeMetric(
                    f"collective-{name}-fraction-of-rated",
                    fraction,
                    help=f"{result.name} busbw / achievable ring ceiling",
                )
            )
            details[f"{name}_fraction_of_rated"] = round(fraction, 3)

    if fractions:
        worst = min(fractions, key=fractions.get)
        ok = fractions[worst] >= threshold
        summary = (
            f"{len(results)} collectives over {n}x {rated.generation}: worst "
            f"{worst} at {fractions[worst]:.0%} of rated"
            + ("" if ok else f" (< {threshold:.0%} threshold)")
        )
    else:
        ok = True
        best = max(results, key=lambda nr: nr[1].busbw_gbps)
        summary = (
            f"{len(results)} collectives over {n} device(s): best {best[0]} "
            f"{best[1].busbw_gbps:.1f} GB/s (no rated comparison)"
        )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
