"""MXU matmul probe.

Times a large bf16 matmul — the op the systolic array exists for — and
compares achieved TFLOP/s against the chip's rated bf16 peak. A chip
delivering well under rated peak on a clean 8k×8k×8k matmul is
throttled, misconfigured, or sick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    dim: int = 8192,
    iters: int = 10,
    threshold: float = 0.75,
) -> ProbeResult:
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if not on_tpu and dim > 2048:
        dim = 1024  # keep CPU runs quick; no rated comparison there anyway
    a = jax.random.normal(jax.random.key(0), (dim, dim), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (dim, dim), jnp.bfloat16)

    def make_chain(k):
        @jax.jit
        def chain(a, b):
            x = b
            for _ in range(k):  # data-dependent: each feeds the next
                x = jnp.dot(a, x, preferred_element_type=jnp.bfloat16)
            return x.astype(jnp.float32).sum()

        return chain

    seconds = chain_delta_seconds(make_chain, a, b, k1=2, k2=8, iters=iters)
    tflops = 2 * dim**3 / seconds / 1e12

    rated = rated_for(device.device_kind)
    metrics = [
        ProbeMetric("mxu-matmul-tflops", tflops, help="Achieved bf16 matmul TFLOP/s")
    ]
    details = {"dim": dim, "seconds_per_op": seconds, "device_kind": device.device_kind}
    ok = True
    if rated is not None and on_tpu:
        fraction = tflops / rated.bf16_tflops
        metrics.append(
            ProbeMetric(
                "mxu-fraction-of-rated", fraction, help="Achieved / rated bf16 peak"
            )
        )
        details["rated_tflops"] = rated.bf16_tflops
        details["fraction"] = round(fraction, 3)
        ok = fraction >= threshold
        summary = f"matmul {tflops:.0f} TFLOP/s = {fraction:.0%} of rated {rated.bf16_tflops:.0f}"
    else:
        summary = f"matmul {tflops:.2f} TFLOP/s on {device.platform} (no rated comparison)"
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
