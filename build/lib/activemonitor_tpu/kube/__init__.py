"""Minimal native Kubernetes REST layer.

The reference talks to the API server through client-go / controller-
runtime (reference: cmd/main.go:70-118, healthcheck_controller.go:134,
:155, :617). This package is the framework's own equivalent: a small
async REST client built directly on aiohttp — no dependency on the
``kubernetes`` Python package — plus an in-process stub API server
(:mod:`activemonitor_tpu.kube.stub`) that plays the role the reference's
envtest binaries play in its integration tier (reference:
internal/controllers/suite_test.go:67-134).
"""

from activemonitor_tpu.kube.client import ApiError, KubeApi, api_path, core_path
from activemonitor_tpu.kube.config import KubeConfig, load_kube_config

__all__ = [
    "ApiError",
    "KubeApi",
    "KubeConfig",
    "api_path",
    "core_path",
    "load_kube_config",
]
