"""Fused flash-attention kernel (ops/flash_attention.py) + probe.

Runs in Pallas interpret mode on the CPU mesh — the same code path
Mosaic compiles on TPU (measured there: ~90 TFLOP/s causal on v5e at
S=4096 with the default blocks, ~4-5x unfused XLA attention).
"""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.flash_attention import attention_flops, flash_attention
from activemonitor_tpu.ops.ring_attention import reference_attention


def _qkv(batch=1, seq=256, heads=2, head_dim=64, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(0), 3)
    return tuple(
        jax.random.normal(k, (batch, seq, heads, head_dim), dtype) for k in keys
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(256, 256), (64, 64), (64, 128), (128, 64)])
def test_matches_reference(causal, block_q, block_k):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == want.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_bf16_inputs_match_reference():
    q, k, v = _qkv(batch=2, seq=128, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=64, block_k=64)
    want = reference_attention(q, k, v)
    err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )
    assert got.dtype == jnp.bfloat16
    assert err < 2e-2  # bf16 output rounding


def test_blocks_clamped_to_seq():
    # default blocks (1024/512) exceed seq — must clamp, not raise
    q, k, v = _qkv(seq=128)
    got = flash_attention(q, k, v)
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_bhsd_layout_matches_bshd():
    q, k, v = _qkv(seq=128)
    want = flash_attention(q, k, v, block_q=64, block_k=64)
    got = flash_attention(
        *(jnp.swapaxes(x, 1, 2) for x in (q, k, v)),
        block_q=64,
        block_k=64,
        layout="bhsd",
    )
    assert float(jnp.max(jnp.abs(jnp.swapaxes(got, 1, 2) - want))) == 0.0


def test_bad_layout_rejected():
    q, k, v = _qkv(seq=128)
    with pytest.raises(ValueError, match="layout"):
        flash_attention(q, k, v, layout="sbhd")


def test_indivisible_blocks_adapt():
    # explicit 128-blocks don't divide seq=192 — the wrapper adapts to
    # the largest tileable divisor (96) instead of raising
    q, k, v = _qkv(seq=192)
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_mismatched_shapes_rejected():
    q, k, v = _qkv(seq=128)
    with pytest.raises(ValueError, match="k/v shapes differ"):
        flash_attention(q, k[:, :64], v)
    with pytest.raises(ValueError, match="batch or head_dim"):
        flash_attention(q, k[:, :, :, :32], v[:, :, :, :32])
    with pytest.raises(ValueError, match="divisible by n_kv_heads"):
        # 2 q heads cannot group over 2-but-sliced-to-odd kv heads
        flash_attention(_qkv(seq=128, heads=4)[0], k[:, :, :1].repeat(3, 2), v[:, :, :1].repeat(3, 2))


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _qkv(seq=256)
    tgt = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(
            (flash_attention(q, k, v, causal=causal, block_q=128, block_k=128) - tgt)
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum((reference_attention(q, k, v, causal=causal) - tgt) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", got, want):
        err = float(jnp.max(jnp.abs(a - b)))
        assert err < 1e-4, f"d{name} err {err}"


def test_gradients_adapt_blocks_to_any_forward_seq():
    # seq=384 divides the forward's 128-blocks but not the backward's
    # preferred 1024x256 — the backward must shrink its blocks, not raise
    q, k, v = _qkv(seq=384)
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, block_q=128, block_k=128) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(got, want):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_plan_padding_avoids_block_collapse():
    from activemonitor_tpu.ops.flash_attention import _plan_padding

    # healthy divisors: just the 8-alignment pad, fitted block kept
    assert _plan_padding(4096, 1024) == (4096, 1024)
    assert _plan_padding(100, 1024) == (104, 104)
    assert _plan_padding(192, 128) == (192, 96)  # within 2x: no extra pad
    # divisor collapse (136 = 8x17 -> only divisor 8): pad to the block
    assert _plan_padding(136, 128) == (256, 128)
    assert _plan_padding(1000, 512) == (1024, 512)
    # a healthy mid-size divisor must NOT trigger near-2x padding:
    # 1032 = 8*3*43 fits 344-blocks — doubling rows to 2048 for
    # 1024-blocks costs ~4x attention work for a ~3x block gain
    assert _plan_padding(1032, 1024) == (1032, 344)
    assert _plan_padding(4104, 1024) == (4104, 456)
    # but a true cliff (8*131 -> sole divisor 8) still pads
    assert _plan_padding(1048, 1024) == (2048, 1024)


def test_block_collapse_seq_still_correct():
    # seq=136 pads to 256 with 128-blocks and masked keys — must match
    # the unpadded reference in forward and gradients
    q, k, v = _qkv(seq=136)
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5
    g = jax.grad(
        lambda a, b, c: jnp.sum(
            flash_attention(a, b, c, block_q=128, block_k=128) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda a, b, c: jnp.sum(reference_attention(a, b, c) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_fit_block_prefers_tileable_divisors():
    from activemonitor_tpu.ops.flash_attention import _fit_block

    assert _fit_block(4096, 1024) == 1024
    assert _fit_block(384, 256) == 192  # divisor, multiple of 8
    assert _fit_block(640, 256) == 160
    assert _fit_block(24, 1024) == 24  # 8-aligned seq: whole seq is legal
    with pytest.raises(ValueError, match="no TPU-tileable block"):
        _fit_block(100, 256)  # non-8-aligned: Mosaic would reject any tile


@pytest.mark.parametrize("causal", [True, False])
def test_non_tileable_seq_pads_and_masks(causal):
    # seq=100 is not a multiple of Mosaic's 8-row tiling unit — the
    # wrapper zero-pads to 104, masks the fake keys, and slices the
    # output back; forward AND gradients must match the unpadded
    # reference exactly
    q, k, v = _qkv(seq=100)
    got = flash_attention(q, k, v, causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == want.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_flash = jax.grad(
        loss(lambda a, b, c: flash_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        assert a.shape == b.shape
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize("n_heads,n_kv_heads", [(8, 2), (4, 1), (4, 4)])
@pytest.mark.parametrize("causal", [True, False])
def test_gqa_grouped_heads(n_heads, n_kv_heads, causal):
    """GQA/MQA: fewer K/V heads than query heads, never materialized —
    forward and the group-summed dK/dV must match the repeat-heads
    reference (whose autodiff sums the group implicitly)."""
    keys = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(keys[0], (2, 128, n_heads, 32), jnp.float32)
    k = jax.random.normal(keys[1], (2, 128, n_kv_heads, 32), jnp.float32)
    v = jax.random.normal(keys[2], (2, 128, n_kv_heads, 32), jnp.float32)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == q.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_flash = jax.grad(
        loss(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, block_q=64, block_k=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert g_flash[1].shape == k.shape  # group already summed
    for a, b in zip(g_flash, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize("seq_q,seq_k", [(64, 256), (64, 192), (100, 50)])
@pytest.mark.parametrize("causal", [True, False])
def test_cross_attention_lengths(seq_q, seq_k, causal):
    """seq_k != seq_q (decode / cross-attention shapes). Causal masking
    is bottom-right aligned: a short q sees the whole KV prefix."""
    if causal and seq_q > seq_k:
        # leading queries would have no visible keys — rejected up front
        q, k, v = (
            jax.random.normal(kk, (1, s, 2, 32), jnp.float32)
            for kk, s in zip(jax.random.split(jax.random.key(2), 3),
                             (seq_q, seq_k, seq_k))
        )
        with pytest.raises(ValueError, match="no visible keys"):
            flash_attention(q, k, v, causal=True)
        return
    keys = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(keys[0], (2, seq_q, 2, 32), jnp.float32)
    k = jax.random.normal(keys[1], (2, seq_k, 2, 32), jnp.float32)
    v = jax.random.normal(keys[2], (2, seq_k, 2, 32), jnp.float32)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == q.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_flash = jax.grad(
        loss(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, block_q=64, block_k=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


@pytest.mark.parametrize("causal", [True, False])
def test_segment_ids_match_reference(causal):
    """Packed sequences: attention stays within matching segment ids,
    forward and gradients, against the reference oracle."""
    q, k, v = _qkv(batch=2, seq=128, heads=4, head_dim=32)
    seg = jnp.concatenate(
        [jnp.zeros((2, 50), jnp.int32), jnp.ones((2, 78), jnp.int32)], axis=1
    )
    got = flash_attention(
        q, k, v, causal=causal, segment_ids=seg, block_q=64, block_k=64
    )
    want = reference_attention(q, k, v, causal=causal, segment_ids=seg)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_flash = jax.grad(
        loss(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, segment_ids=seg, block_q=64, block_k=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(
            a, b, c, causal=causal, segment_ids=seg
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_segment_ids_equal_separate_sequences():
    """The gold semantic: a packed batch must reproduce each sequence
    attended SEPARATELY — packing is an optimization, not a semantics
    change."""
    q, k, v = _qkv(batch=2, seq=128, heads=4, head_dim=32)
    seg = jnp.concatenate(
        [jnp.zeros((2, 50), jnp.int32), jnp.ones((2, 78), jnp.int32)], axis=1
    )
    packed = flash_attention(
        q, k, v, causal=True, segment_ids=seg, block_q=64, block_k=64
    )
    sep_a = flash_attention(
        q[:, :50], k[:, :50], v[:, :50], causal=True, block_q=64, block_k=64
    )
    sep_b = flash_attention(
        q[:, 50:], k[:, 50:], v[:, 50:], causal=True, block_q=64, block_k=64
    )
    assert float(jnp.max(jnp.abs(packed[:, :50] - sep_a))) < 1e-5
    assert float(jnp.max(jnp.abs(packed[:, 50:] - sep_b))) < 1e-5


def test_segment_ids_compose_with_gqa_and_padding():
    """Segments + grouped heads + non-8-multiple (padded) lengths in
    one call — padding sentinels must never match a real segment."""
    keys = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(keys[0], (1, 100, 4, 32), jnp.float32)
    k = jax.random.normal(keys[1], (1, 100, 2, 32), jnp.float32)
    v = jax.random.normal(keys[2], (1, 100, 2, 32), jnp.float32)
    seg = jnp.concatenate(
        [jnp.zeros((1, 40), jnp.int32), jnp.ones((1, 60), jnp.int32)], axis=1
    )
    got = flash_attention(q, k, v, segment_ids=seg)
    want = reference_attention(q, k, v, segment_ids=seg)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5
    g = jax.grad(
        lambda a, b, c: jnp.sum(
            flash_attention(a, b, c, segment_ids=seg) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda a, b, c: jnp.sum(
            reference_attention(a, b, c, segment_ids=seg) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_segment_ids_validation():
    q, k, v = _qkv(seq=128)
    with pytest.raises(ValueError, match="segment_ids shapes"):
        flash_attention(q, k, v, segment_ids=jnp.zeros((1, 64), jnp.int32))
    with pytest.raises(ValueError, match="tuple"):
        flash_attention(
            q, k[:, :64], v[:, :64], causal=False,
            segment_ids=jnp.zeros((1, 128), jnp.int32),
        )


def test_gqa_cross_odd_seq_combined():
    """All three generalizations at once: grouped heads + differing
    odd (padded) lengths + causal offset, with gradients."""
    keys = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(keys[0], (1, 50, 4, 32), jnp.float32)
    k = jax.random.normal(keys[1], (1, 100, 2, 32), jnp.float32)
    v = jax.random.normal(keys[2], (1, 100, 2, 32), jnp.float32)
    got = flash_attention(q, k, v)
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5
    g = jax.grad(
        lambda a, b, c: jnp.sum(flash_attention(a, b, c) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    gr = jax.grad(
        lambda a, b, c: jnp.sum(reference_attention(a, b, c) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_gradients_bf16_and_uneven_blocks():
    # bwd uses its own block shape (1024x256 clamped to seq) — distinct
    # q/k blocking must still produce reference-level gradients
    q, k, v = _qkv(seq=128, dtype=jnp.bfloat16)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

        return inner

    got = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert a.dtype == jnp.bfloat16
        scale = max(1e-9, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        rel = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))) / scale
        assert rel < 5e-2  # bf16 grads


def test_attention_flops_causal_half():
    full = attention_flops(2, 256, 4, 64, causal=False)
    causal = attention_flops(2, 256, 4, 64, causal=True)
    assert full == 4.0 * 64 * 2 * 4 * 256 * 256
    assert abs(causal / full - 0.5) < 0.01  # (S+1)/2S


@pytest.mark.slow  # minutes of interpret-mode compile; tier-2 coverage
def test_model_flash_attention_matches_dense_on_mesh():
    # the probe model's flash path (shard_map over tp heads on the
    # dp x tp mesh) must agree with dense attention in loss and grads
    from activemonitor_tpu.models.probe_model import (
        flash_attention_fn,
        init_params,
        loss_fn,
        tiny_config,
    )
    from activemonitor_tpu.parallel.mesh import make_2d_mesh

    mesh = make_2d_mesh()
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    dense = float(loss_fn(params, tokens, cfg))
    flash = float(loss_fn(params, tokens, cfg, flash_attention_fn(cfg, mesh)))
    assert abs(dense - flash) < 1e-3  # bf16 compute
    grads_dense = jax.grad(lambda p: loss_fn(p, tokens, cfg))(params)
    grads_flash = jax.grad(
        lambda p: loss_fn(p, tokens, cfg, flash_attention_fn(cfg, mesh))
    )(params)
    errs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_dense, grads_flash
    )
    assert max(jax.tree.leaves(errs)) < 5e-3


def test_model_flash_rejects_oversized_tp_axis():
    from activemonitor_tpu.models.probe_model import flash_attention_fn, tiny_config
    from jax.sharding import Mesh
    import numpy as np

    # tiny_config has 4 heads; an 8-wide model axis cannot shard them
    mesh = Mesh(np.array(jax.devices()).reshape(1, 8), ("data", "model"))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention_fn(tiny_config(), mesh)


@pytest.mark.slow  # minutes of interpret-mode compile; tier-2 coverage
def test_probe_model_gqa_trains_and_decodes():
    """The probe model runs GQA end to end: dense and fused-kernel
    losses agree, a train step works, and the decode cache holds only
    the narrower kv heads."""
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        decode_step,
        flash_attention_fn,
        init_kv_cache,
        init_params,
        loss_fn,
        param_count,
    )
    from activemonitor_tpu.parallel.mesh import make_2d_mesh

    cfg = ProbeModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    params = init_params(jax.random.key(0), cfg)
    assert params["layers"][0]["wkv"].shape == (64, 2, 2, 16)
    assert param_count(cfg) == sum(
        x.size for x in jax.tree.leaves(params)
    )
    tokens = jax.random.randint(jax.random.key(1), (4, 33), 0, cfg.vocab_size)
    dense = float(loss_fn(params, tokens, cfg))
    assert dense == dense and dense > 0
    # tp axis must divide the NARROW kv heads too (2) — 2-wide model axis
    mesh = make_2d_mesh(shape=(4, 2))
    flash = float(loss_fn(params, tokens, cfg, flash_attention_fn(cfg, mesh)))
    assert abs(dense - flash) < 1e-3
    with pytest.raises(ValueError, match="n_kv_heads"):
        flash_attention_fn(cfg, make_2d_mesh(shape=(2, 4)))
    grads = jax.grad(lambda p: loss_fn(p, tokens, cfg))(params)
    assert all(
        bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads)
    )

    cache = init_kv_cache(cfg, batch=2, max_seq=8)
    assert cache["k"].shape == (2, 2, 2, 8, 16)  # [L, B, Hkv, S, D]
    token = jnp.zeros((2,), jnp.int32)
    logits, cache = decode_step(params, cache, token, jnp.int32(0), cfg)
    assert logits.shape == (2, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize("n_kv_heads", [8, 2, 1])
def test_flash_decode_matches_masked_dense(n_kv_heads):
    """The fused decode kernel against the masked-cache dense
    computation, across positions including block boundaries, MHA
    through MQA."""
    from activemonitor_tpu.ops.flash_attention import flash_decode

    B, H, D, S = 2, 8, 64, 128
    keys = jax.random.split(jax.random.key(6), 3)
    q = jax.random.normal(keys[0], (B, H, D), jnp.float32)
    kc = jax.random.normal(keys[1], (B, n_kv_heads, S, D), jnp.float32)
    vc = jax.random.normal(keys[2], (B, n_kv_heads, S, D), jnp.float32)

    def dense(pos):
        g = H // n_kv_heads
        qg = q.reshape(B, n_kv_heads, g, D)
        s = jnp.einsum("bhgd,bhsd->bhgs", qg, kc) / jnp.sqrt(D)
        s = jnp.where(jnp.arange(S)[None, None, None] <= pos, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhgs,bhsd->bhgd", p, vc).reshape(B, H, D)

    for pos in (0, 63, 64, 100, 127):
        got = flash_decode(q, kc, vc, jnp.int32(pos), block_k=64)
        assert float(jnp.max(jnp.abs(got - dense(pos)))) < 1e-5

    # pos must be traceable (the decode loop jits once, reruns per token)
    fn = jax.jit(lambda p: flash_decode(q, kc, vc, p, block_k=64))
    got = fn(jnp.int32(77))
    assert float(jnp.max(jnp.abs(got - dense(77)))) < 1e-5
    # out-of-range pos clamps to the full cache instead of returning an
    # unwritten output buffer (pos is traced — unvalidatable)
    got = fn(jnp.int32(S + 100))
    assert float(jnp.max(jnp.abs(got - dense(S - 1)))) < 1e-5


def test_flash_decode_validation():
    from activemonitor_tpu.ops.flash_attention import flash_decode

    q = jnp.zeros((1, 6, 32), jnp.float32)
    cache = jnp.zeros((1, 4, 64, 32), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        flash_decode(q, cache, cache, jnp.int32(0))
    bad = jnp.zeros((1, 2, 60, 32), jnp.float32)
    with pytest.raises(ValueError, match="multiple of 8"):
        flash_decode(q[:, :4], bad, bad, jnp.int32(0))


@pytest.mark.slow  # multi-position fused decode walk; tier-2 coverage
def test_decode_step_flash_matches_dense():
    """The model's fused decode path reproduces the dense masked-cache
    path, MHA and GQA."""
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        decode_step,
        init_kv_cache,
        init_params,
    )

    for n_kv in (4, 2):
        cfg = ProbeModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=n_kv,
            n_layers=2, d_ff=64, max_seq_len=16, dtype=jnp.float32,
        )
        params = init_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 5), 0, cfg.vocab_size)
        cache_a = init_kv_cache(cfg, batch=2, max_seq=8)
        cache_b = init_kv_cache(cfg, batch=2, max_seq=8)
        for pos in range(tokens.shape[1]):
            la, cache_a = decode_step(
                params, cache_a, tokens[:, pos], jnp.int32(pos), cfg
            )
            lb, cache_b = decode_step(
                params, cache_b, tokens[:, pos], jnp.int32(pos), cfg,
                use_flash=True,
            )
        assert float(jnp.max(jnp.abs(la - lb))) < 1e-4


@pytest.mark.parametrize("n_kv_heads,use_flash", [(4, False), (2, False), (2, True)])
def test_prefill_matches_stepped_decode(n_kv_heads, use_flash):
    """Batched prefill must be indistinguishable from feeding the
    prompt token-by-token through decode_step — same last-token logits,
    same banked K/V, and a fused decode continues correctly from it."""
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        decode_step,
        init_kv_cache,
        init_params,
        prefill,
    )

    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=n_kv_heads,
        n_layers=2, d_ff=64, max_seq_len=16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab_size)
    cache_a = init_kv_cache(cfg, 2, 8)
    for pos in range(tokens.shape[1]):
        la, cache_a = decode_step(
            params, cache_a, tokens[:, pos], jnp.int32(pos), cfg
        )
    cache_b = init_kv_cache(cfg, 2, 8)
    lb, cache_b = prefill(params, cache_b, tokens, cfg, use_flash=use_flash)
    assert float(jnp.max(jnp.abs(la - lb))) < 1e-5
    assert (
        float(jnp.max(jnp.abs(cache_a["k"][..., :6, :] - cache_b["k"][..., :6, :])))
        < 1e-5
    )
    next_a, _ = decode_step(params, cache_a, tokens[:, 0], jnp.int32(6), cfg)
    next_b, _ = decode_step(
        params, cache_b, tokens[:, 0], jnp.int32(6), cfg, use_flash=True
    )
    assert float(jnp.max(jnp.abs(next_a - next_b))) < 1e-4


def test_gqa_decode_matches_forward():
    """Decode-cache GQA attention must agree with the batched forward
    on the same prefix (the decode path reshapes query groups against
    the narrow cache)."""
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        decode_step,
        forward,
        init_kv_cache,
        init_params,
    )

    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=1, n_layers=2,
        d_ff=64, max_seq_len=16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab_size)
    want = forward(params, tokens, cfg)  # [B, S, V]
    cache = init_kv_cache(cfg, batch=2, max_seq=8)
    for pos in range(tokens.shape[1]):
        logits, cache = decode_step(
            params, cache, tokens[:, pos], jnp.int32(pos), cfg
        )
    assert float(jnp.max(jnp.abs(logits - want[:, -1]))) < 1e-4


@pytest.mark.slow  # whole train-step compile through the fused kernel; tier-2 coverage
def test_training_step_probe_flash_attention():
    from activemonitor_tpu.probes import training_step

    result = training_step.run(
        tiny=True, batch_per_device=2, seq=32, steps=1, attention="flash"
    )
    assert result.ok
    assert result.details["attention"] == "flash"


@pytest.mark.slow  # full probe battery slice in interpret mode; tier-2 coverage
def test_probe_runs_on_cpu():
    from activemonitor_tpu.probes import flash

    result = flash.run(batch=1, seq=256, heads=2, head_dim=64, iters=2)
    assert result.ok
    names = {m.name for m in result.metrics}
    assert "flash-attention-max-error" in names
    assert "flash-attention-tflops" in names
    assert result.details["max_error"] < 1e-2
    # off-TPU: timing falls back to the XLA expression
    assert result.details["kernel"] == "xla"
    # the generalized kernel paths (GQA, packed segments, cross-length)
    # are part of every probe run, so a real-TPU battery validates
    # their Mosaic compilation — not just interpret mode
    gen = result.details["generalized_max_errors"]
    assert set(gen) == {"gqa", "packed", "cross"}
    assert all(isinstance(e, float) and e < 1e-2 for e in gen.values())


@pytest.mark.slow  # probe + contract plumbing; tier-2 coverage
def test_probe_contract_line_parses():
    import json

    from activemonitor_tpu.probes import flash

    result = flash.run(batch=1, seq=128, heads=2, head_dim=64, iters=2)
    parsed = json.loads(result.contract_line())
    assert {m["name"] for m in parsed["metrics"]} >= {
        "flash-attention-max-error",
        "flash-attention-tflops",
    }


@pytest.mark.slow  # probe re-runs per tolerance; tier-2 coverage
def test_probe_tolerance_drives_gradient_gate():
    from activemonitor_tpu.probes import flash

    # an absurdly tight tolerance must fail the combined verdict (the
    # gradient gate is 2.5x of it — ADVICE r2: --tolerance must bite)
    result = flash.run(batch=1, seq=128, heads=2, head_dim=64, iters=2, tolerance=1e-9)
    assert not result.ok
    assert result.details["grad_tolerance"] == 2.5e-9


@pytest.mark.slow  # minutes of interpret-mode compile; tier-2 coverage
def test_sweep_produces_block_tables():
    from activemonitor_tpu.probes import flash

    result = flash.sweep(
        batch=1, seq=128, heads=2, head_dim=64, iters=1, rounds=1,
        fwd_blocks=(64, 128), bwd_blocks=((64, 64), (128, 64)),
    )
    assert result.ok
    fwd = result.details["forward_table_tflops"]
    assert set(fwd) == {"64x64", "64x128", "128x64", "128x128"}
    assert result.details["best_forward"] in fwd
    train = result.details["train_table_tflops"]
    assert set(train) == {"64x64", "128x64"}
    names = {m.name for m in result.metrics}
    assert "flash-sweep-best-fwd-tflops" in names
