"""Expert parallelism — a mixture-of-experts FFN sharded over "ep".

Experts live sharded across the mesh (E/n per device); tokens are
sharded over the same axis (the usual ep≡dp setup). Each round:
``all_gather`` the token shards so every device sees all tokens, each
device runs only ITS experts on the tokens routed to them (top-1
learned router, softmax gate), and ``psum_scatter`` returns each
token's single expert output to the device that owns the token — the
all_gather/reduce-scatter pair is the collective skeleton of MoE
dispatch/combine.

This formulation computes each local expert over the full token set and
masks (dense dispatch) — exactly correct, static-shaped, and the right
fidelity for a *health probe* of expert-parallel collectives; a
production MoE would add capacity-based gather/scatter to skip the
masked compute.

The layout is DATA: regex partition rules (:func:`moe_partition_rules`
by default) resolve the shard_map specs, the token (scatter) dimension
is DERIVED from the resolved spec rather than hard-coded — a re-meshed
layout carrying a leading replicated batch/group dim scatters the right
axis instead of silently scattering dim 0 — and the token all-gather
routes through ``parallel/autotune.all_gather(schedule="auto")`` so the
tuned decision table runs in the dispatch hot path (untuned: the XLA
builtin, bitwise-identical to before).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from activemonitor_tpu.parallel.partition import (
    match_partition_rules,
    resolve_tiers,
    shard_map,
    spec_axes,
)
from jax.sharding import Mesh, PartitionSpec as P


def moe_partition_rules(axis="ep"):
    """Default rules for the expert-parallel pytree: the router
    replicates, expert weights split their leading (expert) dim over
    ``axis``, and the token tensor splits its token dim (position 0 in
    the default [T, D] layout) over the same axis. ``axis`` may be a
    tuple of mesh axes — the two-tier ("dcn", "ici") expert layout."""
    return (
        ("^router$", P(None, None)),
        (r"^w_(up|down)$", P(axis, None, None)),
        ("^x$", P(axis, None)),
    )


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, n_experts: int
) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts), jnp.float32) * scale,
        "w_up": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32) * scale,
        "w_down": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32)
        * (1.0 / jnp.sqrt(d_ff)),
    }


def moe_ffn_reference(params: Dict, x: jax.Array) -> jax.Array:
    """Single-device dense MoE (top-1): the correctness oracle.
    ``x`` is [..., T, D] — leading batch dims broadcast."""
    logits = x @ params["router"]  # [..., T, E]
    expert = jnp.argmax(logits, axis=-1)  # [..., T]
    gate = jax.nn.softmax(logits, axis=-1)
    gate = jnp.take_along_axis(gate, expert[..., None], axis=-1)  # [..., T, 1]
    h = jnp.einsum("...td,edf->...tef", x, params["w_up"])
    h = jax.nn.gelu(h)
    y = jnp.einsum("...tef,efd->...ted", h, params["w_down"])  # [..., T, E, D]
    chosen = jnp.take_along_axis(
        y, expert[..., None, None], axis=-2
    )[..., 0, :]
    return chosen * gate


def _entry_covers(entry, axes: tuple) -> bool:
    """True when one spec ENTRY shards its dim over every axis in
    ``axes`` (a bare name for a single axis, or a tuple entry carrying
    them all — the two-tier layout)."""
    named = (
        set(entry) if isinstance(entry, (tuple, list))
        else {entry} if entry is not None else set()
    )
    return set(axes) <= named


def _token_dim(spec: P, axes: tuple, ndim: int) -> int:
    """The dimension the resolved spec shards over ``axes`` — the
    gather/scatter dimension. Derived, not hard-coded: a rules dict
    that re-meshes the token layout moves the scatter with it."""
    entries = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    hits = [d for d, entry in enumerate(entries) if _entry_covers(entry, axes)]
    if len(hits) != 1:
        raise ValueError(
            f"resolved token spec {spec} must shard exactly one dim over "
            f"{axes if len(axes) > 1 else axes[0]!r} (found {len(hits)})"
        )
    return hits[0]


def moe_ffn_expert_parallel(
    params: Dict, x: jax.Array, mesh: Mesh, axis: str = "ep", rules=None
) -> jax.Array:
    """x: [..., T, D] with the token dim sharded over ``mesh[axis]``
    (which dim that is comes from the resolved rules — position 0 of
    the default 2D layout); experts sharded the same way. Leading dims
    beyond the sharded one are replicated batch dims. Returns an array
    shaped and sharded like x.

    On a two-tier ("dcn", "ici") mesh that carries the tiers instead
    of ``axis`` (``parallel/partition.resolve_tiers``), experts span
    both tiers dcn-major and the token gather dispatches the
    HIERARCHICAL composition (``autotune.all_gather`` over the axis
    pair: slice gather over ICI, cross-slice over DCN) — zero
    call-site changes."""
    axes, _tier_reason = resolve_tiers(mesh, axis)
    axis_token = axes[0] if len(axes) == 1 else axes
    tier_n = tuple(mesh.shape[a] for a in axes)
    n = math.prod(tier_n)
    n_experts = params["router"].shape[1]
    if n_experts % n:
        raise ValueError(f"{n_experts} experts do not split over {n} devices")
    resolved = match_partition_rules(
        rules if rules is not None else moe_partition_rules(axis_token),
        {**params, "x": x},
        mesh=mesh,
    )
    x_spec = resolved["x"]
    if not set(axes) <= spec_axes(x_spec):
        raise ValueError(
            f"resolved spec for the token tensor ({x_spec}) does not "
            f"shard over {axis_token!r}"
        )
    # the dispatch math below indexes w_up[e]/w_down[e] as THIS shard's
    # local experts and computes router logits identically everywhere —
    # rules that leave the expert weights unsharded (each shard would
    # reuse the first e_local GLOBAL experts) or shard the router must
    # fail here, not produce silently wrong outputs
    for name in ("w_up", "w_down"):
        w_spec = tuple(resolved[name])
        leading = w_spec[0] if w_spec else None
        if not _entry_covers(leading, axes):
            raise ValueError(
                f"resolved spec for {name!r} ({resolved[name]}) must "
                f"shard the leading (expert) dim over {axis_token!r}"
            )
    if spec_axes(resolved["router"]) & set(axes):
        raise ValueError(
            f"resolved spec for 'router' ({resolved['router']}) must "
            f"not shard over {axis_token!r} — every shard routes the "
            "full token set"
        )
    token_dim = _token_dim(x_spec, axes, x.ndim)
    if x.shape[token_dim] % n:
        raise ValueError(
            f"{x.shape[token_dim]} tokens do not shard over {n} devices"
        )
    e_local = n_experts // n

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            resolved["router"], resolved["w_up"], resolved["w_down"], x_spec,
        ),
        out_specs=x_spec,
        check_vma=False,
    )
    def run(router, w_up, w_down, x_shard):
        my_rank = jax.lax.axis_index(axis_token)
        # dispatch: every device sees all tokens — the tuned surface
        # picks the gather schedule per payload octave (dim-0 token
        # layouts; a derived token dim elsewhere rides the XLA builtin,
        # which gathers any dimension). Tuple axes dispatch the
        # hierarchical gather with per-tier winners.
        from activemonitor_tpu.parallel import autotune

        if token_dim == 0:
            tokens = autotune.all_gather(
                x_shard, axis_token, schedule="auto",
                n=tier_n if len(axes) > 1 else n,
            )
        else:
            tokens = jax.lax.all_gather(
                x_shard, axis_token, axis=token_dim, tiled=True
            )
        logits = tokens @ router
        expert = jnp.argmax(logits, axis=-1)
        gate = jax.nn.softmax(logits, axis=-1)
        gate = jnp.take_along_axis(gate, expert[..., None], axis=-1)
        out = jnp.zeros_like(tokens)
        for e in range(e_local):  # static loop over this device's experts
            eid = my_rank * e_local + e
            mask = (expert == eid)[..., None].astype(tokens.dtype)
            h = jax.nn.gelu(tokens @ w_up[e])
            out = out + mask * gate * (h @ w_down[e])
        # each token's output exists on exactly one device: the
        # scatter-sum both combines and re-shards back to the token
        # owners, along the dim the RESOLVED spec shards (derived above
        # — never a hard-coded 0); tuple axes scatter dcn-major, the
        # same linearization the gather and the P(axes) layout use
        return jax.lax.psum_scatter(
            out, axis_token, scatter_dimension=token_dim, tiled=True
        )

    return run(params["router"], params["w_up"], params["w_down"], x)
