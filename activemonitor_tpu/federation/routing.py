"""Capability-aware routing: which cluster should run this check?

Decision precedence, strictest claim first:

1. **Slice ownership** — a check targeting a named slice lands on the
   HEALTHY cluster that declares that slice. An unhealthy owner falls
   through (this is the reroute path: when a cluster goes dark its
   slice-pinned checks start matching by capability instead).
2. **Capability match** — among healthy clusters satisfying every
   declared requirement (generation equality, chips >= what the mesh
   shape needs, dcn tier), pick the TIGHTEST fit: the fewest chips,
   name as the tiebreak. Tightest-fit keeps the big pods free for the
   checks that actually need them — the same bin-packing instinct as
   the paper's goodput argument (idle v5p is badness you paid for).
3. **Default spread** — no requirements at all: a stable hash of the
   routing key over the healthy set, so repeat submissions of one
   check land on one cluster (cache/coalescing locality at the global
   door) without any cluster owning the unclaimed traffic.

No healthy cluster can satisfy the requirements -> a structured
``no_capable_cluster`` refusal (decision, not exception): the global
front door books it in the tenant's refused ledger and the caller gets
the machine-readable why.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional

from activemonitor_tpu.federation.registry import ClusterDescriptor, ClusterRegistry

NO_CAPABLE_CLUSTER = "no_capable_cluster"


def _chips_in(topology: str) -> int:
    """Chips implied by a "4x4" / "2x2x4"-style mesh shape (product of
    the axis sizes); 0 for empty/malformed shapes — a requirement that
    cannot be parsed must not silently match everything big."""
    text = str(topology).strip().lower()
    if not text:
        return 0
    total = 1
    for part in text.split("x"):
        try:
            dim = int(part.strip())
        except ValueError:
            return 0
        if dim <= 0:
            return 0
        total *= dim
    return total


@dataclass(frozen=True)
class Requirement:
    """What a check declares it needs from a cluster (all fields
    optional; an empty Requirement routes by default spread)."""

    generation: str = ""  # rated-table generation, e.g. "v5p"
    topology: str = ""  # mesh shape the check wants, e.g. "4x4"
    min_chips: int = 0
    min_dcn_gbps: float = 0.0
    slice_name: str = ""  # pin to the cluster owning this slice

    @classmethod
    def from_spec(cls, spec) -> "Requirement":
        """Build from an api.types.RequiresSpec (or any duck with the
        same fields); None -> the empty requirement."""
        if spec is None:
            return cls()
        return cls(
            generation=str(getattr(spec, "generation", "") or ""),
            topology=str(getattr(spec, "topology", "") or ""),
            min_chips=int(getattr(spec, "min_chips", 0) or 0),
            min_dcn_gbps=float(getattr(spec, "min_dcn_gbps", 0.0) or 0.0),
            slice_name=str(getattr(spec, "slice_name", "") or ""),
        )

    def chips_needed(self) -> int:
        """The chip floor: the declared mesh shape's footprint or the
        explicit min_chips, whichever is larger."""
        return max(self.min_chips, _chips_in(self.topology))

    def empty(self) -> bool:
        return not (
            self.generation
            or self.topology
            or self.min_chips
            or self.min_dcn_gbps
            or self.slice_name
        )

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "topology": self.topology,
            "min_chips": self.min_chips,
            "min_dcn_gbps": self.min_dcn_gbps,
            "slice_name": self.slice_name,
        }


@dataclass(frozen=True)
class RouteDecision:
    """The routing verdict: either a cluster plus how it was matched
    (``slice`` / ``capability`` / ``default``), or a structured refusal
    with a human-readable ``why``."""

    routed: bool
    cluster: str = ""
    matched: str = ""  # slice | capability | default
    reason: str = ""  # refusal code (NO_CAPABLE_CLUSTER) when not routed
    why: str = ""

    def to_dict(self) -> dict:
        return {
            "routed": self.routed,
            "cluster": self.cluster,
            "matched": self.matched,
            "reason": self.reason,
            "why": self.why,
        }


MATCHED_SLICE = "slice"
MATCHED_CAPABILITY = "capability"
MATCHED_DEFAULT = "default"


class CapabilityRouter:
    """Routes checks over the registry's healthy set. Stateless beyond
    the registry reference — every decision re-reads health, so a
    cluster going unhealthy between submissions reroutes automatically."""

    def __init__(self, registry: ClusterRegistry, *, metrics=None):
        self.registry = registry
        self.metrics = metrics

    def route(self, key: str, requirement: Optional[Requirement] = None) -> RouteDecision:
        """Decide where ``key`` (the routing identity — check name or
        coalescing key) should run given its declared requirement."""
        req = requirement or Requirement()
        healthy = self.registry.healthy()
        decision = self._decide(key, req, healthy)
        if self.metrics is not None:
            self.metrics.record_federation_route(
                decision.cluster or "(none)",
                decision.matched or decision.reason or "(none)",
            )
        return decision

    def _decide(
        self, key: str, req: Requirement, healthy: List[ClusterDescriptor]
    ) -> RouteDecision:
        if not healthy:
            return RouteDecision(
                routed=False,
                reason=NO_CAPABLE_CLUSTER,
                why="no healthy clusters in the federation",
            )

        # 1. slice ownership, healthy owners only (unhealthy owner
        # falls through to capability/default — the reroute path)
        if req.slice_name:
            for descriptor in healthy:
                if req.slice_name in descriptor.slices:
                    return RouteDecision(
                        routed=True,
                        cluster=descriptor.name,
                        matched=MATCHED_SLICE,
                    )

        # 2. capability filter, tightest fit wins
        if not req.empty():
            needed = req.chips_needed()
            candidates = [
                d
                for d in healthy
                if (not req.generation or d.generation == req.generation)
                and (needed <= 0 or d.chips >= needed)
                and (req.min_dcn_gbps <= 0 or d.dcn_gbps >= req.min_dcn_gbps)
            ]
            if candidates:
                best = min(candidates, key=lambda d: (d.chips, d.name))
                return RouteDecision(
                    routed=True, cluster=best.name, matched=MATCHED_CAPABILITY
                )
            return RouteDecision(
                routed=False,
                reason=NO_CAPABLE_CLUSTER,
                why=(
                    "no healthy cluster matches requirement "
                    f"{req.to_dict()} (healthy: "
                    f"{[d.name for d in healthy]})"
                ),
            )

        # 3. default spread: stable hash over the healthy set so one
        # key keeps landing on one cluster (global-door coalescing
        # locality) while unclaimed traffic still spreads
        digest = hashlib.sha1(str(key).encode("utf-8", "replace")).digest()
        index = int.from_bytes(digest[:8], "big") % len(healthy)
        return RouteDecision(
            routed=True, cluster=healthy[index].name, matched=MATCHED_DEFAULT
        )
