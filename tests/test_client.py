"""In-memory HealthCheck client tests: CAS semantics, conflict retry, watch."""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    ConflictError,
    InMemoryHealthCheckClient,
    NotFoundError,
    retry_on_conflict,
)


def make_hc(name="hc-a"):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {"repeatAfterSec": 60, "level": "cluster"},
        }
    )


@pytest.mark.asyncio
async def test_apply_get_roundtrip():
    c = InMemoryHealthCheckClient()
    created = await c.apply(make_hc())
    assert created.metadata.uid
    assert created.metadata.resource_version
    got = await c.get("health", "hc-a")
    assert got == created


@pytest.mark.asyncio
async def test_get_missing_returns_none():
    c = InMemoryHealthCheckClient()
    assert await c.get("health", "nope") is None


@pytest.mark.asyncio
async def test_generate_name_assigns_name():
    c = InMemoryHealthCheckClient()
    hc = make_hc()
    hc.metadata.name = ""
    hc.metadata.generate_name = "gen-"
    created = await c.apply(hc)
    assert created.metadata.name.startswith("gen-")
    assert len(created.metadata.name) > len("gen-")


@pytest.mark.asyncio
async def test_update_status_cas_conflict():
    c = InMemoryHealthCheckClient()
    created = await c.apply(make_hc())
    stale = created.deepcopy()
    fresh = await c.get("health", "hc-a")
    fresh.status.success_count = 1
    await c.update_status(fresh)
    stale.status.success_count = 99
    with pytest.raises(ConflictError):
        await c.update_status(stale)
    # the fresh write won
    now = await c.get("health", "hc-a")
    assert now.status.success_count == 1


@pytest.mark.asyncio
async def test_update_status_deleted_raises_not_found():
    c = InMemoryHealthCheckClient()
    created = await c.apply(make_hc())
    await c.delete("health", "hc-a")
    with pytest.raises(NotFoundError):
        await c.update_status(created)


@pytest.mark.asyncio
async def test_retry_on_conflict_retries_then_succeeds():
    c = InMemoryHealthCheckClient()
    await c.apply(make_hc())
    c.force_conflicts(2)
    attempts = 0

    async def attempt():
        nonlocal attempts
        attempts += 1
        fresh = await c.get("health", "hc-a")
        fresh.status.success_count = 5
        return await c.update_status(fresh)

    await retry_on_conflict(attempt)
    assert attempts == 3
    assert (await c.get("health", "hc-a")).status.success_count == 5


@pytest.mark.asyncio
async def test_retry_on_conflict_gives_up():
    async def always_conflict():
        raise ConflictError("nope")

    with pytest.raises(ConflictError):
        await retry_on_conflict(always_conflict, attempts=3, base_delay=0.001)


@pytest.mark.asyncio
async def test_watch_sees_lifecycle_events():
    c = InMemoryHealthCheckClient()
    events = []

    async def watcher():
        async for ev in c.watch():
            events.append((ev.type, ev.name))
            if len(events) == 3:
                return

    task = asyncio.create_task(watcher())
    await asyncio.sleep(0)
    await c.apply(make_hc())
    fresh = await c.get("health", "hc-a")
    fresh.status.success_count = 1
    await c.update_status(fresh)
    await c.delete("health", "hc-a")
    await asyncio.wait_for(task, 2)
    assert events == [("ADDED", "hc-a"), ("MODIFIED", "hc-a"), ("DELETED", "hc-a")]


@pytest.mark.asyncio
async def test_spec_update_preserves_status():
    c = InMemoryHealthCheckClient()
    await c.apply(make_hc())
    fresh = await c.get("health", "hc-a")
    fresh.status.success_count = 7
    await c.update_status(fresh)
    updated_spec = make_hc()
    updated_spec.spec.repeat_after_sec = 120
    await c.apply(updated_spec)
    got = await c.get("health", "hc-a")
    assert got.spec.repeat_after_sec == 120
    assert got.status.success_count == 7  # apply does not clobber status
