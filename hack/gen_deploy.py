#!/usr/bin/env python
"""Generate deploy/deploy-active-monitor-tpu.yaml from the config/
kustomize tree — config/ is the single source of truth; the one-shot
deploy file is build output, drift-checked in CI like the generated CRD
(reference split: config/ kubebuilder tree vs deploy/ one-shots).

Usage: python hack/gen_deploy.py [--check]
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

ROOT = Path(__file__).resolve().parent.parent
OUT = ROOT / "deploy" / "deploy-active-monitor-tpu.yaml"

HEADER = """\
# One-shot install of the controller into namespace "health"
# (reference equivalent: deploy/deploy-active-monitor.yaml).
# Apply config/crd/activemonitor.keikoproj.io_healthchecks.yaml first.
# GENERATED from config/{manager,rbac} by hack/gen_deploy.py — edit
# those files, then `make deploy-manifest`.
"""

# install order: namespace first, then identity, grants, workload
SOURCES = [
    "config/manager/namespace.yaml",
    "config/rbac/service_account.yaml",
    "config/rbac/role.yaml",
    "config/rbac/role_binding.yaml",
    "config/manager/manager.yaml",
]


def render() -> str:
    chunks = []
    for rel in SOURCES:
        text = (ROOT / rel).read_text()
        # drop each source file's own header comment (lines before the
        # first key) — the deploy file carries its own header; object-
        # internal comments are preserved verbatim
        lines = text.split("\n")
        start = 0
        while start < len(lines) and (
            lines[start].startswith("#") or not lines[start].strip()
        ):
            start += 1
        chunk = "\n".join(lines[start:]).strip("\n")
        assert yaml.safe_load(chunk), f"{rel} renders no object"
        chunks.append(chunk)
    return HEADER + "\n---\n".join(chunks) + "\n"


def main() -> int:
    content = render()
    if "--check" in sys.argv:
        current = OUT.read_text() if OUT.exists() else ""
        if current != content:
            print(
                f"{OUT.relative_to(ROOT)} is stale; run `make deploy-manifest`",
                file=sys.stderr,
            )
            return 1
        return 0
    OUT.write_text(content)
    print(f"wrote {OUT.relative_to(ROOT)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
