"""Scheduling engine: cron parsing, inverse-exponential backoff, timer
wheel, and the shared seeded Poisson arrival process (the one open-loop
traffic contract the serving probe and the front door both ride)."""

from activemonitor_tpu.scheduler.arrivals import PoissonArrivals
from activemonitor_tpu.scheduler.backoff import (
    BackoffParams,
    InverseExpBackoff,
    compute_backoff_params,
)
from activemonitor_tpu.scheduler.cron import (
    CronParseError,
    CronSchedule,
    EverySchedule,
    parse_cron,
    seconds_until_next,
)
from activemonitor_tpu.scheduler.timers import TimerWheel

__all__ = [
    "BackoffParams",
    "PoissonArrivals",
    "CronParseError",
    "CronSchedule",
    "EverySchedule",
    "InverseExpBackoff",
    "TimerWheel",
    "compute_backoff_params",
    "parse_cron",
    "seconds_until_next",
]
