"""Async Kubernetes REST client.

The framework's replacement for client-go's typed/dynamic clients
(reference: healthcheck_controller.go:134,:155,:617). One class, four
verbs, JSON in/out, plus a streaming ``watch``. Everything the
controller needs — CRs with a status subresource, core v1 objects,
RBAC, Leases, Events — is plain REST against well-known paths, so no
generated client code is required.
"""

from __future__ import annotations

import datetime
import json
import logging
from typing import Any, AsyncIterator, Dict, Optional

from activemonitor_tpu.kube.config import KubeConfig

log = logging.getLogger("activemonitor.kube")

# JSON merge patch (RFC 7386) — what the controller uses for status
# writes; the API server also accepts it for ordinary updates
MERGE_PATCH = "application/merge-patch+json"

# verbs the circuit breaker gates (resilience/breaker.py): writes are
# what a sick apiserver must be protected from; reads stay open so
# recovery remains observable and watch streams keep reconnecting
MUTATING_METHODS = frozenset({"POST", "PUT", "PATCH", "DELETE"})


def _breaker_exempt(path: str) -> bool:
    """Leadership leases are the one write that must always be
    ATTEMPTED: rejecting a renewal while the breaker is open would make
    the controller abdicate leadership over an outage its lease timing
    already handles — self-inflicted failover on top of degradation.
    Matched on the coordination.k8s.io GROUP segment, not a bare
    '/leases/' substring — a CR that happens to be named 'leases'
    (…/healthchecks/leases/status) must not slip through the gate."""
    return path.startswith("/apis/coordination.k8s.io/")


def _json_default(obj):
    """Timestamps show up in status payloads as datetime objects; the
    wire format is RFC3339 strings."""
    if isinstance(obj, datetime.datetime):
        return obj.isoformat()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


class ApiError(Exception):
    def __init__(self, status: int, reason: str = "", body: Any = None):
        super().__init__(f"API error {status}: {reason}")
        self.status = status
        self.reason = reason
        self.body = body

    @property
    def not_found(self) -> bool:
        return self.status == 404

    @property
    def conflict(self) -> bool:
        return self.status == 409


def core_path(plural: str, namespace: str = "", name: str = "") -> str:
    """Path for a core/v1 resource (pods, events, serviceaccounts...)."""
    parts = ["/api/v1"]
    if namespace:
        parts.append(f"namespaces/{namespace}")
    parts.append(plural)
    if name:
        parts.append(name)
    return "/".join(parts)


def api_path(
    group: str,
    version: str,
    plural: str,
    namespace: str = "",
    name: str = "",
    subresource: str = "",
) -> str:
    """Path for a grouped resource (CRs, RBAC, Leases...). Empty
    ``namespace`` means cluster-scoped (ClusterRole) or an
    all-namespaces list/watch (CR collections)."""
    parts = [f"/apis/{group}/{version}"]
    if namespace:
        parts.append(f"namespaces/{namespace}")
    parts.append(plural)
    if name:
        parts.append(name)
    if subresource:
        parts.append(subresource)
    return "/".join(parts)


class KubeApi:
    """aiohttp-backed REST session against one API server."""

    def __init__(self, config: KubeConfig):
        self._config = config
        self._session = None  # created lazily inside the running loop
        self._auth_lock = None  # serializes exec-plugin refreshes
        self._closed = False
        # optional shared circuit breaker (resilience/): gates mutating
        # verbs and records every request outcome. None (the default)
        # keeps this client's behavior exactly as before.
        self._breaker = None

    def set_breaker(self, breaker) -> None:
        """Attach the controller's shared circuit breaker to this
        transport. Mutating verbs are rejected fast with
        BreakerOpenError while it is open (leases exempt); every request
        outcome — reads included — feeds its failure/success stream."""
        self._breaker = breaker

    @classmethod
    def from_default_config(cls, kubeconfig: str | None = None) -> "KubeApi":
        """Credential-discovering constructor (in-cluster, then
        kubeconfig) — the one bootstrap path every cluster-mode
        component shares."""  # pragma: no cover - needs a cluster
        from activemonitor_tpu.kube.config import load_kube_config

        return cls(load_kube_config(kubeconfig))

    # -- plumbing -------------------------------------------------------
    async def _headers(self, content_type: str = "application/json") -> Dict[str, str]:
        import asyncio

        headers = {"Accept": "application/json", "Content-Type": content_type}
        if self._config.exec_spec is not None:
            # fast path when the config says its cached token is still
            # fresh — no lock/thread hop (lease renewals have a hard
            # deadline on this path)
            token = self._config.cached_token()
            if token is None:
                # credential plugins shell out (up to tens of seconds
                # cold) — off the event loop, one refresh at a time
                if self._auth_lock is None:
                    self._auth_lock = asyncio.Lock()
                async with self._auth_lock:
                    token = await asyncio.to_thread(self._config.bearer_token)
        else:
            token = self._config.bearer_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    async def _ensure_session(self):
        import aiohttp

        if self._closed:
            # close() is final: silently rebuilding a session here would
            # leak its connector and mask use-after-close bugs
            raise RuntimeError("KubeApi is closed")
        if self._session is None or self._session.closed:
            connector = aiohttp.TCPConnector(ssl=self._config.ssl_context())
            self._session = aiohttp.ClientSession(
                connector=connector,
                # watch streams are read line-by-line; the default 64 KiB
                # buffer would abort on any object bigger than that
                # (etcd allows ~1.5 MiB)
                read_bufsize=2**22,
            )
        return self._session

    def _url(self, path: str) -> str:
        # plain concatenation, NOT RFC 3986 join: server URLs with a path
        # component (Rancher/proxied clusters, https://host/k8s/clusters/x)
        # must keep their prefix in front of /api|/apis paths
        return self._config.server.rstrip("/") + path

    async def close(self) -> None:
        self._closed = True
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None

    async def request(
        self,
        method: str,
        path: str,
        *,
        body: Optional[dict] = None,
        params: Optional[dict] = None,
        content_type: str = "application/json",
        timeout: float = 30.0,
    ) -> dict:
        import aiohttp

        breaker = self._breaker
        if (
            breaker is not None
            and method.upper() in MUTATING_METHODS
            and not _breaker_exempt(path)
            and not breaker.allow()
        ):
            from activemonitor_tpu.resilience.breaker import BreakerOpenError

            raise BreakerOpenError(breaker.name, breaker.retry_after())
        session = await self._ensure_session()
        data = None if body is None else json.dumps(body, default=_json_default).encode()
        try:
            async with session.request(
                method,
                self._url(path),
                data=data,
                params=params,
                headers=await self._headers(content_type),
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                text = await resp.text()
                payload: Any = None
                if text:
                    try:
                        payload = json.loads(text)
                    except json.JSONDecodeError:
                        payload = text
                if resp.status >= 400:
                    reason = ""
                    if isinstance(payload, dict):
                        reason = payload.get("message") or payload.get("reason") or ""
                    raise ApiError(resp.status, reason or text[:200], payload)
        except Exception as e:
            # every outcome feeds the breaker: transient statuses and
            # connection-level failures count toward tripping it, a
            # deterministic 4xx proves liveness and resets the streak
            # (classification lives in resilience/breaker.py)
            if breaker is not None:
                breaker.observe(e)
            raise
        if breaker is not None:
            breaker.observe(None)
        return payload if isinstance(payload, dict) else {}

    # -- verbs ----------------------------------------------------------
    async def get(self, path: str, params: Optional[dict] = None) -> dict:
        return await self.request("GET", path, params=params)

    async def create(self, path: str, body: dict) -> dict:
        return await self.request("POST", path, body=body)

    async def replace(self, path: str, body: dict) -> dict:
        return await self.request("PUT", path, body=body)

    async def merge_patch(self, path: str, body: dict) -> dict:
        return await self.request("PATCH", path, body=body, content_type=MERGE_PATCH)

    async def delete(self, path: str) -> dict:
        return await self.request("DELETE", path)

    # -- watch ----------------------------------------------------------
    async def watch(
        self,
        path: str,
        *,
        resource_version: str = "",
        timeout_seconds: int = 300,
        label_selector: str = "",
    ) -> AsyncIterator[dict]:
        """One watch connection: yields ``{"type": ..., "object": ...}``
        events until the server closes the stream (or ``timeout_seconds``
        elapses server-side). Reconnect/re-list policy belongs to the
        caller — a 410 Gone surfaces as ApiError(410)."""
        import aiohttp

        session = await self._ensure_session()
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
            # bookmarks keep the resume resourceVersion fresh in quiet
            # clusters, avoiding a 410 full-resync on every reconnect
            "allowWatchBookmarks": "true",
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        if label_selector:
            params["labelSelector"] = label_selector
        async with session.get(
            self._url(path),
            params=params,
            headers=await self._headers(),
            # long-lived by design, but a half-open TCP connection must
            # not hang the watch forever: the server closes the stream
            # by timeout_seconds, so a read gap beyond that means the
            # connection is dead
            timeout=aiohttp.ClientTimeout(
                total=None, sock_connect=30, sock_read=timeout_seconds + 30
            ),
        ) as resp:
            if resp.status >= 400:
                text = await resp.text()
                raise ApiError(resp.status, text[:200])
            async for line in resp.content:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError:
                    log.warning("undecodable watch line: %.120r", line)
                    continue
                if event.get("type") == "ERROR":
                    # the event's object is a full Status — keep it on
                    # the error so callers can branch on reason
                    # (Expired vs InternalError), like typed clients do
                    obj = event.get("object", {}) or {}
                    raise ApiError(
                        int(obj.get("code", 500)),
                        obj.get("message", "watch error"),
                        obj,
                    )
                yield event
