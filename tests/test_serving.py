"""Continuous-batching serving runtime (ISSUE 14).

Covers the paged KV cache's block lifecycle edges (free-list reuse
after retirement, structured out-of-blocks refusal, fragmentation
exactness), the partition-rule layout surface (wrong layouts raise,
scalars never partition), the paged-vs-static decode equivalence, the
seeded open-loop generator + scheduler-trace determinism, and the
closed-loop acceptance soak: an open-loop Poisson soak on the scripted
virtual clock shows continuous batching beating sequential
static-batch decode on tokens/s, with TTFT/inter-token tails in the
stdout contract, exact token conservation, logits agreement with the
static path, and the `serving` matrix cell producing a
baseline-tracked, roofline-stamped verdict in the durable sidecar.
"""

import json

import pytest

from activemonitor_tpu.ops.kv_cache import KVBlockManager, kv_bytes_per_token
from activemonitor_tpu.scheduler.serving import (
    ContinuousBatchingScheduler,
    Request,
    open_loop_requests,
)


# ---------------------------------------------------------------------
# KV block lifecycle edges
# ---------------------------------------------------------------------


def test_block_manager_allocate_append_free_roundtrip():
    mgr = KVBlockManager(n_blocks=8, block_size=4)
    blocks = mgr.allocate(1, 10)  # 10 tokens -> 3 blocks
    assert blocks == [0, 1, 2]
    assert mgr.used_blocks == 3 and mgr.free_blocks == 5
    assert mgr.table(1) == [0, 1, 2]
    assert mgr.append(1, 10)
    assert mgr.length(1) == 10 and mgr.banked_tokens == 10
    # appending past the reserved capacity is a structured refusal
    assert mgr.append(1, 3) is False
    assert mgr.length(1) == 10  # refused append must not half-apply
    assert mgr.free(1) == 3
    assert mgr.free_blocks == 8 and mgr.banked_tokens == 0
    # freeing an unknown id is 0, not a raise
    assert mgr.free(99) == 0


def test_block_manager_free_list_reuse_after_retirement():
    """A retired sequence's blocks are the very next admission's grant
    (LIFO reuse) — recycling, not pool growth."""
    mgr = KVBlockManager(n_blocks=4, block_size=2)
    first = mgr.allocate(1, 4)  # blocks [0, 1]
    second = mgr.allocate(2, 4)  # blocks [2, 3]
    assert first == [0, 1] and second == [2, 3]
    mgr.free(1)
    reused = mgr.allocate(3, 4)
    assert set(reused) == {0, 1}  # exactly the retired blocks, reused
    assert mgr.free_blocks == 0


def test_block_manager_out_of_blocks_is_structured_refusal():
    mgr = KVBlockManager(n_blocks=2, block_size=4)
    assert mgr.allocate(1, 8) == [0, 1]
    # deficit: None, never a raise — and no partial grant
    assert mgr.can_allocate(1) is False
    assert mgr.allocate(2, 1) is None
    assert mgr.used_blocks == 2 and mgr.free_blocks == 0
    # a double-allocate for a LIVE id is a caller bug and does raise
    with pytest.raises(ValueError):
        mgr.allocate(1, 4)


def test_block_manager_fragmentation_ratio_is_exact():
    mgr = KVBlockManager(n_blocks=8, block_size=4)
    assert mgr.fragmentation_ratio() == 0.0  # nothing reserved, no waste
    mgr.allocate(1, 6)  # 2 blocks = 8 slots reserved
    assert mgr.fragmentation_ratio() == 1.0  # reserved, nothing banked
    mgr.append(1, 5)
    assert mgr.fragmentation_ratio() == (8 - 5) / 8
    mgr.allocate(2, 4)  # +1 block = 12 slots reserved total
    mgr.append(2, 4)
    assert mgr.fragmentation_ratio() == (12 - 9) / 12
    mgr.free(1)
    assert mgr.fragmentation_ratio() == 0.0  # seq 2 fills its block exactly
    assert mgr.stats()["fragmentation_ratio"] == 0.0


# ---------------------------------------------------------------------
# partition-rule layout surface
# ---------------------------------------------------------------------


def test_kv_partition_rules_shard_heads_and_reject_bad_mesh():
    import jax
    from jax.sharding import PartitionSpec as P

    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.ops.kv_cache import paged_kv_specs
    from activemonitor_tpu.parallel.mesh import make_mesh

    cfg = tiny_config()
    mesh = make_mesh(("model",), (2,), devices=jax.devices()[:2])
    specs = paged_kv_specs(cfg, n_blocks=4, block_size=8, mesh=mesh)
    assert specs["k"] == P(None, None, "model", None, None)
    assert specs["v"] == P(None, None, "model", None, None)
    # a layout naming an axis the mesh lacks raises UP FRONT with the
    # rule in the message, never a tracer crash inside the serving loop
    data_mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="model"):
        paged_kv_specs(cfg, n_blocks=4, block_size=8, mesh=data_mesh)


def test_kv_partition_rules_never_partition_scalars():
    from jax.sharding import PartitionSpec as P

    from activemonitor_tpu.ops.kv_cache import kv_partition_rules
    from activemonitor_tpu.parallel.partition import match_partition_rules

    import numpy as np

    # a scalar leaf whose NAME matches the k/v rule still resolves P()
    specs = match_partition_rules(
        kv_partition_rules(), {"k": np.float32(1.0), "v": np.zeros(())}
    )
    assert specs["k"] == P() and specs["v"] == P()


# ---------------------------------------------------------------------
# paged decode == static decode (the runtime's numerics contract)
# ---------------------------------------------------------------------


def test_paged_decode_step_matches_static_decode_step():
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import (
        decode_step,
        init_kv_cache,
        init_params,
        prefill,
        tiny_config,
    )
    from activemonitor_tpu.ops.kv_cache import (
        bank_prompt,
        init_paged_kv,
        paged_decode_step,
    )

    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    prompt_len, steps, block_size = 6, 4, 4
    prompt = jax.random.randint(
        jax.random.key(1), (1, prompt_len), 0, cfg.vocab_size
    )
    # static path: contiguous cache, scalar positions
    cache = init_kv_cache(cfg, 1, prompt_len + steps + 1)
    static_logits, cache = prefill(params, cache, prompt, cfg)
    # paged path: bank the same prefill into non-contiguous blocks via
    # a scrambled-ish table (allocate a decoy first so ids aren't 0..n)
    n_blocks = 8
    storage = init_paged_kv(cfg, n_blocks + 1, block_size)
    blocks = [3, 4, 5]  # any distinct ids: the table IS the layout
    # cache is [L, B, Hkv, S, Dh]: take seq 0 heads-major [L, Hkv, S, Dh]
    pk = cache["k"][:, 0, :, :prompt_len]
    pv = cache["v"][:, 0, :, :prompt_len]
    storage = bank_prompt(storage, pk, pv, jnp.asarray(blocks, jnp.int32))
    tables = jnp.asarray([blocks + [n_blocks]], jnp.int32)  # pad w/ trash
    token_s = jnp.argmax(static_logits, axis=-1)
    token_p = token_s
    for i in range(steps):
        pos = prompt_len + i
        static_logits, cache = decode_step(
            params, cache, token_s, jnp.asarray(pos), cfg
        )
        paged_logits, storage = paged_decode_step(
            params,
            storage,
            token_p,
            jnp.asarray([pos], jnp.int32),
            tables,
            cfg,
        )
        scale = max(float(jnp.max(jnp.abs(static_logits))), 1e-6)
        rel = float(jnp.max(jnp.abs(paged_logits - static_logits))) / scale
        assert rel < 2e-2, f"step {i}: paged diverged {rel}"
        # teacher-force the static tokens into both paths
        token_s = jnp.argmax(static_logits, axis=-1)
        token_p = token_s


# ---------------------------------------------------------------------
# open-loop generator + scheduler determinism
# ---------------------------------------------------------------------


def test_open_loop_generator_is_seeded_and_mixed():
    a = open_loop_requests(16, 4.0, seed=5)
    b = open_loop_requests(16, 4.0, seed=5)
    assert a == b  # same seed, byte-identical schedule
    c = open_loop_requests(16, 4.0, seed=6)
    assert a != c
    assert all(x.arrival < y.arrival for x, y in zip(a, a[1:]))
    assert len({r.prompt_len for r in a}) > 1  # mixed lengths
    assert {r.tenant for r in a} == {"tenant-a", "tenant-b"}
    with pytest.raises(ValueError):
        open_loop_requests(0, 4.0, seed=1)


def _scripted_schedule(requests, max_batch, n_blocks, block_size=4):
    """Drive the scheduler purely (no model): every 'decode step' emits
    token 7 for each in-flight sequence at virtual 1s per step."""
    mgr = KVBlockManager(n_blocks, block_size)
    sched = ContinuousBatchingScheduler(requests, mgr, max_batch)
    now = 0.0
    while not sched.done:
        nxt = sched.next_arrival()
        if not sched.active and nxt is not None and nxt > now:
            now = nxt
        for seq in sched.admit(now):
            sched.record_first_token(seq, 7, now)
        batch = sched.decode_batch()
        now += 1.0
        if batch:
            sched.record_decode_step({s.slot: 7 for s in batch}, now)
    return sched


def test_scheduler_trace_is_deterministic_per_seed():
    reqs_a = open_loop_requests(12, 3.0, seed=11, output_choices=(2, 3))
    reqs_b = open_loop_requests(12, 3.0, seed=11, output_choices=(2, 3))
    trace_a = _scripted_schedule(reqs_a, max_batch=3, n_blocks=12).trace
    trace_b = _scripted_schedule(reqs_b, max_batch=3, n_blocks=12).trace
    assert trace_a == trace_b  # same seed => identical admission order
    admits = [rid for ev, rid, _t in trace_a if ev == "admit"]
    assert admits == sorted(admits)  # FIFO admission order held


def test_scheduler_refusals_are_structured_and_conservation_exact():
    # 1 batch slot, 2 blocks of 4: the second arrival must defer, the
    # ledger must still balance to the token at every point
    reqs = [
        Request(0, "tenant-a", 0.0, prompt_len=4, output_tokens=3),
        Request(1, "tenant-b", 0.0, prompt_len=4, output_tokens=2),
    ]
    sched = _scripted_schedule(reqs, max_batch=1, n_blocks=2, block_size=4)
    assert sched.refusals["batch"] >= 1 or sched.refusals["blocks"] >= 1
    cons = sched.conservation()
    assert cons["ok"] is True
    assert cons["admitted"] == 2 and cons["completed"] == 2
    assert cons["tokens_emitted"] == 3 + 2
    assert cons["tenants"]["tenant-a"]["tokens"] == 3
    assert cons["tenants"]["tenant-b"]["tokens"] == 2


# ---------------------------------------------------------------------
# the closed-loop acceptance soak (scripted virtual clock)
# ---------------------------------------------------------------------


def test_acceptance_continuous_batching_beats_sequential_static():
    """ISSUE-14 acceptance: open-loop Poisson soak on the injectable
    clock — continuous batching must beat sequential static-batch
    decode on tokens/s under the memory-bound cost model (a decode
    step streams the weights regardless of batch width), with logits
    agreeing with the static path, conservation exact, and the tails
    exported through the stdout contract."""
    import jax

    from activemonitor_tpu.models.probe_model import init_params, tiny_config
    from activemonitor_tpu.probes import serving as serving_probe

    cfg = tiny_config()
    requests = open_loop_requests(
        8, 2.0, seed=7, prompt_len_choices=(4, 6), output_choices=(3, 4)
    )
    costs = serving_probe.StepCosts(
        prefill=lambda plen: 0.01 * plen, decode=lambda _n: 1.0
    )
    soak = serving_probe.run_soak(
        cfg, requests, max_batch=4, costs=costs, collect=3, seed=0
    )
    cons = soak.scheduler.conservation()
    assert cons["ok"] is True
    assert cons["completed"] == len(requests)
    total_tokens = sum(r.output_tokens for r in requests)
    assert cons["tokens_emitted"] == total_tokens  # exact, to the token
    # continuous batching: many sequences share each 1s decode step
    continuous_tps = total_tokens / soak.busy_seconds
    sequential_tps = total_tokens / serving_probe.sequential_static_seconds(
        requests, costs
    )
    assert continuous_tps > sequential_tps, (
        f"continuous {continuous_tps:.3f} <= sequential {sequential_tps:.3f}"
    )
    # logits agreement with the per-sequence static path
    params = init_params(jax.random.key(0), cfg)
    rel = serving_probe._check_against_static(cfg, params, soak)
    assert rel <= 0.05
    assert len(soak.logit_trace) == 3  # the checked sequences really ran


def test_serving_probe_contract_line_and_gates():
    """The probe end to end on a deterministic fake timer: every
    pinned serving-* metric rides the stdout contract, the verdict
    gates hold, and the roofline capture lands as a structured skip on
    CPU (cost_source model territory — never a TPU-bar fraction)."""
    from activemonitor_tpu.probes import serving as serving_probe

    ticks = {"t": 0.0}

    def fake_timer() -> float:
        ticks["t"] += 0.25
        return ticks["t"]

    result = serving_probe.run(
        tiny=True, n_requests=6, max_batch=3, timer=fake_timer
    )
    assert result.ok, result.summary
    doc = json.loads(result.contract_line())
    names = {m["name"]: m["value"] for m in doc["metrics"]}
    for metric in (
        "serving-tokens-per-s",
        "serving-ttft-p50-ms",
        "serving-ttft-p99-ms",
        "serving-intertoken-p99-ms",
        "serving-batch-occupancy",
        "serving-kv-frag-ratio",
        "serving-consistency",
        "serving-kv-bytes-per-token",
    ):
        assert metric in names, f"{metric} missing from the contract"
    assert names["serving-consistency"] == 1.0
    assert names["serving-ttft-p99-ms"] >= names["serving-ttft-p50-ms"] > 0
    assert 0 < names["serving-batch-occupancy"] <= 1.0
    assert 0 <= names["serving-kv-frag-ratio"] < 1.0
    assert result.details["conservation"]["ok"] is True
    # phase timings rode the contract (the attribution layer's food)
    assert "soak" in doc["timings"]
    # structured roofline skip on CPU — never a silent omission
    roofline_detail = result.details["roofline"]["serving"]
    assert "skipped" in roofline_detail or "bound" in roofline_detail


def test_serving_and_decode_share_one_kv_bytes_figure():
    """The ceiling cross-check satellite: both probes derive their
    memory-bound ceiling input from ops/kv_cache.kv_bytes_per_token,
    and the static decode probe now exports it."""
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import ProbeModelConfig, tiny_config

    cfg = tiny_config()
    expected = (
        2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    assert kv_bytes_per_token(cfg) == expected
    # GQA halves the figure with half the kv heads
    gqa = ProbeModelConfig(n_kv_heads=ProbeModelConfig().n_heads // 2)
    assert kv_bytes_per_token(gqa) == kv_bytes_per_token(ProbeModelConfig()) / 2


def test_decode_probe_records_clamp_and_kv_bytes():
    """The silent-truncation satellite: a decode_tokens request the
    model's max_seq_len cannot hold is recorded in the details with
    the effective budget — and the kv-bytes metric rides the
    contract."""
    from activemonitor_tpu.probes import decode

    # tiny max_seq_len=64: prompt 8 + 200 + 1 clamps to 64
    result = decode.run(
        tiny=True, batch=2, prompt_len=8, decode_tokens=200, iters=2
    )
    assert result.details["decode_tokens_requested"] == 200
    assert result.details["decode_tokens_effective"] == 64 - 8 - 1
    assert result.details["decode_tokens_clamped"] is True
    by_name = {m.name: m.value for m in result.metrics}
    assert by_name["decode-kv-bytes-per-token"] > 0
    # an unclamped run says so
    result = decode.run(
        tiny=True, batch=2, prompt_len=4, decode_tokens=4, iters=2
    )
    assert result.details["decode_tokens_clamped"] is False
    assert result.details["decode_tokens_effective"] == 4


# ---------------------------------------------------------------------
# the serving matrix cell: baseline-tracked, roofline-stamped verdict
# ---------------------------------------------------------------------


def test_serving_matrix_cell_lands_in_the_durable_sidecar(tmp_path):
    """The acceptance's observatory leg: a serving cell observed over
    rounds gets a per-cell baseline and a roofline stamp persisted in
    BENCH_BASELINES.json, and a regressing round produces a confirmed
    degraded verdict naming the moved ceiling."""
    from activemonitor_tpu.analysis import matrix as matrix_mod
    from activemonitor_tpu.probes.rated import RatedSpec
    from activemonitor_tpu.utils.clock import FakeClock

    rated = RatedSpec(
        "v5e", bf16_tflops=197.0, hbm_gbps=819.0,
        ici_unidir_gbps=45.0, ici_links=4,
    )
    [cell], skipped = matrix_mod.expand(
        {
            "ops": ["serving"],
            "meshes": [{"model": 2}],
            "dtypes": ["f32"],
            "batch_ceilings": [2],
        },
        n_devices=8,
    )
    assert skipped == []
    assert cell.cell_id == "serving/model2/f32/b2"

    def scripted(seconds):
        return matrix_mod.CellResult(
            cell, matrix_mod.STATUS_OK, value=seconds, seconds=seconds,
            flops=1e9, bytes_accessed=1e9,
        )

    path = str(tmp_path / "BENCH_BASELINES.json")
    observatory = matrix_mod.MatrixObservatory(
        clock=FakeClock(), path=path, warmup_runs=2, confirm_runs=2,
        rated_spec=rated,
    )
    for _ in range(4):
        observatory.observe_round([scripted(0.01)])
    # regress the cell for two confirming rounds
    for _ in range(2):
        summary = observatory.observe_round([scripted(0.1)])
    entry = summary["cells"]["serving/model2/f32/b2"]
    assert entry["verdict"] == "degraded"
    assert entry["roofline"]["bound"] in ("memory", "compute")
    assert summary["regressions"] and summary["regressions"][0]["ceiling"]
    # the verdict is DURABLE: the sidecar restores with the baseline
    doc = json.loads((tmp_path / "BENCH_BASELINES.json").read_text())
    assert any("serving/model2/f32/b2" in key for key in doc["baselines"])
    restored = matrix_mod.MatrixObservatory(
        clock=FakeClock(), path=path, warmup_runs=2, confirm_runs=2,
        rated_spec=rated,
    )
    assert restored.snapshot()["cells"]["serving/model2/f32/b2"]["verdict"] == (
        "degraded"
    )


def test_serving_matrix_impossible_cell_is_structured_device_skip():
    """The config ships a deliberately impossible serving cell
    ({"model": 16} on the 8-device platform) proving the structured
    device-deficit skip path — PR 13's {dcn:2,ici:8} pattern."""
    from activemonitor_tpu.analysis import matrix as matrix_mod

    spec, warning = matrix_mod.load_spec("config/bench_matrix.json")
    assert warning is None
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    runnable = {c.cell_id for c in cells if c.op == "serving"}
    assert "serving/model2/f32/b2" in runnable
    assert "serving/model2/f32/b4" in runnable
    deficits = {
        r.cell.cell_id: r.details["skip"]["code"]
        for r in skipped
        if r.cell.op == "serving"
        and r.details["skip"]["code"] == matrix_mod.SKIP_DEVICES
    }
    assert "serving/model16/f32/b2" in deficits
    assert "16" in next(
        r.reason for r in skipped
        if r.cell.cell_id == "serving/model16/f32/b2"
    )


def test_serving_matrix_runner_executes_on_the_real_engine():
    """One real serving cell through execute_cell: re-meshed over
    model2 via the kv partition rules, measured, conserved."""
    from activemonitor_tpu.analysis import matrix as matrix_mod

    [cell], _ = matrix_mod.expand(
        {
            "ops": ["serving"],
            "meshes": [{"model": 2}],
            "dtypes": ["f32"],
            "batch_ceilings": [2],
        },
        n_devices=8,
    )
    result = matrix_mod.execute_cell(cell, iters=1)
    assert result.status == matrix_mod.STATUS_OK, result.reason
    assert result.value > 0 and result.seconds > 0
    assert result.flops > 0 and result.bytes_accessed > 0
    assert result.details["serving"]["conserved"] is True
    assert result.details["serving"]["tp_axis_n"] == 2


@pytest.mark.slow
def test_long_open_loop_soak_stays_conserved_and_consistent():
    """The deep soak (slow tier): a longer Poisson stream with churny
    lengths — accounting must balance to the token and the paged path
    must track the static path the whole way."""
    import jax

    from activemonitor_tpu.models.probe_model import init_params, tiny_config
    from activemonitor_tpu.probes import serving as serving_probe

    cfg = tiny_config()
    requests = open_loop_requests(
        48, 3.0, seed=13, prompt_len_choices=(4, 6, 8, 10),
        output_choices=(2, 3, 5, 8),
    )
    costs = serving_probe.StepCosts(
        prefill=lambda plen: 0.005 * plen, decode=lambda _n: 0.5
    )
    soak = serving_probe.run_soak(
        cfg, requests, max_batch=6, costs=costs, collect=4, seed=0
    )
    cons = soak.scheduler.conservation()
    assert cons["ok"] is True and cons["completed"] == 48
    assert cons["tokens_emitted"] == sum(r.output_tokens for r in requests)
    params = init_params(jax.random.key(0), cfg)
    assert serving_probe._check_against_static(cfg, params, soak) <= 0.05
    assert soak.scheduler.occupancy_samples  # batching actually batched
    assert max(soak.frag_samples) < 1.0
