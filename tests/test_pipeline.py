"""Pipeline-parallelism tests on the 8-stage CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    apply_block,
    init_params,
)
from activemonitor_tpu.ops.pipeline import pipeline_forward_blocks, stack_layer_params
from activemonitor_tpu.parallel.mesh import make_1d_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = ProbeModelConfig(
        vocab_size=64,
        d_model=32,
        n_heads=2,
        n_layers=8,
        d_ff=64,
        max_seq_len=32,
        dtype=jnp.float32,  # exact comparison; bf16 differs by summation order
    )
    params = init_params(jax.random.key(0), cfg)
    mesh = make_1d_mesh("pp")
    x = jax.random.normal(jax.random.key(1), (8, 16, cfg.d_model), jnp.float32)
    ref = x
    for layer in params["layers"]:
        ref = apply_block(ref, layer, cfg)
    return cfg, params, mesh, x, ref


@pytest.mark.parametrize("microbatches", [2, 4, 8])
def test_pipeline_matches_dense(setup, microbatches):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    got = pipeline_forward_blocks(
        stacked, x, cfg, mesh, "pp", num_microbatches=microbatches
    )
    assert jnp.max(jnp.abs(got - ref)) < 1e-4


def test_pipeline_jits(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    fn = jax.jit(
        lambda layers, x: pipeline_forward_blocks(
            layers, x, cfg, mesh, "pp", num_microbatches=4
        )
    )
    out = fn(stacked, x)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_validates_divisibility(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward_blocks(stacked, x, cfg, mesh, "pp", num_microbatches=3)
    bad = ProbeModelConfig(n_layers=6)
    bad_params = init_params(jax.random.key(0), bad)
    bad_stacked = stack_layer_params(bad_params["layers"])
    with pytest.raises(ValueError, match="stages"):
        pipeline_forward_blocks(bad_stacked, x, bad, mesh, "pp")


def test_stack_layer_params_shapes(setup):
    cfg, params, mesh, x, ref = setup
    stacked = stack_layer_params(params["layers"])
    assert stacked["wqkv"].shape[0] == cfg.n_layers
    assert stacked["ln1"]["scale"].shape == (cfg.n_layers, cfg.d_model)
