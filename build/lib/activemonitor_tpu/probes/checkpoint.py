"""Checkpoint/resume probe — the training-state durability path.

The controller's own durable state is the CR status subresource
(SURVEY.md §5.4); the TRAINING workloads this framework probes durably
persist through orbax sharded checkpoints. A slice whose checkpoint
path is broken (full disk, stale GCS creds, a chip that can't gather
its shards) loses work at the next preemption — long before any
compute probe notices. This probe exercises the real path end to end:

1. build a sharded parameter pytree on a mesh over every device;
2. save it with orbax (device→host gather + serialize + fsync), timed;
3. restore it WITH its shardings (deserialize + host→device scatter),
   timed;
4. verify the round-trip bitwise and the restored sharding layout.

Bandwidth gauges are informational (they measure the checkpoint
filesystem as much as the chips — on a tunneled device, the tunnel:
genuinely the path a checkpoint would take); the verdict gates on
round-trip integrity.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def _make_state(mesh, size_mb: float) -> dict:
    """A sharded train-state-shaped pytree totalling ~size_mb."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    sharded = NamedSharding(mesh, P("d"))
    replicated = NamedSharding(mesh, P())
    total_floats = int(size_mb * 1e6 / 4)
    rows = max(n, (total_floats // 1024 // n) * n)
    key = jax.random.key(0)

    def on_device(k, shape, sharding):
        return jax.device_put(jax.random.normal(k, shape, jnp.float32), sharding)

    k1, k2 = jax.random.split(key)
    return {
        "params": {
            "w": on_device(k1, (rows, 1024), sharded),
            "b": on_device(k2, (1024,), replicated),
        },
        "step": jnp.int32(123),
    }


def run(
    size_mb: float = 64.0,
    directory: str = "",
) -> ProbeResult:
    try:
        import orbax.checkpoint as ocp
    except ImportError:  # pragma: no cover - baked into the image
        return ProbeResult(
            ok=True,
            summary="orbax not installed; checkpoint probe skipped",
            details={"skipped": "no orbax"},
        )

    if jax.process_count() > 1 and not directory:
        # orbax's multi-process protocol needs ONE path on storage every
        # process shares; per-process mkdtemp() paths would wedge the
        # barrier — require an explicit shared --directory instead of
        # hanging the probe on healthy hardware
        return ProbeResult(
            ok=True,
            summary=(
                f"multi-host run ({jax.process_count()} processes) needs a "
                "shared --directory; checkpoint probe skipped"
            ),
            details={"skipped": "no shared directory", "processes": jax.process_count()},
        )

    mesh = make_1d_mesh("d")
    state = _make_state(mesh, size_mb)
    nbytes = sum(
        leaf.nbytes for leaf in jax.tree.leaves(state) if hasattr(leaf, "nbytes")
    )
    workdir = directory or tempfile.mkdtemp(prefix="activemonitor-ckpt-")
    path = os.path.join(workdir, "state")
    checkpointer = ocp.StandardCheckpointer()
    try:
        t0 = time.perf_counter()
        # force: a periodic check reuses its --directory every run
        checkpointer.save(path, state, force=True)
        checkpointer.wait_until_finished()
        save_seconds = time.perf_counter() - t0

        from activemonitor_tpu.probes.training_step import restore_targets

        targets = restore_targets(state)
        t0 = time.perf_counter()
        restored = checkpointer.restore(path, targets)
        jax.block_until_ready(restored)
        restore_seconds = time.perf_counter() - t0

        bitwise = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored))
        )
        sharding_ok = (
            restored["params"]["w"].sharding == state["params"]["w"].sharding
        )
    finally:
        if not directory:
            shutil.rmtree(workdir, ignore_errors=True)

    save_gbps = nbytes / save_seconds / 1e9
    restore_gbps = nbytes / restore_seconds / 1e9
    ok = bitwise and sharding_ok
    metrics = [
        ProbeMetric(
            "checkpoint-save-gbps", save_gbps, help="Sharded checkpoint save GB/s"
        ),
        ProbeMetric(
            "checkpoint-restore-gbps",
            restore_gbps,
            help="Sharded checkpoint restore GB/s",
        ),
        ProbeMetric(
            "checkpoint-roundtrip-ok",
            1.0 if ok else 0.0,
            help="1 if save/restore round-trips bitwise with shardings intact",
        ),
    ]
    details = {
        "devices": mesh.devices.size,
        "payload_mb": nbytes / 1e6,
        "save_seconds": round(save_seconds, 4),
        "restore_seconds": round(restore_seconds, 4),
        "bitwise": bitwise,
        "sharding_preserved": sharding_ok,
        "directory": directory or "(temp)",
    }
    if not bitwise:
        verdict = "ROUND-TRIP CORRUPTION"
    elif not sharding_ok:
        verdict = "SHARDING LOST"
    else:
        verdict = "round-trip ok"
    summary = (
        f"checkpoint {nbytes/1e6:.0f} MB over {mesh.devices.size} devices: "
        f"save {save_gbps:.2f} GB/s, restore {restore_gbps:.2f} GB/s — {verdict}"
    )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
