"""Cluster-mode components against the stub API server.

This is the tier the reference gets from envtest (suite_test.go:67-134):
the Kubernetes data model is real (CRUD, conflicts, watch, RBAC
objects, Events), no external controllers run. Every class that was
previously gated on a live cluster executes here for real:
KubernetesHealthCheckClient, KubernetesRBACBackend,
KubernetesEventRecorder, and (in test_leader_k8s.py) the lease elector.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    ConflictError,
    KubernetesRBACBackend,
    MANAGED_BY_LABEL_KEY,
    MANAGED_BY_VALUE,
    NotFoundError,
    RBACProvisioner,
)
from activemonitor_tpu.controller.client_k8s import PLURAL, KubernetesHealthCheckClient
from activemonitor_tpu.controller.events import KubernetesEventRecorder

from tests.kube_harness import stub_env

RBAC_GROUP = "rbac.authorization.k8s.io"

WF_INLINE = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
spec:
  entrypoint: main
"""


def make_hc(name="hc-a", level="cluster", remedy=False):
    spec = {
        "repeatAfterSec": 60,
        "level": level,
        "workflow": {
            "generateName": "check-",
            "workflowtimeout": 10,
            "resource": {
                "namespace": "health",
                "serviceAccount": "check-sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if remedy:
        spec["remedyworkflow"] = {
            "generateName": "remedy-",
            "resource": {
                "namespace": "health",
                "serviceAccount": "remedy-sa",
                "source": {"inline": WF_INLINE},
            },
        }
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


# ---------------------------------------------------------------------------
# KubernetesHealthCheckClient
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_k8s_client_crud_roundtrip():
    async with stub_env() as (_, api):
        client = KubernetesHealthCheckClient(api)
        created = await client.apply(make_hc())
        assert created.metadata.resource_version
        assert created.metadata.uid

        got = await client.get("health", "hc-a")
        assert got is not None and got.spec.repeat_after_sec == 60
        assert await client.get("health", "ghost") is None

        listed = await client.list()
        assert [hc.metadata.name for hc in listed] == ["hc-a"]
        assert await client.list("other-ns") == []

        await client.delete("health", "hc-a")
        with pytest.raises(NotFoundError):
            await client.delete("health", "hc-a")


@pytest.mark.asyncio
async def test_k8s_client_apply_updates_spec_preserving_status():
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        created = await client.apply(make_hc())
        created.status.status = "Succeeded"
        created.status.success_count = 3
        await client.update_status(created)

        hc2 = make_hc()
        hc2.spec.repeat_after_sec = 30
        updated = await client.apply(hc2)  # create conflicts -> spec patch
        assert updated.spec.repeat_after_sec == 30
        assert updated.status.success_count == 3  # status survived the apply


@pytest.mark.asyncio
async def test_k8s_client_apply_removes_dropped_spec_fields():
    """Editing the manifest to drop remedyworkflow and re-applying must
    actually remove it — a merge-patch would silently keep the remedy
    running forever."""
    async with stub_env() as (_, api):
        client = KubernetesHealthCheckClient(api)
        await client.apply(make_hc(remedy=True))
        got = await client.get("health", "hc-a")
        assert not got.spec.remedy_workflow.is_empty()

        await client.apply(make_hc(remedy=False))
        got = await client.get("health", "hc-a")
        assert got.spec.remedy_workflow.is_empty()


@pytest.mark.asyncio
async def test_k8s_client_apply_merges_labels_additively():
    """Labels set by other tools survive an apply; labels in the
    manifest land."""
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        await client.apply(make_hc())
        # another tool labels the object
        from activemonitor_tpu import GROUP as G, VERSION as V

        obj = server.obj(G, V, PLURAL, "health", "hc-a")
        obj["metadata"].setdefault("labels", {})["helm.sh/chart"] = "x-1.0"

        hc = make_hc()
        hc.metadata.labels = {"team": "sre"}
        updated = await client.apply(hc)
        assert updated.metadata.labels["helm.sh/chart"] == "x-1.0"
        assert updated.metadata.labels["team"] == "sre"


@pytest.mark.asyncio
async def test_k8s_client_status_conflict_maps_to_conflict_error():
    async with stub_env() as (_, api):
        client = KubernetesHealthCheckClient(api)
        created = await client.apply(make_hc())
        stale = created.deepcopy()
        created.status.status = "Succeeded"
        await client.update_status(created)  # bumps resourceVersion

        stale.status.status = "Failed"
        with pytest.raises(ConflictError):
            await client.update_status(stale)

        ghost = make_hc("ghost")
        ghost.metadata.resource_version = ""
        with pytest.raises(NotFoundError):
            await client.update_status(ghost)


@pytest.mark.asyncio
async def test_k8s_client_watch_delivers_and_survives_reconnect():
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        seen = []
        done = asyncio.Event()

        async def consume():
            async for ev in client.watch():
                seen.append((ev.type, ev.name))
                if len(seen) >= 3:
                    done.set()
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.1)
        await client.apply(make_hc("hc-1"))
        await asyncio.sleep(0.05)
        await client.apply(make_hc("hc-2"))
        await client.delete("health", "hc-1")
        await asyncio.wait_for(done.wait(), 5)
        task.cancel()
        assert ("ADDED", "hc-1") in seen
        assert ("ADDED", "hc-2") in seen
        assert ("DELETED", "hc-1") in seen


@pytest.mark.asyncio
async def test_k8s_client_watch_410_synthesizes_missed_deletions():
    """A watch gap that outlives etcd compaction (410 Gone) swallows
    DELETED events; the client must list+diff and synthesize them, or
    deleted checks keep their schedules forever."""
    from activemonitor_tpu.kube import ApiError

    def ev(type_, name, rv):
        return {
            "type": type_,
            "object": {
                "metadata": {"namespace": "health", "name": name, "resourceVersion": rv}
            },
        }

    class ScriptedApi:
        def __init__(self):
            self.calls = 0

        async def watch(self, path, resource_version=""):
            self.calls += 1
            if self.calls == 1:
                yield ev("ADDED", "hc-keep", "1")
                yield ev("ADDED", "hc-gone", "2")
                raise ApiError(410, "too old resource version")
            # post-410 stream: server replays current state only
            yield ev("ADDED", "hc-keep", "9")

        async def get(self, path, params=None):
            # the re-list: hc-gone was deleted during the gap
            return {
                "items": [
                    {"metadata": {"namespace": "health", "name": "hc-keep"}}
                ]
            }

    client = KubernetesHealthCheckClient(ScriptedApi())
    seen = []
    async for event in client.watch():
        seen.append((event.type, event.name))
        if len(seen) >= 4:
            break
    assert ("DELETED", "hc-gone") in seen
    # the synthesized deletion lands between the streams, before the replay
    assert seen.index(("DELETED", "hc-gone")) < seen.index(("ADDED", "hc-keep"), 1)


# ---------------------------------------------------------------------------
# KubernetesRBACBackend
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_rbac_provisioner_creates_real_cluster_objects():
    async with stub_env() as (server, api):
        prov = RBACProvisioner(KubernetesRBACBackend(api))
        await prov.create_rbac_for_workflow(make_hc(), "healthCheck")

        sa = server.obj("", "v1", "serviceaccounts", "health", "check-sa")
        assert sa is not None
        assert sa["metadata"]["labels"][MANAGED_BY_LABEL_KEY] == MANAGED_BY_VALUE

        role = server.obj(RBAC_GROUP, "v1", "clusterroles", "", "check-sa-cluster-role")
        assert role is not None
        # read-only defaults (reference :85-101), except the scoped
        # Argo-executor reporting grant (divergence #9, docs/design.md)
        for rule in role["rules"]:
            if rule["resources"] == ["workflowtaskresults"]:
                assert set(rule["verbs"]) == {"create", "patch"}
            else:
                assert set(rule["verbs"]) == {"get", "list", "watch"}
            assert "*" not in rule["resources"]

        binding = server.obj(
            RBAC_GROUP, "v1", "clusterrolebindings", "", "check-sa-cluster-role-binding"
        )
        assert binding["roleRef"] == {
            "apiGroup": RBAC_GROUP,
            "kind": "ClusterRole",
            "name": "check-sa-cluster-role",
        }
        assert binding["subjects"] == [
            {"kind": "ServiceAccount", "name": "check-sa", "namespace": "health"}
        ]


@pytest.mark.asyncio
async def test_rbac_namespace_level_uses_roles():
    async with stub_env() as (server, api):
        prov = RBACProvisioner(KubernetesRBACBackend(api))
        await prov.create_rbac_for_workflow(make_hc(level="namespace"), "healthCheck")
        role = server.obj(RBAC_GROUP, "v1", "roles", "health", "check-sa-ns-role")
        assert role is not None
        binding = server.obj(
            RBAC_GROUP, "v1", "rolebindings", "health", "check-sa-ns-role-binding"
        )
        assert binding["roleRef"]["kind"] == "Role"
        assert server.objs(RBAC_GROUP, "v1", "clusterroles") == []


@pytest.mark.asyncio
async def test_rbac_create_is_idempotent_and_keeps_existing():
    async with stub_env() as (server, api):
        prov = RBACProvisioner(KubernetesRBACBackend(api))
        await prov.create_rbac_for_workflow(make_hc(), "healthCheck")
        sa_uid = server.obj("", "v1", "serviceaccounts", "health", "check-sa")[
            "metadata"
        ]["uid"]
        await prov.create_rbac_for_workflow(make_hc(), "healthCheck")
        assert (
            server.obj("", "v1", "serviceaccounts", "health", "check-sa")["metadata"][
                "uid"
            ]
            == sa_uid
        )


@pytest.mark.asyncio
async def test_remedy_rbac_lifecycle_and_managed_by_guard():
    async with stub_env() as (server, api):
        backend = KubernetesRBACBackend(api)
        prov = RBACProvisioner(backend)
        hc = make_hc(remedy=True)
        await prov.create_rbac_for_workflow(hc, "remedy")

        role = server.obj(RBAC_GROUP, "v1", "clusterroles", "", "remedy-sa-cluster-role")
        # write-capable defaults for remedies (reference :104-120)
        assert any("delete" in rule["verbs"] for rule in role["rules"])

        # a user-owned SA with the same name as remedy cleanup target is
        # not ours: plant one without the managed-by label
        server.seed(
            "",
            "v1",
            "serviceaccounts",
            {"metadata": {"name": "user-sa", "namespace": "health", "labels": {}}},
        )
        await prov.delete_rbac_for_workflow(hc)
        assert server.obj("", "v1", "serviceaccounts", "health", "remedy-sa") is None
        assert server.obj(RBAC_GROUP, "v1", "clusterroles", "", "remedy-sa-cluster-role") is None
        # unmanaged object untouched
        assert server.obj("", "v1", "serviceaccounts", "health", "user-sa") is not None

        # double delete is fine (404 tolerated)
        await prov.delete_rbac_for_workflow(hc)


@pytest.mark.asyncio
async def test_rbac_custom_rules_override_defaults():
    async with stub_env() as (server, api):
        hc = make_hc()
        hc.spec.workflow.rbac_rules = [
            __import__(
                "activemonitor_tpu.api.types", fromlist=["PolicyRule"]
            ).PolicyRule(api_groups=[""], resources=["secrets"], verbs=["get"])
        ]
        prov = RBACProvisioner(KubernetesRBACBackend(api))
        await prov.create_rbac_for_workflow(hc, "healthCheck")
        role = server.obj(RBAC_GROUP, "v1", "clusterroles", "", "check-sa-cluster-role")
        assert role["rules"] == [
            {"apiGroups": [""], "resources": ["secrets"], "verbs": ["get"]}
        ]


# ---------------------------------------------------------------------------
# KubernetesEventRecorder
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_event_recorder_posts_core_events():
    async with stub_env() as (server, api):
        recorder = KubernetesEventRecorder(api)
        hc = make_hc()
        hc.metadata.uid = "uid-123"
        recorder.event(hc, "Normal", "Testing", "workflow submitted")
        recorder.event(hc, "Warning", "Failed", "workflow failed")
        await recorder.flush()
        recorder.close()

        events = server.objs("", "v1", "events")
        assert len(events) == 2
        by_reason = {e["reason"]: e for e in events}
        assert by_reason["Testing"]["involvedObject"]["name"] == "hc-a"
        assert by_reason["Testing"]["involvedObject"]["uid"] == "uid-123"
        assert by_reason["Failed"]["type"] == "Warning"
        # the in-memory ring still works (CLI/describe path)
        assert len(recorder.events_for("health", "hc-a")) == 2


@pytest.mark.asyncio
async def test_event_recorder_survives_post_failures():
    async with stub_env(token="sekret") as (server, _):
        from activemonitor_tpu.kube import KubeApi, KubeConfig

        unauthed = KubeApi(KubeConfig(server=server.url))  # all posts 401
        try:
            recorder = KubernetesEventRecorder(unauthed)
            recorder.event(make_hc(), "Normal", "Testing", "msg")
            await recorder.flush()  # must not raise
            recorder.close()
            assert server.objs("", "v1", "events") == []
            assert len(recorder.all) == 1  # local ring unaffected
        finally:
            await unauthed.close()
